"""Benchmark: ASHA trials/hour through the full framework stack on one chip.

The BASELINE metric (BASELINE.md / BASELINE.json): the reference publishes no
numbers, so the comparison point is a SEQUENTIAL baseline — the same ASHA
schedule executed trial-by-trial with no async scheduling — mirroring what
the reference's Spark-stage-based alternative would do (its whole pitch is
overlapping trials on long-lived executors, `README.rst:21-26`).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

import numpy as np


def make_data(n=2048, key=0):
    rng = np.random.default_rng(key)
    X = rng.normal(size=(n, 16, 16, 1)).astype(np.float32)
    y = (X.mean(axis=(1, 2, 3)) > 0).astype(np.int32)
    return X, y


DATA_X, DATA_Y = make_data()
STEPS_PER_BUDGET = 25
BATCH = 256


def train_mnist(lr, budget=1, reporter=None):
    """One ASHA trial: budget-scaled training of the MNIST CNN. Shapes are
    hparam-independent so XLA's compile cache amortizes across trials."""
    import jax
    import jax.numpy as jnp
    import optax

    from maggy_tpu.models import MnistCNN
    from maggy_tpu.train import ShardedBatchIterator, Trainer, cross_entropy_loss
    from maggy_tpu.parallel import make_mesh

    mesh = make_mesh({"data": 1}, devices=jax.devices()[:1])
    model = MnistCNN(kernel_size=3, pool_size=2, features=16, num_classes=2)
    trainer = Trainer(
        model, optax.adam(lr),
        lambda logits, batch: cross_entropy_loss(logits, batch["labels"]),
        mesh, strategy="dp",
    )
    trainer.init(jax.random.key(0), (jnp.zeros((1, 16, 16, 1)),))
    steps = int(STEPS_PER_BUDGET * budget)
    it = iter(ShardedBatchIterator({"x": DATA_X, "y": DATA_Y}, batch_size=BATCH,
                                   epochs=None, seed=1))
    loss = None
    for i in range(steps):
        b = next(it)
        loss = trainer.step(trainer.place_batch(
            {"inputs": (jnp.asarray(b["x"]),), "labels": jnp.asarray(b["y"])}))
        if reporter is not None and i % 5 == 0:
            reporter.broadcast(-float(loss), step=i)
    return {"metric": -float(loss)}


def run_framework_sweep(num_trials=9, workers=3):
    from maggy_tpu import OptimizationConfig, Searchspace, experiment
    from maggy_tpu.optimizers import Asha

    sp = Searchspace(lr=("DOUBLE", [1e-4, 3e-2]))
    config = OptimizationConfig(
        name="bench_asha", num_trials=num_trials,
        optimizer=Asha(reduction_factor=3, resource_min=1, resource_max=9, seed=0),
        searchspace=sp, direction="max", num_workers=workers,
        hb_interval=0.2, es_policy="none", seed=0,
    )
    t0 = time.time()
    result = experiment.lagom(train_mnist, config)
    wall = time.time() - t0
    return result, wall


def run_sequential_baseline(schedule):
    """The same (lr, budget) runs, executed back-to-back with no framework."""
    t0 = time.time()
    for lr, budget in schedule:
        train_mnist(lr, budget=budget)
    return time.time() - t0


def main():
    os.environ.setdefault("MAGGY_TPU_BASE_DIR", tempfile.mkdtemp(prefix="bench_"))

    # Warm-up: compile the two step shapes once so both measurements see a
    # warm cache (the persistent compilation cache does this across runs).
    train_mnist(1e-3, budget=1)

    result, wall = run_framework_sweep()
    n_runs = result["num_trials"]
    trials_per_hour = n_runs / wall * 3600

    # Sequential baseline over an equivalent schedule (same total budget).
    from maggy_tpu.core.environment import EnvSing
    import glob, json as _json

    exp_dirs = sorted(glob.glob(os.path.join(
        os.environ["MAGGY_TPU_BASE_DIR"], "*")))
    schedule = []
    for td in glob.glob(os.path.join(exp_dirs[-1], "*", "trial.json")):
        with open(td) as f:
            t = _json.load(f)
        schedule.append((t["params"]["lr"], t["params"].get("budget", 1)))
    seq_wall = run_sequential_baseline(schedule)
    seq_trials_per_hour = len(schedule) / seq_wall * 3600

    print(json.dumps({
        "metric": "ASHA trials/hour (MNIST CNN sweep, 1 chip, 3 concurrent runners)",
        "value": round(trials_per_hour, 1),
        "unit": "trials/hour",
        "vs_baseline": round(trials_per_hour / seq_trials_per_hour, 3),
    }))


if __name__ == "__main__":
    sys.exit(main())
