"""Benchmark: ASHA trials/hour through the full framework stack on one chip.

The BASELINE metric (BASELINE.md / BASELINE.json): the reference publishes
no numbers, so the comparison point is STAGE-BASED execution — what the
reference's own pitch positions async scheduling against
(`README.rst:21-26`). Two baselines run over the sweep's executed schedule:

- PRIMARY (``vs_baseline``): synchronous successive halving — each rung's
  runs packed over the workers, a BARRIER between rungs, early-stopped
  trials at full budget. This is the best a stage scheduler can actually
  do: rung N+1's trial set is computed from rung N's results, so no stage
  system can overlap rungs, and it has no mid-trial control (ASHA paper,
  arXiv:1810.05934, makes the same comparison).
- SECONDARY (``detail.oracle_replay``): the async run's OWN executed
  schedule replayed packed with no barriers at all — an oracle no real
  scheduler could produce (it needs the outcomes before running them). The
  framework-to-oracle ratio isolates pure scheduling+control overhead.

Output contract: up to TWO JSON lines on stdout — the headline
{"metric", "value", "unit", "vs_baseline"} printed before any extra bench
touches the device, then (when extras ran) an enriched line with the SAME
headline values plus extras merged into "detail". A consumer taking either
the first or the last JSON line reads the same headline numbers.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

import numpy as np


def make_data(n=2048, key=0):
    rng = np.random.default_rng(key)
    X = rng.normal(size=(n, 16, 16, 1)).astype(np.float32)
    y = (X.mean(axis=(1, 2, 3)) > 0).astype(np.int32)
    return X, y


DATA_X, DATA_Y = make_data()
# Mirror of runner_pool._ACCEL_BOOTSTRAP_VARS (NOT imported: the
# orchestrator process deliberately never imports maggy_tpu/jax). Vars that
# make a TPU-plugin sitecustomize dial the accelerator tunnel at child
# interpreter startup; CPU-bound invocations must strip them.
_ACCEL_BOOTSTRAP_VARS = ("PALLAS_AXON_POOL_IPS",)
STEPS_PER_BUDGET = int(os.environ.get("BENCH_STEPS", "40"))
# Swept batch sizes: trial DURATION varies ~4x across the space — the
# normal shape of a real sweep (batch/width/depth hparams change cost), and
# precisely what stage-based execution pays for: every synchronized wave
# waits for its slowest member, while the async scheduler backfills.
BATCH_CHOICES = [128, 256, 512]


def _bench_loss(logits, batch):
    from maggy_tpu.train import cross_entropy_loss

    return cross_entropy_loss(logits, batch["labels"])


def train_mnist(lr, batch=256, budget=1, reporter=None):
    """One ASHA trial: budget-scaled training of the MNIST CNN. Shapes
    depend only on the DISCRETE batch hparam, so the whole sweep compiles
    exactly len(BATCH_CHOICES) train steps — shared through the warm
    cache's AUTOMATIC program key (model config + mesh + swept-optimizer
    family; no hand-written step_key), the compile-once default."""
    import jax
    import jax.numpy as jnp
    import optax

    from maggy_tpu.models import MnistCNN
    from maggy_tpu.train import (ShardedBatchIterator, Trainer,
                                 cross_entropy_loss, swept_transform)
    from maggy_tpu.parallel import make_mesh

    mesh = make_mesh({"data": 1}, devices=jax.devices()[:1])
    model = MnistCNN(kernel_size=3, pool_size=2, features=16, num_classes=2)
    # lr rides in opt_state (swept_transform), so every trial of the sweep
    # is the SAME program: repeat-shape trials reuse the warm slot's
    # compiled step and donated state buffers.
    trainer = Trainer(
        model, swept_transform(optax.adam, learning_rate=lr),
        _bench_loss, mesh, strategy="dp",
    )
    trainer.init(jax.random.key(0), (jnp.zeros((1, 16, 16, 1)),))
    steps = max(1, int(STEPS_PER_BUDGET * budget))
    it = iter(ShardedBatchIterator({"x": DATA_X, "y": DATA_Y},
                                   batch_size=int(batch), epochs=None, seed=1))
    loss = None
    for i in range(steps):
        b = next(it)
        loss = trainer.step(trainer.place_batch(
            {"inputs": (jnp.asarray(b["x"]),), "labels": jnp.asarray(b["y"])}))
        if reporter is not None and i % 2 == 0:
            # Maps step onto the shared [0, max-budget] resource axis so the
            # median rule compares trials at equal progress. The metric is
            # passed as a LAZY device scalar — the reporter materializes it
            # on the heartbeat thread, so the step stream stays pipelined
            # (a blocking float() here costs ~50 ms/sync over the tunnel).
            reporter.broadcast(-loss, step=i)
    return {"metric": -float(loss)}


# --vmap micro-trial knobs: the trial body must DOMINATE the per-trial
# control-plane cost (dir mint, journal edges, FINAL round-trip) or the
# block's K-for-one dispatch saving drowns in fixed overhead and the
# speedup gate measures the scheduler, not the engine.
VMAP_STEPS = int(os.environ.get("BENCH_VMAP_STEPS", "2500"))
VMAP_BATCH = int(os.environ.get("BENCH_VMAP_BATCH", "256"))


def train_mnist_vmap(lr, lanes=None, reporter=None):
    """Micro-trial for the --vmap gate: a tiny MnistMLP (matmul +
    elementwise only — the model family the bitwise lane-parity property
    is pinned on) trained full-batch for VMAP_STEPS. Lanes-capable: under
    ``config.vmap_lanes`` > 1 the executor hands a `LaneSet` and the K
    configs train as ONE vmapped program; with ``lanes=None`` (scalar
    dispatch, and the warm-up trial every runner's first dispatch always
    is) it degrades to the plain Trainer path."""
    import jax
    import jax.numpy as jnp
    import optax

    from maggy_tpu.models import MnistMLP
    from maggy_tpu.parallel import make_mesh
    from maggy_tpu.train import Trainer, VmapTrainer, swept_transform

    mesh = make_mesh({"data": 1}, devices=jax.devices()[:1])
    model = MnistMLP(features=8, num_classes=2)
    batch = {"inputs": (jnp.asarray(DATA_X[:VMAP_BATCH]),),
             "labels": jnp.asarray(DATA_Y[:VMAP_BATCH])}
    rng = jax.random.key(0)
    if lanes is None:
        trainer = Trainer(
            model, swept_transform(optax.adam, learning_rate=lr),
            _bench_loss, mesh, strategy="dp")
        trainer.init(rng, (batch["inputs"][0][:1],))
        loss = None
        for i in range(VMAP_STEPS):
            loss = trainer.step(trainer.place_batch(batch))
            if reporter is not None and i % 100 == 0:
                reporter.broadcast(-loss, step=i)
        return {"metric": -float(loss)}
    # Vectorized block: one AOT executable trains every lane in lockstep.
    # The raw (unplaced) batch is broadcast across lanes by the trainer
    # (in_axes=None on the batch leaf).
    vt = VmapTrainer(
        model, optax.adam,
        [{"learning_rate": h["lr"]} for h in lanes.hparams],
        _bench_loss, mesh, strategy="dp")
    vt.init(rng, (batch["inputs"][0][:1],))
    losses = None
    for i in range(VMAP_STEPS):
        losses = vt.step(batch)
        if i % 100 == 0:
            reporter.broadcast_lanes(-jnp.asarray(losses), step=i)
            for li in lanes.take_stopped():
                lanes.retire(li, -float(np.asarray(losses)[li]))
    final = np.asarray(losses)
    return {tid: -float(final[i])
            for i, tid in enumerate(lanes.trial_ids)}


def run_framework_sweep(num_trials=None, workers=3):
    if num_trials is None:
        num_trials = int(os.environ.get("BENCH_NUM_TRIALS", "18"))
    from maggy_tpu import OptimizationConfig, Searchspace, experiment
    from maggy_tpu.optimizers import Asha

    sp = Searchspace(lr=("DOUBLE_LOG", [1e-4, 3e-2]),
                     batch=("DISCRETE", BATCH_CHOICES))
    # ASHA multi-fidelity schedule + median-rule mid-trial early stopping:
    # the two async control loops the reference pitches against stage-based
    # execution (`README.rst:21-26`). The wave baseline below runs the SAME
    # trials without them — a stage scheduler cannot stop a running trial.
    config = OptimizationConfig(
        name="bench_asha", num_trials=num_trials,
        optimizer=Asha(reduction_factor=3, resource_min=1, resource_max=9, seed=0),
        searchspace=sp, direction="max", num_workers=workers,
        hb_interval=0.1, es_policy="median", es_interval=1, es_min=3, seed=0,
    )
    t0 = time.time()
    result = experiment.lagom(train_mnist, config)
    wall = time.time() - t0
    return result, wall


def run_packed_baseline(schedule, workers=3):
    """Runs executed by ``workers`` bare threads pulling from a shared
    queue — packed/backfilled, no synchronization beyond the final join.
    This models tasks inside ONE stage (a Spark stage backfills tasks onto
    free executors); device parallelism is identical to the framework run,
    with none of its control plane."""
    import queue as _queue
    import threading

    q = _queue.SimpleQueue()
    for args in schedule:
        q.put(args)
    errors = []

    def worker():
        while True:
            try:
                lr, batch, budget = q.get_nowait()
            except _queue.Empty:
                return
            try:
                train_mnist(lr, batch=batch, budget=budget)
            except BaseException as e:  # noqa: BLE001
                errors.append(e)
                return

    t0 = time.time()
    threads = [threading.Thread(target=worker) for _ in range(workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        # A failed baseline trial would silently shrink the measurement.
        raise errors[0]
    return time.time() - t0


def run_sync_sha_baseline(rung_schedule, workers=3):
    """Synchronous successive halving: each rung's runs packed over the
    workers, with a BARRIER between rungs (a stage scheduler must finish
    rung k to compute rung k+1's promotions), and no mid-trial control
    (early-stopped trials at full budget). The PRIMARY stage-based
    comparator."""
    t0 = time.time()
    for rung in sorted(rung_schedule):
        run_packed_baseline(rung_schedule[rung], workers=workers)
    return time.time() - t0


def log(msg):
    print("[bench] {}".format(msg), file=sys.stderr, flush=True)


def _current_platform():
    """The substrate THIS process measures on — stamped into every
    detail block (the ROADMAP flaky-TPU note: numbers are only
    comparable within one platform) and checked by the A/B parity
    comparator, which refuses to compare mixed-platform arms."""
    try:
        import jax

        return str(jax.default_backend())
    except Exception:  # noqa: BLE001 - a stamp, never a failure
        return os.environ.get("JAX_PLATFORMS") or "unknown"


def run_compile_ab(trials=None, workers=1):
    """Repeat-shape warm_start A/B (ROADMAP item 3's gate): the SAME
    fixed-shape random-search sweep run twice on the SAME platform — warm
    path on (the default) vs off (legacy build-per-trial). Returns per-arm
    wall/ttfm numbers plus the gate: within the WARM run (cold first trial
    vs warm repeats — same run, same platform, per the ROADMAP's flaky-TPU
    comparability note), repeat-shape warm ttfm p50 must land >=5x below
    the cold ttfm p50.
    """
    import functools
    import glob as _glob

    from maggy_tpu import OptimizationConfig, Searchspace, experiment
    from maggy_tpu.telemetry import JOURNAL_NAME, replay_journal
    from maggy_tpu.train import clear_warm

    if trials is None:
        trials = int(os.environ.get("BENCH_AB_TRIALS", "6"))
    # Fixed batch/budget: every trial is the same program+shape, so trial
    # 1 is the arm's only cold compile and 2..N are pure repeat-shape.
    train_fn = functools.partial(train_mnist, batch=256, budget=0.5)
    out = {}
    for arm, warm_on in (("warm", True), ("cold", False)):
        clear_warm()  # each arm starts from an empty warm cache
        arm_dir = os.path.join(os.environ["MAGGY_TPU_BASE_DIR"],
                               "compile_ab_{}".format(arm))
        config = OptimizationConfig(
            name="bench_ab_{}".format(arm), num_trials=trials,
            optimizer="randomsearch",
            searchspace=Searchspace(lr=("DOUBLE_LOG", [1e-4, 3e-2])),
            direction="max", num_workers=workers, hb_interval=0.1,
            es_policy="none", seed=11, warm_start=warm_on,
            experiment_dir=arm_dir,
        )
        t0 = time.time()
        experiment.lagom(train_fn, config)
        wall = time.time() - t0
        exp_dirs = sorted(d for d in _glob.glob(os.path.join(arm_dir, "*"))
                          if os.path.isdir(d))
        derived = replay_journal(os.path.join(exp_dirs[-1], JOURNAL_NAME))
        comp = derived.get("compile") or {}
        out[arm] = {
            "wall_s": round(wall, 2),
            "trials": trials,
            "warm_hits": comp.get("warm_hits", 0),
            "warm_misses": comp.get("warm_misses", 0),
            "ttfm_warm": comp.get("ttfm_warm") or {},
            "ttfm_cold": comp.get("ttfm_cold") or {},
            # The arm's chip-time ledger + platform: --goodput gates
            # warm-vs-cold COMPILE badput on these, and the stamp feeds
            # the same-platform refusal.
            "goodput": derived.get("goodput") or {},
            "platform": _current_platform(),
        }
    warm_p50 = (out["warm"]["ttfm_warm"] or {}).get("median_ms")
    cold_p50 = (out["warm"]["ttfm_cold"] or {}).get("median_ms")
    gate = {"warm_ttfm_p50_ms": warm_p50, "cold_ttfm_p50_ms": cold_p50}
    if warm_p50 and cold_p50:
        gate["ratio"] = round(cold_p50 / warm_p50, 2)
        gate["gate_ok"] = cold_p50 >= 5.0 * warm_p50
    if out["warm"]["wall_s"] and out["cold"]["wall_s"]:
        gate["trials_per_hour_ratio"] = round(
            out["cold"]["wall_s"] / out["warm"]["wall_s"], 3)
    out["gate"] = gate
    return out


def handoff_gaps(trials):
    """FALLBACK hand-off estimator from trial.json dicts (start+duration
    -> same runner's next start), for experiment dirs that predate the
    telemetry journal. The artifact of record is now the journal:
    `scheduling_telemetry` replays <exp_dir>/telemetry.jsonl through
    `maggy_tpu.telemetry.replay_journal`, whose driver-observed span
    timestamps ("finalized" -> same partition's next "running") measure
    the control plane directly instead of reconstructing it. Gaps
    spanning rung-barrier idle waits are excluded by capping at 2 s
    (idling on purpose is scheduling, not overhead) — both paths share
    that rule, so the numbers stay comparable across rounds."""
    by_partition = {}
    for t in trials:
        pid = (t.get("info_dict") or {}).get("partition")
        if pid is None or t.get("start") is None or t.get("duration") is None:
            continue
        by_partition.setdefault(pid, []).append(
            (t["start"], t["start"] + t["duration"]))
    gaps = []
    for runs in by_partition.values():
        runs.sort()
        for (s0, e0), (s1, _) in zip(runs, runs[1:]):
            gap = s1 - e0
            if 0 <= gap < 2.0:
                gaps.append(gap * 1e3)
    if not gaps:
        return {}
    gaps.sort()
    return {"median_ms": round(gaps[len(gaps) // 2], 1),
            "p95_ms": round(gaps[int(len(gaps) * 0.95)], 1),
            "n": len(gaps)}


def scheduling_telemetry(exp_dir, trial_dicts):
    """Hand-off gap + early-stop reaction latency for the detail block,
    derived from the experiment's telemetry journal. The journal is the
    reproducibility contract: `maggy_tpu.telemetry.replay_journal` over
    the SAME file yields the SAME numbers offline, so a BENCH_*.json
    detail block can be re-derived from the artifact alone. Falls back to
    the trial.json reconstruction for pre-telemetry experiment dirs."""
    from maggy_tpu.telemetry import JOURNAL_NAME, replay_journal

    journal = os.path.join(exp_dir, JOURNAL_NAME)
    if os.path.exists(journal):
        derived = replay_journal(journal)
        return {
            "handoff": derived.get("handoff") or {},
            "early_stop_reaction": derived.get("early_stop_reaction") or {},
            # Pipelined hand-off health: prefetch hit/miss counts + hit
            # rate and controller suggest() latency (empty when the sweep
            # ran with config.prefetch=False or a pre-pipeline journal).
            "suggest": derived.get("suggest") or {},
            # Compile-once hot path: warm-slot hit rate, ttfm split
            # cold/warm, phase breakdown, persistent-cache counters
            # (empty for warm_start=False or pre-warm journals).
            "compile": derived.get("compile") or {},
            # Chip-time goodput ledger: where every held chip-second of
            # the sweep went (train vs init/compile/ckpt/rework/handoff/
            # queue_wait/idle badput, unaccounted residual).
            "goodput": derived.get("goodput") or {},
            "source": "telemetry_journal",
            "journal": journal,
        }
    return {"handoff": handoff_gaps(trial_dicts),
            "early_stop_reaction": {},
            "suggest": {},
            "compile": {},
            "goodput": {},
            "source": "trial_json_fallback"}


def analysis_detail(witness=None):
    """``detail.analysis``: the static-analysis posture of the package
    this bench ran against — finding/suppression counts per checker, the
    lock inventory, and (when a soak ran under the lock-order witness)
    the dynamically observed edge count. Recorded in every BENCH_*.json
    so concurrency-discipline drift shows up in the trajectory next to
    the perf numbers (a new suppression or a findings spike is visible
    without re-running the analyzer against an old checkout)."""
    try:
        from maggy_tpu.analysis import run_analysis

        report = run_analysis()
        out = {
            "findings": len(report["findings"]),
            "per_checker": report["summary"],
            "suppressed": len(report["suppressed"]),
            "locks": report["num_locks"],
            "order_edges": len(report.get("lock_edges", [])),
        }
    except Exception as e:  # noqa: BLE001 - posture is best-effort here;
        # the tier-1 conformance test is the enforcement point
        out = {"error": repr(e)}
    if witness:
        out["witness_edges"] = witness.get("edge_count")
        out["witness_violations"] = len(witness.get("violations") or [])
    return out


# ------------------------------------------------------------- MFU + kernels

# Peak bf16 matmul throughput per chip, by device_kind prefix.
CHIP_PEAK_FLOPS = [
    ("TPU v5 lite", 197e12),  # v5e
    ("TPU v5e", 197e12),
    ("TPU v5p", 459e12),
    ("TPU v6", 918e12),
    ("TPU v4", 275e12),
    ("TPU v3", 123e12),
]


def chip_peak_flops():
    import jax

    kind = jax.devices()[0].device_kind
    for prefix, peak in CHIP_PEAK_FLOPS:
        if kind.startswith(prefix):
            return kind, peak
    return kind, 197e12  # conservative default; kind is recorded alongside


def _time_fn(fn, *args, iters=10, warmup=2):
    """Median wall time of ``fn(*args)`` with device sync per call."""
    import jax

    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2]


def bench_llama_mfu(num_layers=None, remat=False):
    """Jitted train step of a one-chip Llama config (bf16, flash attention)
    -> step time + model FLOPs utilization. FLOPs counted as the standard
    6 * params * tokens plus the attention term 12 * L * H * D * S^2
    (fwd+bwd, causal halves the scores but the bwd recompute restores it).

    With ``remat=True`` the TRUE FLOPs are ~8*params*tokens (forward
    recomputed in the backward); MFU is still reported on the 6N
    convention and the artifact carries ``remat`` so the number reads
    honestly."""
    import jax
    import jax.numpy as jnp
    import optax

    from maggy_tpu.models import Llama, LlamaConfig
    from maggy_tpu.parallel import make_mesh
    from maggy_tpu.train import Trainer, next_token_loss

    B = int(os.environ.get("BENCH_LLAMA_BATCH", "4"))
    S = int(os.environ.get("BENCH_LLAMA_SEQ", "2048"))
    # Sized to compile in ~1-2 min on a tunneled chip: the r3 run showed an
    # 8-layer config blowing a 240 s budget on FIRST compile (cached runs
    # are fast, but the artifact must survive a cold cache).
    cfg = LlamaConfig(
        vocab_size=32000,
        hidden_dim=int(os.environ.get("BENCH_LLAMA_HIDDEN", "2048")),
        intermediate_dim=int(os.environ.get("BENCH_LLAMA_INTER", "5632")),
        num_layers=int(num_layers if num_layers is not None
                       else os.environ.get("BENCH_LLAMA_LAYERS", "4")),
        num_heads=16, num_kv_heads=8, head_dim=128, max_seq_len=S,
        dtype=jnp.bfloat16,
        # Default no rematerialization: activations at this size fit HBM,
        # and remat recomputes the forward (real FLOPs ~8NP vs the 6NP
        # counted), understating MFU. The llama8 extra opts in to afford
        # the deeper config.
        remat=remat,
    )
    mesh = make_mesh({"data": 1}, devices=jax.devices()[:1])
    model = Llama(cfg)
    trainer = Trainer(
        model, optax.adamw(3e-4),
        lambda logits, batch: next_token_loss(logits, batch["tokens"]),
        mesh, strategy="dp")
    tokens = jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, size=(B, S)), jnp.int32)
    trainer.init(jax.random.key(0), (tokens,))
    n_params = sum(int(np.prod(x.shape)) for x in
                   jax.tree_util.tree_leaves(trainer.variables))
    batch = trainer.place_batch({"inputs": (tokens,), "tokens": tokens})

    def step(b):
        return trainer.step(b)

    sec = _time_fn(step, batch, iters=8)
    tokens_per_step = B * S
    attn_flops = 12 * cfg.num_layers * cfg.num_heads * cfg.head_dim * S * S * B
    flops = 6.0 * n_params * tokens_per_step + attn_flops
    kind, peak = chip_peak_flops()
    return {
        "model": "llama {}L/{}h (bf16, flash{})".format(
            cfg.num_layers, cfg.hidden_dim, ", remat" if remat else ""),
        "params_m": round(n_params / 1e6, 1),
        "step_time_ms": round(sec * 1e3, 2),
        "tokens_per_s": round(tokens_per_step / sec),
        "mfu": round(flops / sec / peak, 4),
        "remat": bool(remat),
        "chip": kind,
    }


def bench_bert_mfu():
    """BERT-base fwd+bwd step time (head_dim 64 + padding mask: the shapes
    that now dispatch to the Pallas kernel)."""
    import jax
    import jax.numpy as jnp
    import optax

    from maggy_tpu.models import BertConfig, BertEncoder
    from maggy_tpu.parallel import make_mesh
    from maggy_tpu.train import Trainer, cross_entropy_loss

    B = int(os.environ.get("BENCH_BERT_BATCH", "32"))
    S = int(os.environ.get("BENCH_BERT_SEQ", "128"))
    cfg = BertConfig.base(num_classes=2)
    mesh = make_mesh({"data": 1}, devices=jax.devices()[:1])
    model = BertEncoder(cfg)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(B, S)), jnp.int32)
    attn_mask = jnp.asarray(
        np.arange(S)[None, :] < rng.integers(S // 2, S + 1, size=(B, 1)))
    labels = jnp.asarray(rng.integers(0, 2, size=(B,)), jnp.int32)
    trainer = Trainer(
        model, optax.adamw(3e-5),
        lambda logits, batch: cross_entropy_loss(logits, batch["labels"]),
        mesh, strategy="dp")
    trainer.init(jax.random.key(0), (tokens,),
                 init_kwargs={"attention_mask": attn_mask})
    n_params = sum(int(np.prod(x.shape)) for x in
                   jax.tree_util.tree_leaves(trainer.variables))
    batch = trainer.place_batch(
        {"inputs": (tokens, attn_mask), "labels": labels})
    sec = _time_fn(lambda b: trainer.step(b), batch, iters=8)
    kind, peak = chip_peak_flops()
    flops = 6.0 * n_params * B * S
    return {
        "model": "bert-base S={} (padding-mask flash)".format(S),
        "params_m": round(n_params / 1e6, 1),
        "step_time_ms": round(sec * 1e3, 2),
        "examples_per_s": round(B / sec, 1),
        "mfu": round(flops / sec / peak, 4),
        "chip": kind,
    }


def bench_flash_vs_xla():
    """flash_attention vs attention_reference, fwd+bwd, at S = 2k/4k/8k.
    The dispatch default is Pallas on TPU; this records the measured edge."""
    import jax
    import jax.numpy as jnp

    from maggy_tpu.ops.attention import attention_reference, flash_attention

    out = {}
    for S, B in ((2048, 4), (4096, 2), (8192, 1)):
        H, D = 8, 128
        rng = np.random.default_rng(S)
        q, k, v = (jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.bfloat16)
                   for _ in range(3))

        def loss_flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v, None, True) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

        g_flash = jax.jit(jax.grad(loss_flash, (0, 1, 2)))
        g_ref = jax.jit(jax.grad(loss_ref, (0, 1, 2)))
        t_flash = _time_fn(g_flash, q, k, v, iters=6)
        t_ref = _time_fn(g_ref, q, k, v, iters=6)
        out["S{}".format(S)] = {
            "flash_ms": round(t_flash * 1e3, 2),
            "xla_ms": round(t_ref * 1e3, 2),
            "speedup": round(t_ref / t_flash, 2),
        }
    return out


EXTRA_BENCHES = {
    "llama": bench_llama_mfu,
    # Deeper/remat variant, NOT in the default set (first compile can blow
    # the budget on a cold cache): run via BENCH_EXTRAS=llama8 once the
    # persistent compile cache is warm.
    "llama8": lambda: bench_llama_mfu(num_layers=8, remat=True),
    "bert": bench_bert_mfu,
    "flash_vs_xla": bench_flash_vs_xla,
}


HEADLINE_METRIC = "ASHA trials/hour (MNIST CNN sweep, 1 chip, 3 concurrent runners)"
HEADLINE_UNIT = "trials/hour"


def _failure_artifact(error):
    return {
        "metric": HEADLINE_METRIC,
        "value": 0.0, "unit": HEADLINE_UNIT, "vs_baseline": 0.0,
        "detail": {"error": error},
    }


def _force_cpu_if_requested():
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # Env vars alone lose to an already-imported TPU plugin
        # (sitecustomize); force the live config like __graft_entry__ does.
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:  # noqa: BLE001
            pass


def _pin_bench_env(cpu=False, fake_devices=None):
    """Shared prologue for every bench child/gate: mint the shared base
    dir once (NOT setdefault(k, mkdtemp()) — the fallback arg evaluates
    eagerly, so every child spawned by the orchestrator, which already
    exported the shared base dir, would mint and abandon an empty
    /tmp/bench_* dir), and for the CPU-pinned A/B gates pin the platform
    BEFORE any jax import: the JAX_PLATFORMS env var, the
    accelerator-bootstrap scrub (a TPU-plugin sitecustomize must not
    dial the tunnel at child interpreter startup), and the live-config
    force. ``fake_devices`` adds the
    xla_force_host_platform_device_count flag for soaks whose topology
    is N fake host devices."""
    if "MAGGY_TPU_BASE_DIR" not in os.environ:
        os.environ["MAGGY_TPU_BASE_DIR"] = _mint_base_dir()
    if cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
        for var in _ACCEL_BOOTSTRAP_VARS:
            os.environ.pop(var, None)
    if fake_devices:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count={}"
                .format(fake_devices)).strip()
    _force_cpu_if_requested()


def headline_main():
    """Child process: warm-up, framework sweep, stage-based baselines.
    Prints the headline JSON line (no extras) on stdout."""
    _pin_bench_env()
    from maggy_tpu.util import enable_compile_cache

    enable_compile_cache()
    import jax

    # Device availability was already probed by the orchestrator in a fresh
    # process; a wedged chip hanging here is bounded by the orchestrator's
    # child timeout (and the failure artifact is printed there).
    log("devices: {}".format(jax.devices()))

    # Warm-up: compile every step shape (one per batch choice) so both
    # measurements see a warm cache (the persistent compilation cache does
    # this across runs).
    t0 = time.time()
    for bs in BATCH_CHOICES:
        train_mnist(1e-3, batch=bs, budget=0.2)
    log("warm-up done in {:.1f}s".format(time.time() - t0))

    result, wall = run_framework_sweep()
    n_runs = result["num_trials"]
    trials_per_hour = n_runs / wall * 3600
    log("framework sweep: {} trials in {:.1f}s ({} early-stopped, best={})".format(
        n_runs, wall, result.get("early_stopped"), result.get("best_val")))

    # Stage-based baselines over the schedule the sweep executed (same
    # trials, same budgets, same 3-way worker parallelism — only the
    # scheduling differs; see module docstring).
    import glob, json as _json

    exp_dirs = sorted(glob.glob(os.path.join(
        os.environ["MAGGY_TPU_BASE_DIR"], "*")))
    trial_dicts = []
    for td in glob.glob(os.path.join(exp_dirs[-1], "*", "trial.json")):
        with open(td) as f:
            trial_dicts.append(_json.load(f))
    schedule = [(t.get("start") or 0,
                 (t.get("info_dict") or {}).get("rung", 0),
                 t["params"]["lr"],
                 t["params"].get("batch", 256),
                 t["params"].get("budget", 1)) for t in trial_dicts]
    # Submission order (start timestamps) within each rung — the order a
    # stage scheduler would see.
    schedule.sort()
    rung_schedule = {}
    for _, rung, lr, batch, budget in schedule:
        rung_schedule.setdefault(rung, []).append((lr, batch, budget))
    sched = scheduling_telemetry(exp_dirs[-1], trial_dicts)
    handoff = sched["handoff"]
    if handoff:
        log("hand-off gap ms ({}): median {} p95 {} (n={})".format(
            sched["source"], handoff["median_ms"], handoff["p95_ms"],
            handoff["n"]))
    if sched["early_stop_reaction"]:
        log("early-stop reaction ms: median {} p95 {} (n={})".format(
            sched["early_stop_reaction"]["median_ms"],
            sched["early_stop_reaction"]["p95_ms"],
            sched["early_stop_reaction"]["n"]))
    if sched["suggest"]:
        log("hand-off pipeline: {} prefetch hits / {} misses (hit rate "
            "{}), suggest latency {}".format(
                sched["suggest"].get("prefetch_hits"),
                sched["suggest"].get("prefetch_misses"),
                sched["suggest"].get("hit_rate"),
                sched["suggest"].get("latency")))
    if sched["compile"]:
        log("compile-once: {} warm / {} cold (hit rate {}), ttfm p50 warm "
            "{} vs cold {}".format(
                sched["compile"].get("warm_hits"),
                sched["compile"].get("warm_misses"),
                sched["compile"].get("warm_hit_rate"),
                (sched["compile"].get("ttfm_warm") or {}).get("median_ms"),
                (sched["compile"].get("ttfm_cold") or {}).get("median_ms")))
    trace_path = _export_trace_artifact(exp_dirs[-1])

    # Two interleaved runs per baseline, keeping each baseline's MIN wall:
    # sustained-load drift (host thermal/noisy-neighbor — measured +12%
    # across back-to-back identical runs on the CPU proxy) would otherwise
    # penalize whichever baseline happens to run last. The min leans
    # conservative: sync-SHA (the primary comparator) gets the earliest,
    # coolest slot.
    oracle_sched = [args[2:] for args in schedule]
    sha_wall = oracle_wall = float("inf")
    for _ in range(2):
        sha_wall = min(sha_wall, run_sync_sha_baseline(rung_schedule))
        oracle_wall = min(oracle_wall, run_packed_baseline(oracle_sched))
    sha_trials_per_hour = len(schedule) / sha_wall * 3600
    log("sync-SHA baseline (rung barriers, min of 2): {} trials in {:.1f}s".format(
        len(schedule), sha_wall))
    log("oracle replay (packed, no barriers, min of 2): {} trials in {:.1f}s".format(
        len(schedule), oracle_wall))

    # Repeat-shape warm A/B: the compile-once gate (same platform as the
    # headline — the ROADMAP's flaky-TPU note demands same-run baselines).
    compile_ab = {}
    try:
        compile_ab = run_compile_ab()
        log("compile A/B: gate {} (warm ttfm p50 {} ms vs cold {} ms, "
            "ratio {}; wall warm {}s vs cold {}s)".format(
                compile_ab["gate"].get("gate_ok"),
                compile_ab["gate"].get("warm_ttfm_p50_ms"),
                compile_ab["gate"].get("cold_ttfm_p50_ms"),
                compile_ab["gate"].get("ratio"),
                compile_ab["warm"]["wall_s"], compile_ab["cold"]["wall_s"]))
    except Exception as e:  # noqa: BLE001 - A/B must not cost the headline
        compile_ab = {"error": repr(e)}
        log("compile A/B failed (headline unaffected): {!r}".format(e))

    print(json.dumps({
        "metric": HEADLINE_METRIC,
        "value": round(trials_per_hour, 1),
        "unit": HEADLINE_UNIT,
        "vs_baseline": round(trials_per_hour / sha_trials_per_hour, 3),
        "detail": {
            "framework_wall_s": round(wall, 1),
            "sync_sha_baseline_wall_s": round(sha_wall, 1),
            "oracle_replay_wall_s": round(oracle_wall, 1),
            "vs_oracle": round(oracle_wall / wall, 3),
            "trials": n_runs,
            "early_stopped": result.get("early_stopped", 0),
            "handoff": handoff,
            "early_stop_reaction": sched["early_stop_reaction"],
            "suggest": sched["suggest"],
            "compile": sched["compile"],
            "goodput": sched["goodput"],
            "compile_ab": compile_ab,
            "handoff_source": sched["source"],
            "platform": _current_platform(),
            "trace": trace_path,
            "analysis": analysis_detail(),
        },
    }), flush=True)
    return 0


def _export_trace_artifact(exp_dir):
    """Export the sweep's Perfetto timeline next to its journal and return
    its path — but ONLY after re-reading the written file and validating
    it parses as Chrome-trace JSON: a path recorded in a BENCH artifact
    must point at something a human can actually load."""
    from maggy_tpu.telemetry import JOURNAL_NAME, read_events
    from maggy_tpu.telemetry.trace import validate_trace, write_trace

    journal = os.path.join(exp_dir, JOURNAL_NAME)
    if not os.path.exists(journal):
        return None
    trace_path = os.path.join(exp_dir, "trace.json")
    try:
        n = write_trace(read_events(journal), trace_path)
        with open(trace_path) as f:
            validate_trace(json.load(f))
    except Exception as e:  # noqa: BLE001 - the artifact is best-effort
        log("trace export failed (not recorded): {!r}".format(e))
        return None
    log("trace: {} events -> {} (perfetto-loadable)".format(n, trace_path))
    return trace_path


def chaos_main():
    """``bench.py --chaos``: deterministic fault-injection soak (see
    maggy_tpu/chaos/). Runs the standard plan (runner kill mid-trial,
    false preemption, METRIC drops, severed FINAL replies) against a real
    local sweep and prints one JSON line with the invariant verdict and
    the fault->requeue recovery latencies replayed from the telemetry
    journal. Exit 1 if any recovery invariant is violated."""
    _pin_bench_env()
    from maggy_tpu.chaos.harness import run_soak

    seed = int(os.environ.get("BENCH_CHAOS_SEED", "7"))
    t0 = time.time()
    report = run_soak(seed=seed,
                      num_trials=int(os.environ.get("BENCH_CHAOS_TRIALS",
                                                    "12")),
                      lock_witness=True,
                      # Invariant 9: the obs endpoints must stay
                      # responsive while runners are killed and replies
                      # severed — the soak doubles as the kill-side obs
                      # responsiveness check (the stall side lives in
                      # the tier-1 obs soak test).
                      obs=True)
    print(json.dumps({
        "metric": "chaos soak (kill+preempt+drop+sever, journal-checked)",
        "value": 1.0 if report["ok"] else 0.0,
        "unit": "invariants_ok",
        "detail": {
            "seed": seed,
            "wall_s": round(time.time() - t0, 1),
            "violations": report["violations"],
            "faults": report["faults"],
            "recoveries": report["recoveries"],
            "trials": report["trials"],
            "health": report.get("health"),
            "obs": report.get("obs"),
            "client_retries": report["client_retries"],
            "goodput": report.get("goodput"),
            "platform": _current_platform(),
            "journal": report["journal"],
            # The soak timeline (chaos injections + health flags as
            # instant markers): validated perfetto-loadable or None.
            "trace": _export_trace_artifact(
                os.path.dirname(report["journal"])),
            # Static posture + the witness edges this soak observed: the
            # soak doubles as a dynamic race check (run_soak fails on any
            # forbidden edge, so a green soak certifies zero).
            "analysis": analysis_detail(report.get("witness")),
        },
    }), flush=True)
    return 0 if report["ok"] else 1


def _journal_goodput(journal_path):
    """Fold one journal's chip-time goodput ledger for a detail block
    (best-effort: a missing/torn journal yields {} rather than costing
    the bench)."""
    try:
        from maggy_tpu.telemetry import read_events
        from maggy_tpu.telemetry.goodput import compute_goodput

        return compute_goodput(read_events(journal_path))
    except Exception as e:  # noqa: BLE001 - accounting must not fail a gate
        return {"error": repr(e)}


def _finalized_ids(events):
    """Finalized trial ids of a journal (content-addressed over params,
    so two runs of the same seeded schedule produce identical sets)."""
    return sorted({ev["trial"] for ev in events
                   if ev.get("ev") == "trial"
                   and ev.get("phase") == "finalized"})


def journal_schedule_parity(events_a, events_b,
                            label_a="a", label_b="b",
                            platform_a=None, platform_b=None):
    """Journal-replayed A/B schedule comparator — the ONE home of the
    same-platform-baseline parity rule (ROADMAP flaky-TPU note): two
    arms of an A/B (``--fork`` forking-on vs forking-off), or a
    recovered run vs an uninterrupted reference (``--failover``),
    executed the SAME schedule exactly when their finalized trial-id
    sets match. Returns {match, <label_a>, <label_b>,
    symmetric_difference, platform?}.

    When both arms carry a platform stamp the comparator REFUSES a
    mixed-platform comparison outright (ValueError naming both sides):
    a cross-substrate A/B is not a measurement, and silently returning
    numbers would let one into a BENCH artifact."""
    if platform_a is not None and platform_b is not None \
            and platform_a != platform_b:
        raise ValueError(
            "refusing cross-platform A/B: arm {!r} ran on {!r} but arm "
            "{!r} ran on {!r} — re-run both arms on one platform "
            "(ROADMAP flaky-TPU comparability note)".format(
                label_a, platform_a, label_b, platform_b))
    ids_a, ids_b = _finalized_ids(events_a), _finalized_ids(events_b)
    out = {"match": ids_a == ids_b,
           label_a: len(ids_a), label_b: len(ids_b),
           "symmetric_difference": sorted(set(ids_a) ^ set(ids_b))}
    if platform_a is not None:
        out["platform"] = platform_a
    return out


def rung0_events(events):
    """Restrict a journal to its RUNG-0 trials' events — the seeded base
    schedule. An ASHA A/B whose arms differ in trial DURATION (forking
    on vs off) can legitimately top the ladder at different wall times,
    so the promotion TAIL is timing-dependent; the rung-0 sample set is
    the seed-deterministic half schedule parity is well-defined over."""
    rung0 = {ev["trial"] for ev in events
             if ev.get("ev") == "trial" and ev.get("phase") == "queued"
             and (ev.get("info") or {}).get("rung", 0) == 0}
    return [ev for ev in events if ev.get("trial") in rung0]


def failover_main():
    """``bench.py --failover``: crash-only driver failover gate (see
    maggy_tpu/chaos/driver_soak.py). Runs the kill_driver soak — a real
    driver process SIGKILLed mid-sweep (twice by default) over surviving
    runner-agent processes, restarted with resume=True each time — and
    gates (a) invariant 13 over the multi-incarnation journal, (b)
    journal-replay recovery MTTR (kill -> ``recovered`` marker) p50
    under the bound, and (c) replayed-vs-live parity: the recovered
    sweep's final trial-id set must be IDENTICAL to an uninterrupted run
    of the same seeded schedule. Exit 1 on any violation."""
    _pin_bench_env()
    from maggy_tpu.chaos.driver_soak import run_driver_soak

    seed = int(os.environ.get("BENCH_FAILOVER_SEED", "7"))
    kills = int(os.environ.get("BENCH_FAILOVER_KILLS", "2"))
    trials = int(os.environ.get("BENCH_FAILOVER_TRIALS", "8"))
    mttr_bound_s = float(os.environ.get("BENCH_FAILOVER_MTTR_S", "60"))
    t0 = time.time()
    report = run_driver_soak(trials=trials, workers=3, seed=seed,
                             kills=kills, lock_witness=True)
    mttr_s = sorted(r["mttr_s"] for r in report["failover"]["recoveries"]
                    if r.get("mttr_s") is not None)
    mttr_p50 = mttr_s[len(mttr_s) // 2] if mttr_s else None
    mttr_p95 = mttr_s[int(len(mttr_s) * 0.95)] if mttr_s else None
    violations = list(report["violations"])
    if mttr_p50 is None:
        violations.append("no recovery MTTR measured: no kill produced a "
                          "recovered marker")
    elif mttr_p50 > mttr_bound_s:
        violations.append(
            "recovery too slow: journal-replay MTTR p50 {:.1f}s exceeds "
            "the {:.0f}s bound".format(mttr_p50, mttr_bound_s))

    # Parity: an UNINTERRUPTED run of the same seeded schedule must
    # produce the identical final trial-id set (trial ids are
    # content-addressed over the params, so this compares the executed
    # schedules exactly; the quick closed-form trial body is fine — ids
    # do not depend on trial duration).
    from maggy_tpu import OptimizationConfig, Searchspace, experiment
    from maggy_tpu.chaos.harness import _soak_train_fn
    from maggy_tpu.telemetry import JOURNAL_NAME, read_events

    ref_base = tempfile.mkdtemp(prefix="maggy_failover_ref_")
    ref_cfg = OptimizationConfig(
        name="failover_ref", num_trials=trials, optimizer="randomsearch",
        searchspace=Searchspace(lr=("DOUBLE", [0.0, 0.2]),
                                units=("INTEGER", [8, 64])),
        direction="max", num_workers=3, seed=seed, es_policy="none",
        hb_interval=0.05, experiment_dir=ref_base)
    experiment.lagom(_soak_train_fn, ref_cfg)
    ref_dirs = sorted(d for d in os.listdir(ref_base)
                      if os.path.isdir(os.path.join(ref_base, d)))
    ref_events = read_events(os.path.join(ref_base, ref_dirs[-1],
                                          JOURNAL_NAME))
    soak_events = read_events(report["journal"])

    platform = _current_platform()
    parity_rec = journal_schedule_parity(soak_events, ref_events,
                                         label_a="soak_trials",
                                         label_b="reference_trials",
                                         platform_a=platform,
                                         platform_b=platform)
    parity = parity_rec["match"]
    if not parity:
        violations.append(
            "replayed-vs-live parity broken: recovered sweep finalized {} "
            "trial(s), uninterrupted run {} — symmetric difference "
            "{}".format(parity_rec["soak_trials"],
                        parity_rec["reference_trials"],
                        parity_rec["symmetric_difference"]))
    ok = not violations
    print(json.dumps({
        "metric": "driver failover (SIGKILL x{} + journal-replay "
                  "recovery)".format(kills),
        "value": 1.0 if ok else 0.0,
        "unit": "invariants_ok",
        "detail": {"failover": {
            "seed": seed, "kills": kills, "trials": trials,
            "wall_s": round(time.time() - t0, 1),
            "violations": violations,
            "mttr_p50_ms": round(mttr_p50 * 1e3, 1)
            if mttr_p50 is not None else None,
            "mttr_p95_ms": round(mttr_p95 * 1e3, 1)
            if mttr_p95 is not None else None,
            "mttr_bound_s": mttr_bound_s,
            "driver_epochs": report["failover"]["driver_epochs"],
            "adopted": report["failover"]["adopted"],
            "requeued": report["trials"]["requeued"],
            "recoveries": report["failover"]["recoveries"],
            "parity": parity_rec,
            # The multi-incarnation ledger: killed attempts surface as
            # rework badput, the restart seam as handoff/queue_wait.
            "goodput": _journal_goodput(report["journal"]),
            "platform": platform,
            "witness": report.get("witness"),
            "journal": report["journal"],
        }},
    }), flush=True)
    return 0 if ok else 1


def fork_main():
    """``bench.py --fork``: the checkpoint-forking A/B gate (ROADMAP
    item 3). The SAME fixed ASHA sweep runs twice on the SAME platform —
    forking ON (config.fork, the default) vs OFF (from-scratch
    promotions) — and the gate asserts:

    (a) top-rung re-trained steps drop by >= the rung ratio: with
        forking OFF every top-rung trial re-trains its parent's whole
        prefix; with forking ON it resumes past it (re-trained ~0);
    (b) exact step-for-step loss parity: every forked trial's recorded
        trajectory equals a from-checkpoint continuation of its parent
        (the trial body is a closed form of (lr, step), so equality is
        bitwise — a fork that silently restarted or loaded the wrong
        step cannot pass);
    (c) trials/hour improves (wall_off / wall_on > 1), and both arms
        executed the IDENTICAL schedule (journal_schedule_parity — the
        same-platform-baseline rule shared with --failover).

    Always CPU-pinned (closed-form trial body; the fake accelerator adds
    nothing) with detail.platform recorded per the ROADMAP flaky-TPU
    comparability note. Exit 1 on any gate failure."""
    _pin_bench_env(cpu=True)
    import glob as _glob

    from maggy_tpu import OptimizationConfig, Searchspace, experiment
    from maggy_tpu.chaos.harness import (fork_ckpt_train_fn,
                                         fork_step_metric)
    from maggy_tpu.optimizers import Asha
    from maggy_tpu.telemetry import (JOURNAL_NAME, read_events,
                                     replay_journal)

    seed = int(os.environ.get("BENCH_FORK_SEED", "7"))
    trials = int(os.environ.get("BENCH_FORK_TRIALS", "9"))
    rf = int(os.environ.get("BENCH_FORK_RF", "3"))
    workers = int(os.environ.get("BENCH_FORK_WORKERS", "3"))
    steps_per_budget = 4  # fork_ckpt_train_fn's contract
    t_start = time.time()
    arms = {}
    for arm, fork_on in (("fork", True), ("scratch", False)):
        arm_dir = os.path.join(os.environ["MAGGY_TPU_BASE_DIR"],
                               "fork_ab_{}".format(arm))
        config = OptimizationConfig(
            name="bench_fork_{}".format(arm), num_trials=trials,
            optimizer=Asha(reduction_factor=rf, resource_min=1,
                           resource_max=rf * rf, seed=seed),
            searchspace=Searchspace(lr=("DOUBLE", [0.05, 0.2])),
            direction="max", num_workers=workers, hb_interval=0.02,
            es_policy="none", seed=seed, fork=fork_on,
            # prefetch invalidation re-draws dropped rung-0 samples with
            # fresh RNG state, making the rung-0 id set timing-dependent;
            # the schedule-parity gate needs strictly sequential draws.
            prefetch=False,
            experiment_dir=arm_dir)
        t0 = time.time()
        experiment.lagom(fork_ckpt_train_fn, config)
        wall = time.time() - t0
        exp_dir = sorted(d for d in _glob.glob(os.path.join(arm_dir, "*"))
                         if os.path.isdir(d))[-1]
        events = read_events(os.path.join(exp_dir, JOURNAL_NAME))
        trial_dicts = []
        for td in _glob.glob(os.path.join(exp_dir, "*", "trial.json")):
            with open(td) as f:
                trial_dicts.append(json.load(f))
        arms[arm] = {
            "wall_s": round(wall, 2), "events": events,
            "trials": trial_dicts,
            "derived": replay_journal(os.path.join(exp_dir, JOURNAL_NAME)),
            "platform": _current_platform(),
        }
        log("{} arm: {} trials in {:.1f}s (fork block: {})".format(
            arm, len(trial_dicts), wall,
            arms[arm]["derived"].get("fork")))

    violations = []

    def _fork_steps(events):
        """trial -> forked step from the journal's genealogy edges."""
        return {ev["trial"]: ev.get("step") for ev in events
                if ev.get("ev") == "trial"
                and ev.get("phase") == "forked_from"}

    def _retrained_top_rung(arm):
        """Sum over top-rung trials of the parent-prefix steps the trial
        RE-TRAINED: the whole prefix when dispatched from scratch, the
        part below its fork point when forked (0 at the fork default —
        the fork point is the parent's last step)."""
        info_of = {t["id"]: t.get("info_dict") or {}
                   for t in arms[arm]["trials"]}
        top = max((i.get("rung", 0) for i in info_of.values()), default=0)
        forked_at = _fork_steps(arms[arm]["events"])
        total = 0
        n = 0
        for tid, info in info_of.items():
            if info.get("rung", 0) != top or info.get("parent") is None:
                continue
            n += 1
            parent_budget = (rf ** (top - 1)) * 1
            parent_steps = steps_per_budget * parent_budget
            resume_offset = forked_at.get(tid)
            executed_from = 0 if resume_offset is None else resume_offset + 1
            total += max(0, parent_steps - executed_from)
        return total, n, top

    retrained_fork, n_top_fork, top_rung = _retrained_top_rung("fork")
    retrained_scratch, n_top_scratch, _ = _retrained_top_rung("scratch")
    if n_top_fork == 0 or n_top_scratch == 0:
        violations.append("no top-rung promotions ran: the sweep never "
                          "climbed the ladder (nothing gated)")
    elif retrained_fork * rf > retrained_scratch:
        violations.append(
            "top-rung re-trained steps did not drop by the rung ratio: "
            "forking-on re-trained {} steps vs {} forking-off "
            "(needed <= {}/{} = {})".format(
                retrained_fork, retrained_scratch, retrained_scratch,
                rf, retrained_scratch / rf))

    # (b) exact fork parity: each forked trial's recorded trajectory ==
    # the from-checkpoint continuation of its parent (closed form).
    forked_at = _fork_steps(arms["fork"]["events"])
    parity_checked = 0
    for t in arms["fork"]["trials"]:
        tid = t["id"]
        if tid not in forked_at or forked_at[tid] is None:
            continue
        s_fork = int(forked_at[tid])
        lr = t["params"]["lr"]
        budget = t["params"].get("budget", 1)
        total_steps = max(1, int(round(steps_per_budget * budget)))
        recorded = dict(zip(t.get("step_history") or [],
                            t.get("metric_history") or []))
        if [s for s in recorded if s <= s_fork]:
            violations.append(
                "forked trial {} re-trained its parent's prefix: "
                "recorded steps {} at or below fork point {}".format(
                    tid, sorted(s for s in recorded if s <= s_fork),
                    s_fork))
            continue
        if not recorded:
            continue  # all broadcasts raced the FINAL; nothing to check
        bad = [s for s, v in recorded.items()
               if v != fork_step_metric(lr, int(s))]
        if bad:
            violations.append(
                "fork parity broken: trial {} steps {} diverge from the "
                "parent's from-checkpoint continuation".format(
                    tid, sorted(bad)))
        else:
            parity_checked += 1
        want_final = fork_step_metric(lr, total_steps - 1)
        if t.get("final_metric") is not None \
                and t["final_metric"] != want_final:
            violations.append(
                "fork final-metric parity broken: trial {} finalized {} "
                "vs continuation {}".format(tid, t["final_metric"],
                                            want_final))
    if not forked_at:
        violations.append("forking-on arm journaled zero forked_from "
                          "edges: the hot path never engaged")

    # (c) throughput + identical seeded base schedule across arms (the
    # promotion TAIL is timing-dependent by design: forking tops the
    # ladder sooner — rung0_events scopes parity to what must match).
    schedule_parity = journal_schedule_parity(
        rung0_events(arms["fork"]["events"]),
        rung0_events(arms["scratch"]["events"]),
        label_a="fork_trials", label_b="scratch_trials",
        platform_a=arms["fork"]["platform"],
        platform_b=arms["scratch"]["platform"])
    if not schedule_parity["match"]:
        violations.append(
            "arms executed different rung-0 schedules: symmetric "
            "difference {}".format(
                schedule_parity["symmetric_difference"]))
    wall_ratio = round(arms["scratch"]["wall_s"]
                       / max(arms["fork"]["wall_s"], 1e-9), 3)
    if wall_ratio <= 1.0:
        violations.append(
            "trials/hour did not improve: forking-on wall {}s vs "
            "forking-off {}s (ratio {})".format(
                arms["fork"]["wall_s"], arms["scratch"]["wall_s"],
                wall_ratio))

    ok = not violations
    print(json.dumps({
        "metric": "checkpoint-forking A/B (same ASHA sweep, forking on "
                  "vs off, journal-replayed)",
        "value": 1.0 if ok else 0.0,
        "unit": "fork_gate_ok",
        "detail": {"fork_ab": {
            "seed": seed, "trials": trials, "rung_ratio": rf,
            "wall_s": round(time.time() - t_start, 1),
            "platform": "cpu (pinned; closed-form trial body — "
                        "comparable across hosts per the ROADMAP note)",
            "violations": violations,
            "top_rung": top_rung,
            "retrained_steps_fork_on": retrained_fork,
            "retrained_steps_fork_off": retrained_scratch,
            "top_rung_trials": n_top_fork,
            "parity_trials_checked": parity_checked,
            "schedule_parity": schedule_parity,
            "trials_per_hour_ratio": wall_ratio,
            "wall_fork_on_s": arms["fork"]["wall_s"],
            "wall_fork_off_s": arms["scratch"]["wall_s"],
            "fork": arms["fork"]["derived"].get("fork"),
            "fork_off": arms["scratch"]["derived"].get("fork"),
            # Per-arm chip-time ledgers: forking-on must show as LESS
            # rework badput than from-scratch (--goodput gates this on
            # its own smaller A/B; recorded here for the trajectory).
            "goodput": arms["fork"]["derived"].get("goodput"),
            "goodput_off": arms["scratch"]["derived"].get("goodput"),
        }},
    }), flush=True)
    return 0 if ok else 1


def _vmap_lane_parity(steps=25):
    """Engine-level bitwise sub-gate for --vmap (idiom shared with
    tests/test_vmap.py): K scalar Trainer runs vs one VmapTrainer block
    over the SAME configs must agree bit-for-bit per lane, per step —
    MnistMLP is matmul+elementwise only, so XLA's scalar and vmapped
    programs schedule the same float ops in the same order. Returns a
    violations list (empty = parity holds)."""
    import jax
    import jax.numpy as jnp
    import optax

    from maggy_tpu.models import MnistMLP
    from maggy_tpu.parallel import make_mesh
    from maggy_tpu.train import (Trainer, VmapTrainer, clear_warm,
                                 swept_transform)

    mesh = make_mesh({"data": 1}, devices=jax.devices()[:1])
    model = MnistMLP(features=8, num_classes=2)
    X = DATA_X[:128]
    batch = {"inputs": (jnp.asarray(X),),
             "labels": jnp.asarray(DATA_Y[:128])}
    rng = jax.random.key(0)
    lrs = [1e-3, 3e-3, 1e-2, 3e-2]

    def scalar_run(lr):
        tr = Trainer(model, swept_transform(optax.adam, learning_rate=lr),
                     _bench_loss, mesh, strategy="dp")
        tr.init(rng, (batch["inputs"][0][:1],))
        return np.asarray([float(tr.step(tr.place_batch(batch)))
                           for _ in range(steps)])

    clear_warm()
    scalar = {lr: scalar_run(lr) for lr in lrs}
    clear_warm()
    vt = VmapTrainer(model, optax.adam,
                     [{"learning_rate": lr} for lr in lrs],
                     _bench_loss, mesh, strategy="dp")
    vt.init(rng, (batch["inputs"][0][:1],))
    vlosses = np.stack([np.asarray(vt.step(batch)) for _ in range(steps)])
    clear_warm()
    violations = []
    for i, lr in enumerate(lrs):
        if not np.array_equal(scalar[lr], vlosses[:, i]):
            d = int(np.argmax(scalar[lr] != vlosses[:, i]))
            violations.append(
                "lane {} (lr={}) diverges from its scalar run at step {}: "
                "{!r} vs {!r}".format(i, lr, d, scalar[lr][d],
                                      vlosses[d, i]))
    return violations


def vmap_main():
    """``bench.py --vmap``: the vectorized micro-trials gate (ROADMAP
    item 4). THREE arms of the SAME seeded random-search micro-sweep on
    ONE pinned platform:

      scalar — vmap_lanes unset (the default 1): one trial per dispatch;
      lanes1 — vmap_lanes=1 explicitly: must journal-replay to the
               IDENTICAL schedule as scalar (the bit-for-bit
               compatibility contract of the default);
      vmap   — vmap_lanes=K: the driver assembles K program-compatible
               suggestions into blocks, each block one vmapped program.

    Gates: (a) trials/hour ratio wall_scalar / wall_vmap >= 5 (the
    micro-trial regime is dispatch-overhead-dominated, so K lanes per
    program approaches Kx even on CPU); (b) engine-level bitwise
    per-lane parity vs scalar runs (`_vmap_lane_parity`); (c) scalar vs
    lanes1 finalized-schedule parity via `journal_schedule_parity` with
    per-arm platform stamps; (d) the vmap arm actually assembled blocks
    (lane-tagged journal edges — a silently-scalar run must not pass).

    Always CPU-pinned (CPU-proxy per the ROADMAP flaky-TPU note) with
    detail.platform stamped. Exit 1 on any gate failure."""
    _pin_bench_env(cpu=True)
    import glob as _glob

    from maggy_tpu import OptimizationConfig, Searchspace, experiment
    from maggy_tpu.telemetry import JOURNAL_NAME, read_events, replay_journal

    seed = int(os.environ.get("BENCH_VMAP_SEED", "7"))
    trials = int(os.environ.get("BENCH_VMAP_TRIALS", "25"))
    lanes_k = int(os.environ.get("BENCH_VMAP_LANES", "8"))
    need = float(os.environ.get("BENCH_VMAP_SPEEDUP", "5"))
    t_start = time.time()
    arms = {}
    for arm, k in (("scalar", None), ("lanes1", 1), ("vmap", lanes_k)):
        arm_dir = os.path.join(os.environ["MAGGY_TPU_BASE_DIR"],
                               "vmap_ab_{}".format(arm))
        config = OptimizationConfig(
            name="bench_vmap_{}".format(arm), num_trials=trials,
            optimizer="randomsearch",
            searchspace=Searchspace(lr=("DOUBLE_LOG", [1e-3, 3e-2])),
            direction="max", num_workers=1, hb_interval=0.05,
            es_policy="none", seed=seed, experiment_dir=arm_dir,
            **({"vmap_lanes": k} if k is not None else {}))
        t0 = time.time()
        experiment.lagom(train_mnist_vmap, config)
        wall = time.time() - t0
        exp_dir = sorted(d for d in _glob.glob(os.path.join(arm_dir, "*"))
                         if os.path.isdir(d))[-1]
        events = read_events(os.path.join(exp_dir, JOURNAL_NAME))
        arms[arm] = {
            "wall_s": round(wall, 2), "events": events,
            "derived": replay_journal(os.path.join(exp_dir, JOURNAL_NAME)),
            "platform": _current_platform(),
        }
        n_lane = len([e for e in events if e.get("phase") == "assigned"
                      and e.get("lane") is not None])
        log("{} arm: {} trials in {:.1f}s ({} lane-tagged assignments)"
            .format(arm, trials, wall, n_lane))

    violations = []

    # (a) throughput: K lanes per program must beat scalar dispatch by
    # the gate factor in the dispatch-bound micro-trial regime.
    speedup = round(arms["scalar"]["wall_s"]
                    / max(arms["vmap"]["wall_s"], 1e-9), 2)
    if speedup < need:
        violations.append(
            "vectorized trials/hour gate missed: scalar {}s / vmap {}s "
            "= {}x (need >= {}x)".format(
                arms["scalar"]["wall_s"], arms["vmap"]["wall_s"],
                speedup, need))

    # (b) bitwise per-lane loss parity at the engine level.
    parity_violations = _vmap_lane_parity()
    violations.extend(parity_violations)

    # (c) vmap_lanes=1 is the scalar path bit-for-bit: identical
    # journal-replayed schedule (same seed => same content-addressed ids).
    schedule_parity = journal_schedule_parity(
        arms["scalar"]["events"], arms["lanes1"]["events"],
        label_a="scalar_trials", label_b="lanes1_trials",
        platform_a=arms["scalar"]["platform"],
        platform_b=arms["lanes1"]["platform"])
    if not schedule_parity["match"]:
        violations.append(
            "vmap_lanes=1 executed a different schedule than the scalar "
            "default: symmetric difference {}".format(
                schedule_parity["symmetric_difference"]))
    lanes1_tagged = [e for e in arms["lanes1"]["events"]
                     if e.get("lane") is not None]
    if lanes1_tagged:
        violations.append(
            "vmap_lanes=1 journaled {} lane-tagged edges; the scalar "
            "path must be bit-for-bit untouched".format(len(lanes1_tagged)))

    # (d) the vmap arm really rode blocks: all but the warm-up scalar
    # dispatches should carry lane-tagged assignment edges.
    lane_assigned = [e for e in arms["vmap"]["events"]
                     if e.get("phase") == "assigned"
                     and e.get("lane") is not None]
    blocks = sorted({e.get("block") for e in lane_assigned})
    if len(lane_assigned) < trials - lanes_k:
        violations.append(
            "vmap arm barely vectorized: only {}/{} trials rode blocks "
            "(need >= {}) — block assembly is not engaging".format(
                len(lane_assigned), trials, trials - lanes_k))

    ok = not violations
    print(json.dumps({
        "metric": "vectorized micro-trials A/B (K configs per chip as one "
                  "vmapped program, journal-replayed)",
        "value": speedup if ok else 0.0,
        "unit": "x_trials_per_hour_vs_scalar",
        "detail": {"vmap_ab": {
            "seed": seed, "trials": trials, "vmap_lanes": lanes_k,
            "steps": VMAP_STEPS,
            "wall_s": round(time.time() - t_start, 1),
            "platform": "cpu (pinned; CPU-proxy micro-trials — "
                        "comparable across hosts per the ROADMAP note)",
            "violations": violations,
            "speedup": speedup, "speedup_needed": need,
            "wall_scalar_s": arms["scalar"]["wall_s"],
            "wall_lanes1_s": arms["lanes1"]["wall_s"],
            "wall_vmap_s": arms["vmap"]["wall_s"],
            "lane_parity_lanes_checked": 4 - len(parity_violations),
            "schedule_parity": schedule_parity,
            "blocks": blocks,
            "lane_assignments": len(lane_assigned),
            # Chip-time ledger of the vectorized arm: block chip-seconds
            # split across lanes, masked tails billed to lane_idle.
            "goodput": arms["vmap"]["derived"].get("goodput"),
            "goodput_scalar": arms["scalar"]["derived"].get("goodput"),
        }},
    }), flush=True)
    return 0 if ok else 1


def goodput_main():
    """``bench.py --goodput``: the chip-time ledger gate. Two
    journal-replayed A/Bs on ONE pinned platform prove the ledger
    measures what it claims:

    (a) warm-start A/B (run_compile_ab): the warm arm's COMPILE badput
        chip-seconds must land strictly below the cold arm's — the
        compile-once win shows up as measured badput reduction, not
        just a ttfm distribution;
    (b) fork A/B (small ASHA sweep, forking on vs off): the forking
        arm's REWORK badput must land strictly below from-scratch — a
        from-scratch promotion re-trains its parent's prefix and the
        accountant books exactly that time as rework;
    (c) every arm's ``unaccounted`` residual stays <= 5% of held
        chip-time — the taxonomy is closed, a leak fails the gate;
    (d) both fork arms carry the SAME platform stamp
        (journal_schedule_parity raises on a mixed-platform A/B).

    CPU-pinned like --fork (closed-form/tiny trial bodies; the ledger
    under test is platform-independent journal arithmetic). Exit 1 on
    any gate failure."""
    _pin_bench_env(cpu=True)
    import glob as _glob

    from maggy_tpu import OptimizationConfig, Searchspace, experiment
    from maggy_tpu.chaos.harness import fork_ckpt_train_fn
    from maggy_tpu.optimizers import Asha
    from maggy_tpu.telemetry import (JOURNAL_NAME, read_events,
                                     replay_journal)

    seed = int(os.environ.get("BENCH_GOODPUT_SEED", "7"))
    rf = 3
    # ASHA's rung ladder needs rf**2 trials to build all three rungs.
    trials = max(int(os.environ.get("BENCH_GOODPUT_TRIALS", "9")), rf * rf)
    bound = float(os.environ.get("BENCH_GOODPUT_UNACCOUNTED", "0.05"))
    t_start = time.time()
    violations = []

    def _bucket(gp, name):
        return ((gp or {}).get("buckets") or {}).get(name) or 0.0

    # (a) warm-start A/B — run_compile_ab already replays each arm's
    # journal; its per-arm blocks now carry the goodput ledger.
    compile_ab = run_compile_ab()
    ledgers = {"warm": compile_ab["warm"]["goodput"],
               "cold": compile_ab["cold"]["goodput"]}
    warm_compile = sum(_bucket(ledgers["warm"], b)
                       for b in ("init", "trace", "compile"))
    cold_compile = sum(_bucket(ledgers["cold"], b)
                       for b in ("init", "trace", "compile"))
    if not warm_compile < cold_compile:
        violations.append(
            "warm-start did not show as measured compile badput "
            "reduction: warm arm {:.2f}s (init+trace+compile) vs cold "
            "arm {:.2f}s".format(warm_compile, cold_compile))
    log("warm A/B compile badput: warm {:.2f}s vs cold {:.2f}s".format(
        warm_compile, cold_compile))

    # (b) fork A/B — the --fork sweep at reduced size, gated on the
    # ledger's REWORK bucket instead of re-trained step counts.
    events_by_arm = {}
    for arm, fork_on in (("fork", True), ("scratch", False)):
        arm_dir = os.path.join(os.environ["MAGGY_TPU_BASE_DIR"],
                               "goodput_ab_{}".format(arm))
        config = OptimizationConfig(
            name="bench_goodput_{}".format(arm), num_trials=trials,
            optimizer=Asha(reduction_factor=rf, resource_min=1,
                           resource_max=rf * rf, seed=seed),
            searchspace=Searchspace(lr=("DOUBLE", [0.05, 0.2])),
            direction="max", num_workers=3, hb_interval=0.02,
            es_policy="none", seed=seed, fork=fork_on,
            # prefetch invalidation re-draws dropped rung-0 samples with
            # fresh RNG state, making the rung-0 id set timing-dependent;
            # the schedule-parity gate needs strictly sequential draws.
            prefetch=False,
            experiment_dir=arm_dir)
        experiment.lagom(fork_ckpt_train_fn, config)
        exp_dir = sorted(d for d in _glob.glob(os.path.join(arm_dir, "*"))
                         if os.path.isdir(d))[-1]
        events_by_arm[arm] = read_events(
            os.path.join(exp_dir, JOURNAL_NAME))
        ledgers[arm] = replay_journal(
            os.path.join(exp_dir, JOURNAL_NAME)).get("goodput") or {}
    fork_rework = _bucket(ledgers["fork"], "rework")
    scratch_rework = _bucket(ledgers["scratch"], "rework")
    if not fork_rework < scratch_rework:
        violations.append(
            "forking did not show as measured rework badput reduction: "
            "forking-on {:.2f}s rework vs from-scratch {:.2f}s".format(
                fork_rework, scratch_rework))
    log("fork A/B rework badput: fork {:.2f}s vs scratch {:.2f}s".format(
        fork_rework, scratch_rework))

    # (c) closed taxonomy: no arm may leak more than the bound.
    for arm, gp in sorted(ledgers.items()):
        if not gp:
            violations.append(
                "arm {} produced no goodput ledger (empty journal "
                "fold)".format(arm))
            continue
        frac = gp.get("unaccounted_fraction")
        if frac is None or frac > bound:
            violations.append(
                "arm {} unaccounted chip-time {} exceeds the {:.0%} "
                "bound".format(arm, frac, bound))

    # (d) same-platform rule: the comparator itself raises on a
    # mixed-platform A/B, so a green parity record certifies the stamp.
    platform = _current_platform()
    try:
        parity = journal_schedule_parity(
            rung0_events(events_by_arm["fork"]),
            rung0_events(events_by_arm["scratch"]),
            label_a="fork_trials", label_b="scratch_trials",
            platform_a=platform, platform_b=platform)
        if not parity["match"]:
            violations.append(
                "fork A/B arms executed different rung-0 schedules: "
                "symmetric difference {}".format(
                    parity["symmetric_difference"]))
    except ValueError as e:
        parity = {"match": False, "error": str(e)}
        violations.append(str(e))

    ok = not violations
    print(json.dumps({
        "metric": "chip-time goodput ledger (warm + fork A/B, "
                  "journal-replayed)",
        "value": 1.0 if ok else 0.0,
        "unit": "goodput_gate_ok",
        "detail": {"goodput_gate": {
            "seed": seed, "trials": trials,
            "wall_s": round(time.time() - t_start, 1),
            "platform": platform,
            "violations": violations,
            "unaccounted_bound": bound,
            "compile_badput_s": {"warm": round(warm_compile, 3),
                                 "cold": round(cold_compile, 3)},
            "rework_s": {"fork": round(fork_rework, 3),
                         "scratch": round(scratch_rework, 3)},
            "schedule_parity": parity,
            "arms": {arm: {
                "goodput_fraction": gp.get("goodput_fraction"),
                "unaccounted_fraction": gp.get("unaccounted_fraction"),
                "held_chip_s": gp.get("held_chip_s"),
                "badput_top": gp.get("badput_top"),
            } for arm, gp in sorted(ledgers.items()) if gp},
        }},
    }), flush=True)
    return 0 if ok else 1


def fleet_main():
    """``bench.py --fleet``: shared-fleet scheduling soak (see
    maggy_tpu/fleet/). Runs two concurrent experiments over one 2-runner
    fleet — a low-priority bulk sweep preempted mid-flight by a
    high-priority arrival — and prints one JSON line whose detail.fleet
    block carries the journal-replayed scheduling numbers (queue wait
    p50/p95, preemption count, share error vs the configured weights).
    Exit 1 if any fleet invariant is violated."""
    _pin_bench_env()
    from maggy_tpu.fleet.soak import run_fleet_soak

    seed = int(os.environ.get("BENCH_FLEET_SEED", "7"))
    t0 = time.time()
    report = run_fleet_soak(seed=seed)
    print(json.dumps({
        "metric": "fleet soak (2 experiments / 2 runners, preempt+resume, "
                  "journal-checked)",
        "value": 1.0 if report["ok"] else 0.0,
        "unit": "invariants_ok",
        "detail": {
            "seed": seed,
            "wall_s": round(time.time() - t0, 1),
            "violations": report["violations"],
            "results": report["results"],
            "fleet": report["detail"],
            # The fleet replay's per-tenant ledger roll-up (also inside
            # detail.fleet.goodput; hoisted for the trajectory reader).
            "goodput": (report["detail"] or {}).get("goodput"),
            "platform": _current_platform(),
            "journal": report["journal"],
        },
    }), flush=True)
    return 0 if report["ok"] else 1


def pack_main():
    """``bench.py --pack``: gang-scheduling pack soak (see
    maggy_tpu/gang.py). Runs the mixed sweep — 1-chip ASHA rung-0 trials
    + 4-chip fsdp gang promotions — on an 8-fake-device CPU proxy fleet
    and prints one JSON line whose detail.pack block carries the
    journal-replayed packing numbers (chip-seconds utilization,
    fragmentation stalls, gang assembly latency p50/p95). Always a CPU
    proxy (the fake-device count IS the topology under test), so runs
    are comparable across hosts per the ROADMAP platform-gating note.
    Exit 1 if the sweep deadlocks, utilization misses the 0.7 gate, or a
    gang trial diverges from the single-process sharded reference."""
    # Before any jax import: the pack soak's topology is 8 fake host
    # devices, regardless of what accelerator the host has.
    _pin_bench_env(cpu=True, fake_devices=8)
    from maggy_tpu.gang import run_pack_soak

    seed = int(os.environ.get("BENCH_PACK_SEED", "7"))
    t0 = time.time()
    report = run_pack_soak(seed=seed)
    pack = report["pack"]
    print(json.dumps({
        "metric": "gang pack soak (mixed 1-chip ASHA + 4-chip fsdp gangs "
                  "on 8 fake devices, journal-replayed)",
        "value": pack.get("chip_seconds_utilization") or 0.0,
        "unit": "chip_seconds_utilization",
        "detail": {
            "seed": seed,
            "wall_s": round(time.time() - t0, 1),
            "violations": report["violations"],
            "pack": pack,
            # Gang-vs-reference parity (MULTICHIP dryrun parity): each
            # gang trial's final loss against the single-process sharded
            # reference for its declared shape.
            "parity": report["parity"],
            "platform": "cpu proxy (8 fake devices via "
                        "--xla_force_host_platform_device_count)",
            "journal": report["journal"],
            "result": report["result"],
            # Gang assembly as grouped lanes + pack instants: validated
            # perfetto-loadable or None.
            "trace": _export_trace_artifact(
                os.path.dirname(report["journal"])),
        },
    }), flush=True)
    return 0 if report["ok"] else 1


def _obs_train_fn(lr, units, reporter=None):
    """Obs-bench trial: pure-python, deterministic, a few broadcast
    steps — the sweep exists to put live load on the scrape path, not to
    measure training."""
    import time as _time

    acc = 1.0 / (1.0 + abs(lr - 0.1) + units / 1e4)
    for step in range(4):
        reporter.broadcast(acc * (step + 1) / 4.0, step=step)
        _time.sleep(0.02)
    return {"metric": acc}


def obs_main():
    """``bench.py --obs``: observability-plane scrape bench (see
    maggy_tpu/telemetry/obs.py). Runs a small sweep with the obs server
    on (ephemeral port) while a scraper polls /metrics + /status +
    /healthz at ~30 Hz, and prints one JSON line whose detail.obs block
    carries per-route scrape latency p50/p95 under live load plus a
    scrape-vs-journal consistency verdict: every scraped finalized-count
    sample must sit between the journal-replayed counts bracketing the
    scrape's wall time. Always a CPU proxy (the plane under test is
    platform-independent Python; pinning the platform keeps rounds
    comparable per the ROADMAP flaky-TPU note — detail.platform records
    it). Exit 1 if the endpoints fail, stall, or disagree with the
    journal."""
    _pin_bench_env(cpu=True)
    import glob
    import threading
    import urllib.error
    import urllib.request

    from maggy_tpu import OptimizationConfig, Searchspace, experiment
    from maggy_tpu.telemetry import JOURNAL_NAME, obs, read_events
    from maggy_tpu.telemetry.spans import _dist_stats

    seed = int(os.environ.get("BENCH_OBS_SEED", "7"))
    trials = int(os.environ.get("BENCH_OBS_TRIALS", "10"))
    t0 = time.time()
    lat = {"/metrics": [], "/status": [], "/healthz": []}
    samples = []  # (wall_t, finalized count scraped from /metrics)
    failures = []
    healthz_bad = 0
    stop = threading.Event()

    def scraper():
        base = None
        while not stop.is_set():
            server = obs.active_server()
            if server is None:
                if base is not None:
                    return
                time.sleep(0.01)
                continue
            if base is None:
                base = "http://{}:{}".format(*server.address)
            try:
                bodies = {}
                for route in ("/metrics", "/status", "/healthz"):
                    r0 = time.monotonic()
                    try:
                        bodies[route] = urllib.request.urlopen(
                            base + route, timeout=5).read().decode()
                    except urllib.error.HTTPError as e:
                        # /healthz legitimately answers 503 (counted —
                        # this fault-free sweep must never be
                        # unhealthy); an error status on any OTHER
                        # route is a broken endpoint, not a scrape.
                        if route != "/healthz":
                            raise
                        bodies[route] = e.read().decode()
                        nonlocal_count["healthz_bad"] += 1
                    lat[route].append((time.monotonic() - r0) * 1e3)
                wall = time.time()
                count = 0
                for line in bodies["/metrics"].splitlines():
                    if line.startswith("maggy_tpu_trial_phase_total") \
                            and 'phase="finalized"' in line:
                        count = int(float(line.rsplit(" ", 1)[1]))
                samples.append((wall, count))
            except Exception as e:  # noqa: BLE001 - the failure IS the finding
                if obs.active_server() is not None:
                    failures.append(repr(e))
            time.sleep(0.03)

    nonlocal_count = {"healthz_bad": 0}
    thread = threading.Thread(target=scraper, daemon=True)
    thread.start()
    config = OptimizationConfig(
        name="bench_obs", num_trials=trials, optimizer="randomsearch",
        searchspace=Searchspace(lr=("DOUBLE", [0.0, 0.2]),
                                units=("INTEGER", [8, 64])),
        direction="max", num_workers=2, hb_interval=0.05, seed=seed,
        es_policy="none", obs_port=0)
    result = experiment.lagom(_obs_train_fn, config)
    stop.set()
    thread.join(timeout=5)
    healthz_bad = nonlocal_count["healthz_bad"]

    exp_dirs = sorted(d for d in glob.glob(os.path.join(
        os.environ["MAGGY_TPU_BASE_DIR"], "*")) if os.path.isdir(d))
    journal = os.path.join(exp_dirs[-1], JOURNAL_NAME)
    events = read_events(journal)
    fin_times = sorted(e["t"] for e in events
                       if e.get("ev") == "trial"
                       and e.get("phase") == "finalized")
    # Scrape-vs-journal: a live counter read at wall time T must agree
    # with the journal replayed to T, up to clock/step slack either side.
    slack = 0.5
    mismatches = []
    for wall, count in samples:
        lo = sum(1 for t in fin_times if t <= wall - slack)
        hi = sum(1 for t in fin_times if t <= wall + slack)
        if not lo <= count <= hi:
            mismatches.append({"t": wall, "scraped": count,
                               "journal_bounds": [lo, hi]})
    ok = bool(samples) and not failures and not mismatches \
        and healthz_bad == 0 and result.get("num_trials") == trials
    print(json.dumps({
        "metric": "obs scrape (live /metrics+/status+/healthz under a "
                  "{}-trial sweep, journal-checked)".format(trials),
        "value": 1.0 if ok else 0.0,
        "unit": "scrape_consistent",
        "detail": {
            "obs": {
                "scrapes": len(samples),
                "failures": failures,
                "healthz_not_ok": healthz_bad,
                "scrape_ms": {route.strip("/"): _dist_stats(vals)
                              for route, vals in lat.items()},
                "consistency": {"samples": len(samples),
                                "mismatches": mismatches,
                                "slack_s": slack,
                                "journal_finalized": len(fin_times),
                                "last_scraped": samples[-1][1]
                                if samples else None},
            },
            "platform": "cpu proxy (forced; the obs plane is "
                        "platform-independent — pinned for "
                        "cross-round comparability)",
            "seed": seed,
            "wall_s": round(time.time() - t0, 1),
            "journal": journal,
        },
    }), flush=True)
    return 0 if ok else 1


def scale_main():
    """``bench.py --scale``: service-scale control-plane soak (see
    maggy_tpu/fleet/soak.py run_scale_soak). Four phases against real
    fleets: (1) a >=500-concurrent-experiment churn through one fleet
    (lagom_submit + the spool path) gating tenant completion, scheduler
    decision throughput, and admission latency p99; (2) the SINK A/B —
    the same churn with telemetry re-enabled through the fleet's journal
    sink (``detail.sink``): decision throughput and admission p99 must
    stay within 10% of the telemetry-off baseline and the sink's
    replayed ingest lag p95 in bound — telemetry at churn scale must be
    near-free (BENCH_SCALE_SINK=0 skips the arm); (3) three weighted
    resident tenants gating journal-replayed fair-share error; (4) the
    slow-tenant A/B — per-tenant dispatch pools ON must hold the victim
    hand-off p95 isolation bound, and the pool-OFF (pre-fix shared-loop)
    arm must show the head-of-line inflation the pools remove. Always a
    CPU-pinned run (the plane under test is platform-independent Python;
    detail.platform records the pin per the ROADMAP comparability note).
    Exit 1 on any gate violation.

    ``--scale --remote`` runs the REMOTE variant instead (ROADMAP item 4
    remainder — "nothing yet measures hundreds of sockets"): the churn
    driven by real agent daemon processes over sockets
    (fleet/soak.py run_remote_scale_soak), recording ``detail.remote``:
    agent join latency p50/p95, ABIND lease round-trip p50/p95, and
    churn completion — with ``detail.platform`` pinned the same way for
    comparability against the in-process rounds."""
    _pin_bench_env(cpu=True)
    seed = int(os.environ.get("BENCH_SCALE_SEED", "7"))
    platform_note = ("cpu pinned (forced; the control plane under test "
                     "is platform-independent — pinned for cross-round "
                     "comparability)")
    t0 = time.time()
    if "--remote" in sys.argv:
        from maggy_tpu.fleet.soak import run_remote_scale_soak

        experiments = int(os.environ.get("BENCH_REMOTE_EXPERIMENTS", "40"))
        agents = int(os.environ.get("BENCH_REMOTE_AGENTS", "4"))
        runners = int(os.environ.get("BENCH_REMOTE_RUNNERS", "2"))
        report = run_remote_scale_soak(
            experiments=experiments, agents=agents, runners=runners,
            seed=seed)
        print(json.dumps({
            "metric": "remote scale soak ({} tenants churned through {} "
                      "real agent processes over sockets, "
                      "journal-checked)".format(experiments, agents),
            "value": report["detail"].get("experiments_per_s") or 0.0,
            "unit": "experiments_per_s",
            "detail": {
                "seed": seed,
                "wall_s": round(time.time() - t0, 1),
                "violations": report["violations"],
                "remote": report["detail"],
                "platform": platform_note,
                "journal": report["journal"],
            },
        }), flush=True)
        return 0 if report["ok"] else 1
    from maggy_tpu.fleet.soak import run_scale_soak

    experiments = int(os.environ.get("BENCH_SCALE_EXPERIMENTS", "520"))
    runners = int(os.environ.get("BENCH_SCALE_RUNNERS", "8"))
    max_active = int(os.environ.get("BENCH_SCALE_MAX_ACTIVE", "12"))
    sink_ab = os.environ.get("BENCH_SCALE_SINK", "1").strip().lower() \
        not in ("0", "false", "off")
    report = run_scale_soak(experiments=experiments, runners=runners,
                            max_active=max_active, seed=seed,
                            sink_ab=sink_ab)
    # The sink A/B block surfaces once, as detail.sink (popped from the
    # soak detail so the record doesn't serialize it twice).
    scale_detail = dict(report["detail"])
    sink_detail = scale_detail.pop("sink", None)
    churn = report["detail"]["churn"]
    print(json.dumps({
        "metric": "scale soak ({} tenants / {} runners churn + weighted "
                  "share + slow-tenant A/B, journal-checked)".format(
                      experiments, runners),
        "value": churn.get("experiments_per_s") or 0.0,
        "unit": "experiments_per_s",
        "detail": {
            "seed": seed,
            "wall_s": round(time.time() - t0, 1),
            "violations": report["violations"],
            "scale": scale_detail,
            "sink": sink_detail,
            "platform": platform_note,
            "journal": report["journal"],
        },
    }), flush=True)
    return 0 if report["ok"] else 1


def extra_main(name):
    """Child process: run ONE extra bench and print its JSON on stdout."""
    if name == "hang":  # test hook: simulates a compile stall / wedged op
        log("hang extra: sleeping forever (test hook)")
        time.sleep(1e9)
        return 0
    _force_cpu_if_requested()
    from maggy_tpu.util import enable_compile_cache

    enable_compile_cache()
    result = EXTRA_BENCHES[name]()
    print(json.dumps(result), flush=True)
    return 0


# ------------------------------------------------------------- orchestrator

def _last_json_line(text):
    for line in reversed(text.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except ValueError:
                continue
    return None


def _run_child(argv, timeout_s):
    """Run a bench child; KILL it on timeout so this process never blocks
    or aborts on a child's device stall (the round-3 wedge came from
    abandoning a worker *thread* mid-device-call and carrying on in the
    same process). NOTE a killed child's TPU claim may still linger on the
    tunneled relay — callers must re-probe the device after any kill and
    skip further device work if it does not come back.

    Returns (status, payload): status in {"ok", "timeout", "crash"};
    payload is the child's last stdout JSON line, or on crash a dict with
    the stderr tail. Child stderr is tee'd through live."""
    import subprocess
    import threading

    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)] + argv,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    out_parts, err_tail = [], []

    # Each pipe gets exactly ONE reader thread (communicate() alongside a
    # tee thread would race it for chunks and drop most of the content).
    def _read_out():
        for line in proc.stdout:
            out_parts.append(line)

    def _tee_err():
        for line in proc.stderr:
            sys.stderr.write(line)
            sys.stderr.flush()
            err_tail.append(line)
            del err_tail[:-40]

    readers = [threading.Thread(target=_read_out, daemon=True),
               threading.Thread(target=_tee_err, daemon=True)]
    for r in readers:
        r.start()
    try:
        proc.wait(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()
        return "timeout", None
    for r in readers:  # EOF arrives once the child's pipe ends close
        r.join(timeout=5)
    parsed = _last_json_line("".join(out_parts))
    if proc.returncode != 0:
        return "crash", parsed if parsed is not None else {
            "stderr_tail": "".join(err_tail)[-2000:]}
    if parsed is None:
        return "crash", {"stderr_tail": "".join(err_tail)[-2000:]}
    return "ok", parsed


_PROBE_CODE = """\
import os
if os.environ.get("JAX_PLATFORMS", "") == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")
import jax
jax.devices()
print("ok")
"""


def _probe_device(timeout_s):
    """Fresh-process device probe (the only reliable wedge detector: the
    current process's view proves nothing about a NEW client's ability to
    claim the chip). Honors the JAX_PLATFORMS=cpu override the same way
    the bench children do (env alone loses to a pre-imported TPU plugin)."""
    import subprocess

    try:
        rc = subprocess.run(
            [sys.executable, "-c", _PROBE_CODE],
            timeout=timeout_s, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL).returncode
        return rc == 0
    except subprocess.TimeoutExpired:
        return False


def _proc_starttime(pid):
    """The kernel's process start time (clock ticks since boot; stat
    field 22) — with the pid it uniquely identifies ONE process incarnation,
    which is what makes the owner-liveness check immune to pid reuse."""
    with open("/proc/{}/stat".format(pid)) as f:
        stat = f.read()
    return stat.rsplit(")", 1)[1].split()[19]


def _mint_base_dir():
    """Create this run's bench tmpdir and record our (pid, starttime) as
    its OWNER (.bench_owner): remediation in later runs uses it to tell a
    crashed run's leftovers (owner gone — killable) from a live concurrent
    run's winding-down children (owner alive — hands off), covering the
    SIGKILL/OOM case the atexit cleanup cannot."""
    base = tempfile.mkdtemp(prefix="bench_")
    try:
        pid = os.getpid()
        with open(os.path.join(base, ".bench_owner"), "w") as f:
            f.write("{} {}".format(pid, _proc_starttime(pid)))
    except OSError:
        pass
    return base


def _owner_is_dead(base):
    """True only when the run that minted ``base`` is POSITIVELY over:
    the recorded owner (pid, starttime) no longer exists. A recycled pid
    shows a different starttime, so it reads as dead rather than
    resurrecting the claim (NOT the owner's environ — /proc environ is
    frozen at exec time and never reflects the os.environ assignment the
    orchestrator makes). Missing/unreadable owner records and permission
    errors stay conservative (False = assume live)."""
    try:
        with open(os.path.join(base, ".bench_owner")) as f:
            fields = f.read().split()
        pid, started = int(fields[0]), fields[1]
    except (OSError, ValueError, IndexError):
        return False
    try:
        return _proc_starttime(pid) != started
    except FileNotFoundError:
        return True  # no such process: the owner is gone
    except (OSError, IndexError):
        return False


def _marker_base_dir(environ: bytes):
    """The MAGGY_TPU_BASE_DIR value from a /proc/<pid>/environ blob, or
    None. The INITIAL environment is the marker of record: mp-spawn
    grandchildren run a generic cmdline but inherit the base dir at exec
    time."""
    for entry in environ.split(b"\x00"):
        if entry.startswith(b"MAGGY_TPU_BASE_DIR="):
            return entry.split(b"=", 1)[1].decode("utf-8", "replace")
    return None


def _is_killable_orphan_marker(base, my_base=None):
    """Kill decision for an init-reparented python with a bench marker.

    A bench_ marker alone is not a death warrant: a CONCURRENT bench run's
    winding-down children are init-reparented during the normal mp-spawn
    teardown window and must never be killed. Killable requires the
    marker to name a bench_ tmpdir that is NOT this process's own run,
    plus positive evidence that run is OVER: its dir is gone from disk
    (the orchestrator removes its tmpdir at clean exit, see main()), or
    the dir remains — a SIGKILLed/OOM-killed run never reaches atexit —
    but the owner pid it recorded (.bench_owner) is dead. A live
    concurrent run fails both tests and is left alone."""
    if not base or not os.path.basename(base).startswith("bench_"):
        return False
    if my_base is None:
        my_base = os.environ.get("MAGGY_TPU_BASE_DIR", "")
    if base == my_base:
        return False
    if not os.path.isdir(base):
        return True
    return _owner_is_dead(base)


def _remediate_device():
    """Best-effort cleanup of stale-claim causes THIS repo's own runs can
    create, between probe attempts. Two known sources (BASELINE.md, the
    round-3 incident): (1) an orphaned bench/runner child from a previous
    run still holding the single-client tunnel claim; (2) a stale libtpu
    lockfile left by a killed process. Only processes that are clearly
    ours (cmdline mentions this repo's bench/runner entry points) and
    orphaned (reparented to init) are touched — never the driver, the
    judge, or live experiments."""
    import glob
    import signal

    killed = []
    try:
        my_pid = os.getpid()
        for status_path in glob.glob("/proc/[0-9]*/cmdline"):
            pid = int(status_path.split("/")[2])
            if pid == my_pid:
                continue
            try:
                with open(status_path, "rb") as f:
                    cmd = f.read().replace(b"\x00", b" ").decode(
                        "utf-8", "replace")
                with open("/proc/{}/stat".format(pid)) as f:
                    ppid = int(f.read().split(")")[-1].split()[1])
            except (OSError, ValueError, IndexError):
                continue
            if ppid != 1 or "python" not in cmd:
                continue
            # Identify OUR orphans by their INITIAL environment, not their
            # cmdline: mp-spawn grandchildren run a generic spawn_main
            # cmdline, while a user's daemonized runner agent (ppid 1 but
            # alive on purpose) must never match. Every process a bench
            # run creates inherits MAGGY_TPU_BASE_DIR=<tmp>/bench_* at
            # exec time, so /proc/<pid>/environ carries the marker.
            try:
                with open("/proc/{}/environ".format(pid), "rb") as f:
                    environ = f.read()
            except OSError:
                continue
            if _is_killable_orphan_marker(_marker_base_dir(environ)):
                try:
                    os.kill(pid, signal.SIGKILL)
                    killed.append(pid)
                except OSError:
                    pass
    except Exception:  # noqa: BLE001 - remediation must never break the bench
        pass
    import fcntl

    for lock in glob.glob("/tmp/libtpu_lockfile*") + glob.glob(
            "/tmp/tpu_lockfile*"):
        # Only delete STALE lockfiles: a live holder keeps its flock, so a
        # successful non-blocking flock proves nobody holds it. Deleting a
        # held lockfile would let two processes both claim the device once
        # the holder's claim frees — worse than the wedge being remediated.
        try:
            fd = os.open(lock, os.O_RDWR)
        except OSError:
            continue
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            os.unlink(lock)
            killed.append(lock)
        except OSError:
            pass
        finally:
            os.close(fd)
    if killed:
        log("remediation removed stale claim-holders/locks: {}".format(killed))


def _probe_device_with_retry(budget_s):
    """Spend the WHOLE probe budget trying to reach the device: probe,
    remediate (kill this repo's orphaned claim-holders, clear stale
    lockfiles), probe again — so a chip that recovers anywhere inside the
    window is caught, instead of one early probe deciding the round
    (the r3/r4 failure mode: both artifacts were information-free 0.0s
    from a single probe at an unlucky moment)."""
    # Per-attempt timeout must cover a SLOW-HEALTHY claim (cold tunnel
    # dial + plugin init can take minutes on a loaded host) — a cap that
    # only fits the fast case would misclassify a live chip as wedged and
    # fall back to the proxy. 150 s gives two patient attempts inside the
    # default 300 s window.
    single = float(os.environ.get("BENCH_PROBE_ATTEMPT_S", "150"))
    deadline = time.monotonic() + budget_s
    attempt = 0
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return False
        attempt += 1
        t0 = time.time()
        if _probe_device(min(single, max(15.0, remaining))):
            if attempt > 1:
                log("device answered on probe attempt {}".format(attempt))
            return True
        log("device probe attempt {} failed after {:.0f}s; remediating".format(
            attempt, time.time() - t0))
        _remediate_device()
        # A hung probe consumed its full timeout already; only sleep when
        # the probe failed fast (plugin error), to avoid hammering.
        if time.time() - t0 < 10:
            time.sleep(min(30.0, max(0.0, deadline - time.monotonic())))


def main():
    """Orchestrator. Never imports jax in this process — every measurement
    runs in a killable child, so no code path here can hold (or leak) a
    device claim. Order of output lines on stdout:

    1. the headline JSON (sweep + baselines, no extras) — printed BEFORE
       any extra bench runs, so a misbehaving extra cannot cost the
       already-measured number;
    2. the final enriched JSON (same headline values + extras in detail).

    A consumer taking either the first or the last JSON line gets the same
    headline numbers."""
    # Share one base dir + compile cache across children. When WE mint the
    # tmpdir, remove it at exit: its absence is the signal a later run's
    # orphan remediation uses to tell "that run is over, kill its
    # leftovers" from "live concurrent run, hands off" (see
    # _is_killable_orphan_marker).
    if "MAGGY_TPU_BASE_DIR" not in os.environ:
        import atexit
        import shutil

        base = _mint_base_dir()
        os.environ["MAGGY_TPU_BASE_DIR"] = base
        atexit.register(shutil.rmtree, base, True)

    # A CPU-pinned invocation (JAX_PLATFORMS=cpu rehearsal) must not let the
    # children's sitecustomize dial the accelerator tunnel at interpreter
    # startup — that can hang before any in-process guard runs.
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        for var in _ACCEL_BOOTSTRAP_VARS:
            os.environ.pop(var, None)

    cpu_fallback = False
    if not _probe_device_with_retry(
            float(os.environ.get("BENCH_DEVICE_PROBE_S", "300"))):
        if os.environ.get("BENCH_CPU_FALLBACK", "1") != "1":
            print(json.dumps(_failure_artifact(
                "device unavailable: jax.devices() did not return within the "
                "probe budget (multiple probe+remediate attempts)")), flush=True)
            return 1
        # The accelerator never answered: measure the framework on CPU
        # rather than emit an information-free 0.0. The artifact says so
        # loudly — a proxy number is comparable (both sides of vs_baseline
        # run on the same substrate) but it is NOT an on-chip result.
        log("device unavailable after full probe window; falling back to "
            "a CPU-proxy headline (detail.platform marks it)")
        cpu_fallback = True
        os.environ["JAX_PLATFORMS"] = "cpu"
        # CRITICAL: also drop the accelerator-bootstrap env vars, or the
        # children's sitecustomize dials the wedged tunnel at interpreter
        # startup — before their JAX_PLATFORMS=cpu guard can run — and the
        # fallback hangs in exactly the scenario it exists for.
        for var in _ACCEL_BOOTSTRAP_VARS:
            os.environ.pop(var, None)
        os.environ.setdefault("BENCH_SKIP_EXTRAS", "1")

    status, headline = _run_child(
        ["--headline"], float(os.environ.get("BENCH_HEADLINE_TIMEOUT_S", "2400")))
    if status == "timeout":
        print(json.dumps(_failure_artifact(
            "headline child timed out and was killed")), flush=True)
        return 1
    if headline is None or "metric" not in headline:
        detail = "headline child crashed without emitting JSON"
        if isinstance(headline, dict) and headline.get("stderr_tail"):
            detail += ": " + headline["stderr_tail"][-500:]
        print(json.dumps(_failure_artifact(detail)), flush=True)
        return 1
    if cpu_fallback:
        headline.setdefault("detail", {})["platform"] = (
            "cpu PROXY FALLBACK — TPU unavailable for the whole probe "
            "window; both sweep and baselines ran on host CPU")
    # Print the headline IMMEDIATELY — before extras can touch the device.
    print(json.dumps(headline), flush=True)
    if status == "crash" or headline.get("value", 0) == 0:
        return 1

    extras = run_extra_benches()
    if extras:
        enriched = dict(headline)
        enriched["detail"] = {**headline.get("detail", {}), **extras}
        print(json.dumps(enriched), flush=True)
    return 0


def run_extra_benches():
    """MFU + kernel measurements, each in its own killable subprocess so a
    compile stall or wedged device op can neither abort this process nor
    leak a device claim. After a timeout, a fresh-process probe decides
    whether the chip survived; remaining extras are skipped if not."""
    extras = {}
    if os.environ.get("BENCH_SKIP_EXTRAS") == "1":
        return extras
    names = [n.strip() for n in os.environ.get(
        "BENCH_EXTRAS", "llama,bert,flash_vs_xla").split(",") if n.strip()]
    budget_s = float(os.environ.get("BENCH_EXTRA_TIMEOUT_S", "420"))
    total_s = float(os.environ.get("BENCH_EXTRA_TOTAL_S", "900"))
    started = time.time()
    device_ok = True
    for name in names:
        if name not in EXTRA_BENCHES and name != "hang":
            extras[name] = {"error": "unknown extra (valid: {})".format(
                ",".join(EXTRA_BENCHES))}
            continue
        if not device_ok:
            extras[name] = {"error": "skipped: device did not recover after "
                                     "a previous extra was killed"}
            continue
        remaining = total_s - (time.time() - started)
        if remaining <= 5:
            extras[name] = {"error": "skipped: extras total budget spent"}
            log("{} bench skipped (total extras budget {}s spent)".format(
                name, total_s))
            continue
        t0 = time.time()
        status, payload = _run_child(["--extra", name], min(budget_s, remaining))
        if status == "ok":
            extras[name] = payload
            log("{} bench done in {:.1f}s: {}".format(
                name, time.time() - t0, payload))
        elif status == "timeout":
            extras[name] = {"error": "timeout: killed after {:.0f}s".format(
                time.time() - t0)}
            log("{} bench TIMED OUT and was killed; probing device".format(name))
            device_ok = _probe_device(
                float(os.environ.get("BENCH_POSTKILL_PROBE_S", "120")))
            log("post-kill device probe: {}".format(
                "ok" if device_ok else "FAILED — skipping remaining extras"))
        else:
            tail = (payload or {}).get("stderr_tail", "")
            extras[name] = {"error": "crashed: {}".format(tail[-500:] or payload)}
            log("{} bench CRASHED: {}".format(name, tail[-1000:] or payload))
    return extras


if __name__ == "__main__":
    if "--headline" in sys.argv:
        sys.exit(headline_main())
    if "--extra" in sys.argv:
        sys.exit(extra_main(sys.argv[sys.argv.index("--extra") + 1]))
    if "--chaos" in sys.argv:
        sys.exit(chaos_main())
    if "--failover" in sys.argv:
        sys.exit(failover_main())
    if "--fork" in sys.argv:
        sys.exit(fork_main())
    if "--vmap" in sys.argv:
        sys.exit(vmap_main())
    if "--goodput" in sys.argv:
        sys.exit(goodput_main())
    if "--fleet" in sys.argv:
        sys.exit(fleet_main())
    if "--pack" in sys.argv:
        sys.exit(pack_main())
    if "--obs" in sys.argv:
        sys.exit(obs_main())
    if "--scale" in sys.argv:
        sys.exit(scale_main())
    sys.exit(main())
