"""Benchmark: ASHA trials/hour through the full framework stack on one chip.

The BASELINE metric (BASELINE.md / BASELINE.json): the reference publishes no
numbers, so the comparison point is a SEQUENTIAL baseline — the same ASHA
schedule executed trial-by-trial with no async scheduling — mirroring what
the reference's Spark-stage-based alternative would do (its whole pitch is
overlapping trials on long-lived executors, `README.rst:21-26`).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

import numpy as np


def make_data(n=2048, key=0):
    rng = np.random.default_rng(key)
    X = rng.normal(size=(n, 16, 16, 1)).astype(np.float32)
    y = (X.mean(axis=(1, 2, 3)) > 0).astype(np.int32)
    return X, y


DATA_X, DATA_Y = make_data()
STEPS_PER_BUDGET = 25
BATCH = 256


def _bench_loss(logits, batch):
    from maggy_tpu.train import cross_entropy_loss

    return cross_entropy_loss(logits, batch["labels"])


def train_mnist(lr, budget=1, reporter=None):
    """One ASHA trial: budget-scaled training of the MNIST CNN. Shapes are
    hparam-independent so XLA's compile cache amortizes across trials."""
    import jax
    import jax.numpy as jnp
    import optax

    from maggy_tpu.models import MnistCNN
    from maggy_tpu.train import (ShardedBatchIterator, Trainer,
                                 cross_entropy_loss, swept_transform)
    from maggy_tpu.parallel import make_mesh

    mesh = make_mesh({"data": 1}, devices=jax.devices()[:1])
    model = MnistCNN(kernel_size=3, pool_size=2, features=16, num_classes=2)
    # lr rides in opt_state (swept_transform) and the step is shared via
    # step_key: the whole sweep compiles its train step ONCE.
    trainer = Trainer(
        model, swept_transform(optax.adam, learning_rate=lr),
        _bench_loss, mesh, strategy="dp", step_key=("bench_mnist", "adam"),
    )
    trainer.init(jax.random.key(0), (jnp.zeros((1, 16, 16, 1)),))
    steps = int(STEPS_PER_BUDGET * budget)
    it = iter(ShardedBatchIterator({"x": DATA_X, "y": DATA_Y}, batch_size=BATCH,
                                   epochs=None, seed=1))
    loss = None
    for i in range(steps):
        b = next(it)
        loss = trainer.step(trainer.place_batch(
            {"inputs": (jnp.asarray(b["x"]),), "labels": jnp.asarray(b["y"])}))
        if reporter is not None and i % 2 == 0:
            # Maps step onto the shared [0, max-budget] resource axis so the
            # median rule compares trials at equal progress. The metric is
            # passed as a LAZY device scalar — the reporter materializes it
            # on the heartbeat thread, so the step stream stays pipelined
            # (a blocking float() here costs ~50 ms/sync over the tunnel).
            reporter.broadcast(-loss, step=i)
    return {"metric": -float(loss)}


def run_framework_sweep(num_trials=18, workers=3):
    from maggy_tpu import OptimizationConfig, Searchspace, experiment
    from maggy_tpu.optimizers import Asha

    sp = Searchspace(lr=("DOUBLE", [1e-4, 3e-2]))
    # ASHA multi-fidelity schedule + median-rule mid-trial early stopping:
    # the two async control loops the reference pitches against stage-based
    # execution (`README.rst:21-26`). The wave baseline below runs the SAME
    # trials without them — a stage scheduler cannot stop a running trial.
    config = OptimizationConfig(
        name="bench_asha", num_trials=num_trials,
        optimizer=Asha(reduction_factor=3, resource_min=1, resource_max=9, seed=0),
        searchspace=sp, direction="max", num_workers=workers,
        hb_interval=0.1, es_policy="median", es_interval=2, es_min=3, seed=0,
    )
    t0 = time.time()
    result = experiment.lagom(train_mnist, config)
    wall = time.time() - t0
    return result, wall


def run_wave_baseline(schedule, workers=3):
    """The same (lr, budget) runs executed in SYNCHRONIZED WAVES of
    ``workers`` — stage-based execution, the Spark-native alternative the
    reference positions itself against (`README.rst:21-26`): every wave
    waits for its slowest trial before the next batch starts, so mixed ASHA
    budgets (1x/3x/9x) leave workers idle on stragglers. Device parallelism
    is identical to the framework run; only the scheduling differs."""
    import threading

    errors = []

    def run(lr, budget):
        try:
            train_mnist(lr, budget=budget)
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    t0 = time.time()
    for i in range(0, len(schedule), workers):
        wave = schedule[i:i + workers]
        threads = [threading.Thread(target=run, args=(lr, budget))
                   for lr, budget in wave]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    if errors:
        # A failed baseline trial would silently shrink the measurement.
        raise errors[0]
    return time.time() - t0


def log(msg):
    print("[bench] {}".format(msg), file=sys.stderr, flush=True)


def main():
    os.environ.setdefault("MAGGY_TPU_BASE_DIR", tempfile.mkdtemp(prefix="bench_"))
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # Env vars alone lose to an already-imported TPU plugin
        # (sitecustomize); force the live config like __graft_entry__ does.
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:  # noqa: BLE001
            pass
    from maggy_tpu.util import enable_compile_cache

    enable_compile_cache()
    import jax

    log("devices: {}".format(jax.devices()))

    # Warm-up: compile the two step shapes once so both measurements see a
    # warm cache (the persistent compilation cache does this across runs).
    t0 = time.time()
    train_mnist(1e-3, budget=1)
    log("warm-up done in {:.1f}s".format(time.time() - t0))

    result, wall = run_framework_sweep()
    n_runs = result["num_trials"]
    trials_per_hour = n_runs / wall * 3600
    log("framework sweep: {} trials in {:.1f}s ({} early-stopped, best={})".format(
        n_runs, wall, result.get("early_stopped"), result.get("best_val")))

    # Stage-based baseline over the EXACT schedule the sweep executed (same
    # trials, same budgets, same worker parallelism — only wave-synchronized
    # scheduling instead of async).
    import glob, json as _json

    exp_dirs = sorted(glob.glob(os.path.join(
        os.environ["MAGGY_TPU_BASE_DIR"], "*")))
    schedule = []
    for td in glob.glob(os.path.join(exp_dirs[-1], "*", "trial.json")):
        with open(td) as f:
            t = _json.load(f)
        schedule.append((t.get("start") or 0,
                         t["params"]["lr"], t["params"].get("budget", 1)))
    # Submission order (start timestamps): the order ASHA produced — rung-0
    # first, promotions late — is what a stage scheduler would see.
    schedule = [(lr, b) for _, lr, b in sorted(schedule)]
    seq_wall = run_wave_baseline(schedule)
    seq_trials_per_hour = len(schedule) / seq_wall * 3600
    log("wave baseline: {} trials in {:.1f}s".format(len(schedule), seq_wall))

    print(json.dumps({
        "metric": "ASHA trials/hour (MNIST CNN sweep, 1 chip, 3 concurrent runners)",
        "value": round(trials_per_hour, 1),
        "unit": "trials/hour",
        "vs_baseline": round(trials_per_hour / seq_trials_per_hour, 3),
        "detail": {
            "framework_wall_s": round(wall, 1),
            "stage_based_baseline_wall_s": round(seq_wall, 1),
            "trials": n_runs,
            "early_stopped": result.get("early_stopped", 0),
        },
    }))


if __name__ == "__main__":
    sys.exit(main())
