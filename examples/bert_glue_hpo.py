"""BERT GLUE-style fine-tune HPO (BASELINE.md config 4): TPE search over
(lr, warmup, batch) with the tiny config; swap `BertConfig.base()` + a real
GLUE task on a 4-chip slice.

Run: python examples/bert_glue_hpo.py [--trials 8]
"""

from __future__ import annotations

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.join(_os.path.dirname(_os.path.abspath(__file__)), ".."))


import argparse

from maggy_tpu.util import apply_platform_env

apply_platform_env()  # honor JAX_PLATFORMS even if a TPU plugin pre-registered

import jax
import jax.numpy as jnp
import numpy as np
import optax

from maggy_tpu import OptimizationConfig, Searchspace, experiment
from maggy_tpu.models import BertConfig, BertEncoder
from maggy_tpu.parallel import make_mesh
from maggy_tpu.train import Trainer, cross_entropy_loss

VOCAB = 128


def make_sst_like(n=512, seq=32, seed=0):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(2, VOCAB, size=(n, seq)).astype(np.int32)
    # sentiment = whether "positive tokens" (upper half) dominate
    y = (tokens > VOCAB // 2).mean(axis=1) > 0.5
    return tokens, y.astype(np.int32)


TOKENS, LABELS = make_sst_like()


def train_fn(lr, warmup_frac, batch, reporter=None):
    n_dev = len(jax.devices())
    mesh = make_mesh({"data": 1}, devices=jax.devices()[:1])
    cfg = BertConfig.tiny(num_classes=2)
    model = BertEncoder(cfg)
    total_steps = 40
    sched = optax.warmup_cosine_decay_schedule(
        0.0, lr, int(total_steps * warmup_frac), total_steps)
    trainer = Trainer(
        model, optax.adamw(sched),
        lambda logits, b: cross_entropy_loss(logits, b["labels"]),
        mesh,
    )
    trainer.init(jax.random.key(0), (jnp.ones((1, 32), jnp.int32),))
    loss = None
    for i in range(total_steps):
        lo = (i * batch) % (len(TOKENS) - batch)
        tb = jnp.asarray(TOKENS[lo:lo + batch])
        yb = jnp.asarray(LABELS[lo:lo + batch])
        loss = trainer.step(trainer.place_batch(
            {"inputs": (tb,), "labels": yb}))
        if reporter is not None and i % 10 == 0:
            reporter.broadcast(-float(loss), step=i)
    preds = jnp.argmax(model.apply(trainer.variables,
                                   jnp.asarray(TOKENS[:256])), -1)
    acc = float(jnp.mean(preds == jnp.asarray(LABELS[:256])))
    return {"metric": acc, "final_loss": float(loss)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--trials", type=int, default=8)
    args = ap.parse_args()

    sp = Searchspace(
        lr=("DOUBLE", [1e-5, 1e-3]),
        warmup_frac=("DOUBLE", [0.0, 0.3]),
        batch=("DISCRETE", [32, 64]),
    )
    config = OptimizationConfig(
        name="bert_glue_hpo", num_trials=args.trials, optimizer="tpe",
        searchspace=sp, direction="max", num_workers=2,
        es_policy="median", es_min=3, seed=0,
    )
    result = experiment.lagom(train_fn, config)
    print("Best accuracy:", result["best_val"], "with", result["best_hp"])


if __name__ == "__main__":
    main()
