"""Distributed data-parallel training — the reference's torch-dist example
(`examples/` notebook 2), TPU-native: the train function gets a ShardingEnv
instead of a DDP-wrapped model; GSPMD inserts the gradient all-reduce.

Run: python examples/distributed_training.py           (single process, all chips)
     JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
         python examples/distributed_training.py       (8 virtual devices)
"""

from __future__ import annotations

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.join(_os.path.dirname(_os.path.abspath(__file__)), ".."))


from maggy_tpu.util import apply_platform_env

apply_platform_env()  # honor JAX_PLATFORMS even if a TPU plugin pre-registered

import jax
import jax.numpy as jnp
import numpy as np
import optax

from maggy_tpu import DistributedConfig, experiment
from maggy_tpu.models import ResNet
from maggy_tpu.train import ShardedBatchIterator, cross_entropy_loss
from maggy_tpu.train.trainer import init_train_state, make_train_step


def train_fn(sharding_env, reporter=None):
    rng = np.random.default_rng(0)
    X = rng.normal(size=(512, 32, 32, 3)).astype(np.float32)
    y = (X.mean(axis=(1, 2, 3)) > 0).astype(np.int32)

    model = ResNet(depth=18, num_classes=2, width=16)
    tx = optax.sgd(0.05, momentum=0.9)
    variables, opt_state, _ = init_train_state(
        model, tx, jax.random.key(0), (jnp.zeros((1, 32, 32, 3)),),
        sharding_env.mesh, strategy="dp",
        init_kwargs={"train": True},
    )
    step = make_train_step(
        model, tx,
        lambda out, batch: cross_entropy_loss(out, batch["labels"]),
        sharding_env.mesh, has_aux_collections=True,
        train_kwargs={"train": True},
    )
    # Input sharded by this process's rank (patching.py:70-79 semantics),
    # then across local devices via the mesh.
    it = ShardedBatchIterator(
        {"x": X, "y": y}, batch_size=128,
        shard_count=sharding_env.shard_count,
        current_shard=sharding_env.current_shard,
        epochs=4, seed=1, mesh=sharding_env.mesh,
    )
    loss = None
    for i, b in enumerate(it):
        variables, opt_state, loss = step(
            variables, opt_state,
            {"inputs": (b["x"],), "labels": b["y"]})
        if reporter is not None and i % 4 == 0:
            reporter.broadcast(float(loss), step=i)
    return {"metric": float(loss)}


def main():
    config = DistributedConfig(
        name="resnet_dp", num_workers=1,
        mesh_shape={"data": len(jax.devices())},
    )
    result = experiment.lagom(train_fn, config)
    print("Average final loss across workers:", result["average_metric"])


if __name__ == "__main__":
    main()
