"""Llama LoRA hyperparameter sweep (BASELINE.md config 5) — ASHA over
(lora_rank, lora_alpha, lr) with per-trial FSDP sharding.

Uses the tiny config by default so it runs anywhere; switch to
`LlamaConfig.llama3_8b(...)` on a v4-32 with a real corpus.

Run: python examples/llama_lora_sweep.py [--trials 9]
"""

from __future__ import annotations

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.join(_os.path.dirname(_os.path.abspath(__file__)), ".."))


import argparse

from maggy_tpu.util import apply_platform_env

apply_platform_env()  # honor JAX_PLATFORMS even if a TPU plugin pre-registered

import jax
import jax.numpy as jnp
import numpy as np
import optax

from maggy_tpu import OptimizationConfig, Searchspace, experiment
from maggy_tpu.models import Llama, LlamaConfig
from maggy_tpu.ops.losses import chunked_next_token_loss
from maggy_tpu.optimizers import Asha
from maggy_tpu.parallel import make_mesh
from maggy_tpu.train import Trainer
from maggy_tpu.train.lora import only_lora

VOCAB = 256


def make_corpus(n=256, seq=64, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, VOCAB, size=(n, seq)).astype(np.int32)


CORPUS = make_corpus()


def train_fn(lora_rank, lora_alpha, lr, budget=1, reporter=None):
    n_dev = len(jax.devices())
    axes = {"fsdp": n_dev} if n_dev > 1 else {"data": 1}
    mesh = make_mesh(axes)
    cfg = LlamaConfig.tiny(vocab_size=VOCAB, lora_rank=int(lora_rank))
    cfg = LlamaConfig(**{**cfg.__dict__, "lora_alpha": float(lora_alpha)})
    model = Llama(cfg)
    # The flagship recipe: the 8B base stays FROZEN (only_lora masks the
    # optimizer to the adapters — no moments for 8B of weights) and the
    # loss is computed vocab-chunked from pre-head activations, never
    # materializing the [B, S, 128k] logits (ops/losses.py).
    trainer = Trainer(
        model, only_lora(optax.adamw(lr)),
        lambda out, batch: chunked_next_token_loss(
            out[0], out[1], batch["tokens"], vocab_chunk=128),
        mesh, strategy="fsdp" if n_dev > 1 else "dp",
        train_kwargs={"return_hidden": True},
    )
    trainer.init(jax.random.key(0), (jnp.ones((1, 16), jnp.int32),))
    steps = int(20 * budget)
    loss = None
    for i in range(steps):
        batch_tokens = jnp.asarray(CORPUS[(i * 16) % 240:(i * 16) % 240 + 16])
        loss = trainer.step(trainer.place_batch(
            {"inputs": (batch_tokens,), "tokens": batch_tokens}))
        if reporter is not None and i % 5 == 0:
            reporter.broadcast(-float(loss), step=i)
    return {"metric": -float(loss), "final_loss": float(loss)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--trials", type=int, default=9)
    ap.add_argument("--resource-max", type=float, default=9,
                    help="ASHA top-rung budget (1 = single rung, e.g. for "
                         "smoke runs with few trials)")
    args = ap.parse_args()

    sp = Searchspace(
        lora_rank=("DISCRETE", [4, 8, 16]),
        lora_alpha=("DOUBLE", [4.0, 32.0]),
        lr=("DOUBLE", [1e-4, 3e-3]),
    )
    config = OptimizationConfig(
        name="llama_lora_sweep", num_trials=args.trials,
        optimizer=Asha(reduction_factor=3, resource_min=1,
                       resource_max=args.resource_max, seed=0),
        searchspace=sp, direction="max", num_workers=3, es_policy="none",
        seed=0,
    )
    result = experiment.lagom(train_fn, config)
    print("Best:", result["best_val"], "with", result["best_hp"])


if __name__ == "__main__":
    main()
