"""MNIST CNN random-search HPO — the reference's README example
(`README.rst:56-84`), TPU-native.

Run: python examples/mnist_hpo.py [--trials 8] [--workers 4]
"""

from __future__ import annotations

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.join(_os.path.dirname(_os.path.abspath(__file__)), ".."))


import argparse

from maggy_tpu.util import apply_platform_env

apply_platform_env()  # honor JAX_PLATFORMS even if a TPU plugin pre-registered

import jax
import jax.numpy as jnp
import numpy as np
import optax

from maggy_tpu import OptimizationConfig, Searchspace, experiment
from maggy_tpu.models import MnistCNN
from maggy_tpu.parallel import make_mesh
from maggy_tpu.train import (ShardedBatchIterator, Trainer,
                             cross_entropy_loss, swept_transform)


def make_mnist_like(n=4096, seed=0):
    """Synthetic MNIST stand-in (the image ships no datasets; swap in real
    MNIST arrays if you have them on disk)."""
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 28, 28, 1)).astype(np.float32)
    y = ((X[:, :14].mean(axis=(1, 2, 3)) > X[:, 14:].mean(axis=(1, 2, 3)))
         .astype(np.int32))
    return X, y


X_TRAIN, Y_TRAIN = make_mnist_like()


def loss_fn(logits, batch):
    """Module-level (not a per-trial lambda) so the warm cache's automatic
    program key matches across trials — see docs/user.md "Compile-once
    sweeps"."""
    return cross_entropy_loss(logits, batch["labels"])


def train_fn(kernel, pool, dropout, lr, reporter=None):
    """One trial: train the CNN, heartbeat val accuracy, return final acc.

    Compile-once: lr rides in opt_state (swept_transform), so trials that
    share (kernel, pool, dropout) — the hparams that change the PROGRAM —
    reuse the runner's warm-compiled step; only distinct model configs
    recompile (bounded by the warm cache's LRU)."""
    mesh = make_mesh({"data": 1}, devices=jax.devices()[:1])
    model = MnistCNN(kernel_size=kernel, pool_size=pool, dropout=dropout,
                     num_classes=2)
    trainer = Trainer(
        model, swept_transform(optax.adam, learning_rate=lr),
        loss_fn, mesh,
    )
    trainer.init(jax.random.key(0), (jnp.zeros((1, 28, 28, 1)),))
    it = ShardedBatchIterator({"x": X_TRAIN, "y": Y_TRAIN}, batch_size=256,
                              epochs=2, seed=1)
    acc = 0.0
    for step, b in enumerate(it):
        loss = trainer.step(trainer.place_batch(
            {"inputs": (jnp.asarray(b["x"]),), "labels": jnp.asarray(b["y"])}))
        if reporter is not None and step % 5 == 0:
            reporter.broadcast(-float(loss), step=step)
    return {"metric": -float(loss), "final_loss": float(loss)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--trials", type=int, default=8)
    ap.add_argument("--workers", type=int, default=4)
    args = ap.parse_args()

    sp = Searchspace(
        kernel=("DISCRETE", [3, 5]),
        pool=("DISCRETE", [2, 3]),
        dropout=("DOUBLE", [0.0, 0.5]),
        lr=("DOUBLE", [1e-4, 1e-2]),
    )
    config = OptimizationConfig(
        name="mnist_hpo", num_trials=args.trials, optimizer="randomsearch",
        searchspace=sp, direction="max", num_workers=args.workers,
        es_policy="median", es_min=3, seed=0,
    )
    result = experiment.lagom(train_fn, config)
    print("Best:", result["best_val"], "with", result["best_hp"])


if __name__ == "__main__":
    main()
