"""Population Based Training over (lr, weight decay) on the MNIST CNN.

Each member trains in budgeted segments; between segments the weakest
members clone the strongest member's WEIGHTS (orbax checkpoint via
`ctx.restore_parent`) and adopt its hyperparameters with a perturbation —
so the learning-rate schedule is discovered during the run instead of
fixed up front (arXiv:1711.09846). Fully async on the trial driver: no
generation barrier, a member's next segment starts the moment its
previous one finalizes.

Run: python examples/pbt_sweep.py [--population 6 --generations 4]
"""

from __future__ import annotations

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.join(_os.path.dirname(_os.path.abspath(__file__)), ".."))


import argparse

from maggy_tpu.util import apply_platform_env

apply_platform_env()  # honor JAX_PLATFORMS even if a TPU plugin pre-registered

import jax
import jax.numpy as jnp
import numpy as np
import optax

from maggy_tpu import OptimizationConfig, Searchspace, experiment
from maggy_tpu.models import MnistCNN
from maggy_tpu.optimizers import PBT
from maggy_tpu.parallel import make_mesh
from maggy_tpu.train import ShardedBatchIterator, Trainer, cross_entropy_loss


def make_data(n=512, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 16, 16, 1)).astype(np.float32)
    y = (X.mean(axis=(1, 2, 3)) > 0).astype(np.int32)
    return X, y


DATA_X, DATA_Y = make_data()
STEPS_PER_SEGMENT = 15


def loss_fn(logits, batch):
    return cross_entropy_loss(logits, batch["labels"])


def train_fn(lr, wd, generation, member, budget=1, ctx=None, reporter=None):
    mesh = make_mesh({"data": 1}, devices=jax.devices()[:1])
    model = MnistCNN(kernel_size=3, pool_size=2, features=8, num_classes=2)
    trainer = Trainer(model, optax.adamw(lr, weight_decay=wd), loss_fn, mesh,
                      strategy="dp")
    trainer.init(jax.random.key(member), (jnp.zeros((1, 16, 16, 1)),))

    # Exploit/continue: resume this lineage's weights. A fresh gen-0 member
    # starts from its own init.
    if ctx is not None and ctx.parent_trial_id is not None:
        restored = ctx.restore_parent(
            jax.tree_util.tree_map(np.asarray, trainer.variables))
        if restored is not None:
            trainer.variables = jax.tree_util.tree_map(
                jnp.asarray, restored)

    it = iter(ShardedBatchIterator({"x": DATA_X, "y": DATA_Y},
                                   batch_size=64, epochs=None, seed=member))
    loss = None
    for i in range(int(STEPS_PER_SEGMENT * budget)):
        b = next(it)
        loss = trainer.step(trainer.place_batch(
            {"inputs": (jnp.asarray(b["x"]),), "labels": jnp.asarray(b["y"])}))
        if reporter is not None and i % 5 == 0:
            reporter.broadcast(-loss, step=i)
    if ctx is not None:
        ctx.save_checkpoint(
            generation, jax.tree_util.tree_map(np.asarray, trainer.variables))
    return {"metric": -float(loss)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--population", type=int, default=6)
    ap.add_argument("--generations", type=int, default=4)
    ap.add_argument("--workers", type=int, default=3)
    args = ap.parse_args()

    sp = Searchspace(lr=("DOUBLE", [1e-4, 3e-2]), wd=("DOUBLE", [0.0, 0.1]))
    opt = PBT(population=args.population, generations=args.generations, seed=0)
    config = OptimizationConfig(
        name="pbt_sweep", num_trials=opt.schedule_size(), optimizer=opt,
        searchspace=sp, direction="max", num_workers=args.workers,
        es_policy="none", seed=0,
    )
    result = experiment.lagom(train_fn, config)
    print("Best:", result["best_val"], "with", result["best_hp"])


if __name__ == "__main__":
    main()
