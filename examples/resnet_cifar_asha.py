"""ResNet/CIFAR-10 ASHA sweep (BASELINE.md config 3: the reference's
torch-distributed example, TPU-native as a data-parallel JAX sweep).

Budget-scaled training epochs are ASHA's fidelity axis; lr / width /
weight-decay are swept. Depth 18 with small widths by default so the
example runs on CPU CI; on a chip, pass --depth 50 (widths are swept
hyperparameters — widen the DISCRETE choices in `main`) and feed real
CIFAR arrays.

Run: python examples/resnet_cifar_asha.py [--trials 9] [--resource-max 9]
                                          [--depth 50]
"""

from __future__ import annotations

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.join(_os.path.dirname(_os.path.abspath(__file__)), ".."))


import argparse

from maggy_tpu.util import apply_platform_env

apply_platform_env()  # honor JAX_PLATFORMS even if a TPU plugin pre-registered

import jax
import jax.numpy as jnp
import numpy as np
import optax

from maggy_tpu import OptimizationConfig, Searchspace, experiment
from maggy_tpu.models import ResNet
from maggy_tpu.optimizers import Asha
from maggy_tpu.parallel import make_mesh
from maggy_tpu.train import (ShardedBatchIterator, Trainer,
                             cross_entropy_loss, swept_transform)

DEPTH = 18  # overridden by --depth
STEPS_PER_BUDGET = 8


def make_cifar_like(n=1024, seed=0):
    """Synthetic CIFAR stand-in (the image ships no datasets; swap in real
    CIFAR-10 arrays if you have them on disk)."""
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 32, 32, 3)).astype(np.float32)
    y = (X.mean(axis=(1, 2, 3)) > 0).astype(np.int32)
    return X, y


X_TRAIN, Y_TRAIN = make_cifar_like()


def loss_fn(logits, batch):
    return cross_entropy_loss(logits, batch["labels"])


def train_fn(lr, width, weight_decay, budget=1, reporter=None):
    """One ASHA trial: budget-scaled ResNet training, data-parallel over
    every visible chip (GSPMD all-reduces gradients over ICI)."""
    mesh = make_mesh({"data": len(jax.devices())})
    model = ResNet(depth=DEPTH, num_classes=2, width=int(width))
    # lr/weight_decay ride in opt_state (swept_transform) and the loss is
    # module-level, so trials sharing a width reuse one warm-compiled
    # step; only distinct widths (a PROGRAM hparam) recompile.
    trainer = Trainer(
        model, swept_transform(optax.adamw, learning_rate=lr,
                               weight_decay=weight_decay),
        loss_fn, mesh, strategy="dp", has_aux_collections=True,
        train_kwargs={"train": True},
    )
    trainer.init(jax.random.key(0), (jnp.zeros((1, 32, 32, 3)),),
                 init_kwargs={"train": True})
    it = iter(ShardedBatchIterator({"x": X_TRAIN, "y": Y_TRAIN},
                                   batch_size=128, epochs=None, seed=1))
    loss = None
    for step in range(int(STEPS_PER_BUDGET * budget)):
        b = next(it)
        loss = trainer.step(trainer.place_batch(
            {"inputs": (jnp.asarray(b["x"]),), "labels": jnp.asarray(b["y"])}))
        if reporter is not None and step % 2 == 0:
            reporter.broadcast(-loss, step=step)  # lazy device scalar
    return {"metric": -float(loss), "final_loss": float(loss)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--trials", type=int, default=9)
    ap.add_argument("--workers", type=int, default=3)
    ap.add_argument("--resource-max", type=float, default=9,
                    help="ASHA top-rung budget (1 = single rung for smoke)")
    ap.add_argument("--depth", type=int, default=18, choices=[18, 50],
                    help="ResNet depth (50 for the full baseline config)")
    args = ap.parse_args()
    global DEPTH
    DEPTH = args.depth

    sp = Searchspace(
        lr=("DOUBLE", [1e-4, 1e-2]),
        width=("DISCRETE", [8, 16, 32]),
        weight_decay=("DOUBLE", [1e-5, 1e-3]),
    )
    config = OptimizationConfig(
        name="resnet_cifar_asha", num_trials=args.trials,
        optimizer=Asha(reduction_factor=3, resource_min=1,
                       resource_max=args.resource_max, seed=0),
        searchspace=sp, direction="max", num_workers=args.workers,
        es_policy="median", es_min=3, seed=0,
    )
    result = experiment.lagom(train_fn, config)
    print("Best:", result["best_val"], "with", result["best_hp"])


if __name__ == "__main__":
    main()
