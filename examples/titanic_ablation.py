"""Titanic-style tabular LOCO ablation study — the reference's ablation
example notebook, TPU-native with declarative specs.

The dataset rides in a ``.tfrecord`` file consumed through the study's
``train_set`` path — the same feature-store format + built-in
feature-dropping pipeline the reference's LOCO used
(reference ``loco.py:41-80``), with no TensorFlow import.

Run: python examples/titanic_ablation.py
"""

from __future__ import annotations

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.join(_os.path.dirname(_os.path.abspath(__file__)), ".."))


from maggy_tpu.util import apply_platform_env

apply_platform_env()  # honor JAX_PLATFORMS even if a TPU plugin pre-registered

import jax
import jax.numpy as jnp
import numpy as np
import optax

from maggy_tpu import AblationConfig, experiment
from maggy_tpu.ablation import AblationStudy
from maggy_tpu.models.surgery import ablatable_model_generator

FEATURES = ["pclass", "sex", "age", "fare", "embarked"]


def make_titanic_like(n=2048, seed=0):
    rng = np.random.default_rng(seed)
    X = {f: rng.normal(size=n).astype(np.float32) for f in FEATURES}
    logits = 1.5 * X["sex"] - 0.8 * X["pclass"] + 0.3 * X["fare"]
    y = (logits + 0.5 * rng.normal(size=n) > 0).astype(np.int32)
    return X, y


def write_dataset_tfrecord(path):
    """Persist the dataset as tf.train.Example records (one per row)."""
    from maggy_tpu.train.tfrecord import write_tfrecord

    X, y = make_titanic_like()
    write_tfrecord(path, (
        {**{f: float(X[f][i]) for f in FEATURES}, "survived": int(y[i])}
        for i in range(len(y))))


def model_layers():
    import flax.linen as nn

    return (
        ("input_dense", lambda: nn.Dense(32)),
        ("hidden_1", lambda: nn.Sequential([nn.Dense(32), nn.relu])),
        ("hidden_2", lambda: nn.Sequential([nn.Dense(32), nn.relu])),
        ("head", lambda: nn.Dense(2)),
    )


def model_generator(ablated_layers=frozenset()):
    return ablatable_model_generator(model_layers(), ablated_layers)


def train_fn(dataset_function, model_function, ablated_feature, ablated_layer,
             reporter=None):
    # dataset_function() is the built-in feature dropper over the study's
    # train_set tfrecord: a dict of per-feature arrays (minus the ablated
    # one) plus the label column.
    data = dataset_function()
    model = model_function()
    y = np.asarray(data.pop("survived"), dtype=np.int32)
    cols = sorted(data)
    X = jnp.asarray(np.stack([data[c] for c in cols], axis=1))
    y = jnp.asarray(y)
    params = model.init(jax.random.key(0), X[:1])
    tx = optax.adam(1e-2)
    opt = tx.init(params)

    @jax.jit
    def step(params, opt):
        def loss_fn(p):
            logits = model.apply(p, X)
            logp = jax.nn.log_softmax(logits)
            return -jnp.mean(logp[jnp.arange(len(y)), y])

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt = tx.update(grads, opt)
        return optax.apply_updates(params, updates), opt, loss

    for i in range(60):
        params, opt, loss = step(params, opt)
        if reporter is not None and i % 20 == 0:
            reporter.broadcast(-float(loss), step=i)
    acc = float(jnp.mean(jnp.argmax(model.apply(params, X), -1) == y))
    return {"metric": acc, "loss": float(loss),
            "ablated_feature": str(ablated_feature),
            "ablated_layer": str(ablated_layer)}


def main():
    import tempfile

    data_path = _os.path.join(tempfile.mkdtemp(prefix="titanic_"),
                              "titanic.tfrecord")
    write_dataset_tfrecord(data_path)
    # Publish the dataset under a name@version in the dataset registry —
    # the featurestore workflow: the study then addresses it by name only
    # (the reference resolved training_dataset_name/version through
    # Hopsworks, `loco.py:41-80`).
    from maggy_tpu.train import DatasetRegistry

    version = DatasetRegistry().register(
        "titanic", data_path, description="synthetic titanic-like tabular")
    study = AblationStudy("titanic", version, "survived")
    study.features.include(*FEATURES)
    study.model.set_base_model_generator(model_generator)
    study.model.layers.include("hidden_1", "hidden_2")
    study.model.layers.include_groups(prefix="hidden")

    config = AblationConfig(name="titanic_loco", ablation_study=study,
                            ablator="loco", direction="max", num_workers=3)
    result = experiment.lagom(train_fn, config)
    print("Trials:", result["num_trials"])
    print("Best (least-harmful ablation):", result["best_hp"],
          "->", result["best_val"])
    print("Worst (most important component):", result["worst_hp"],
          "->", result["worst_val"])


if __name__ == "__main__":
    main()
