"""ViT/CIFAR-10 TPE sweep: the Vision Transformer over the same tabular
HPO machinery as the ResNet example, with a Bayesian (TPE) optimizer.

lr / width / patch size are swept (all three actually change the trained
model — Trainer runs eval-mode apply, so a dropout hparam would be inert);
tiny dims by default so the example runs on CPU CI. On a chip, use
ViTConfig.base() and real CIFAR arrays.

Run: python examples/vit_cifar_hpo.py [--trials 8]
"""

from __future__ import annotations

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.join(_os.path.dirname(_os.path.abspath(__file__)), ".."))


import argparse

from maggy_tpu.util import apply_platform_env

apply_platform_env()  # honor JAX_PLATFORMS even if a TPU plugin pre-registered

import jax
import jax.numpy as jnp
import numpy as np
import optax

from maggy_tpu import OptimizationConfig, Searchspace, experiment
from maggy_tpu.models import ViT, ViTConfig
from maggy_tpu.parallel import make_mesh
from maggy_tpu.train import Trainer, cross_entropy_loss

STEPS = 12


def make_cifar_like(n=512, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 32, 32, 3)).astype(np.float32)
    y = (X.mean(axis=(1, 2, 3)) > 0).astype(np.int32)
    return X, y


X_TRAIN, Y_TRAIN = make_cifar_like()


def train_fn(lr, width, patch, reporter=None):
    # Every swept hparam here (width/patch — and lr via a fresh adamw)
    # changes the compiled program, so this sweep recompiles per config by
    # design; see docs/user.md "Compile-once sweeps" for the swept_transform
    # idiom when only optimizer hparams vary.
    cfg = ViTConfig(image_size=32, patch_size=int(patch), channels=3,
                    hidden_dim=int(width), intermediate_dim=2 * int(width),
                    num_layers=2, num_heads=2, num_classes=2)
    mesh = make_mesh({"data": 1}, devices=jax.devices()[:1])
    trainer = Trainer(
        ViT(cfg), optax.adamw(float(lr)),
        lambda logits, batch: cross_entropy_loss(logits, batch["labels"]),
        mesh, strategy="dp")
    x, y = jnp.asarray(X_TRAIN), jnp.asarray(Y_TRAIN)
    trainer.init(jax.random.key(0), (x[:1],))
    batch = trainer.place_batch({"inputs": (x,), "labels": y})
    loss = None
    for i in range(STEPS):
        loss = trainer.step(batch)
        if reporter is not None and i % 4 == 0:
            reporter.broadcast(-loss, step=i)
    return {"metric": -float(loss)}


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--trials", type=int, default=8)
    args = p.parse_args()
    sp = Searchspace(lr=("DOUBLE_LOG", [1e-4, 1e-2]),
                     width=("DISCRETE", [32, 48]),
                     patch=("DISCRETE", [4, 8]))
    config = OptimizationConfig(
        name="vit_cifar_tpe", num_trials=args.trials, optimizer="tpe",
        searchspace=sp, direction="max", num_workers=2, seed=0,
        es_policy="none")
    result = experiment.lagom(train_fn, config)
    print("Best:", result["best_hp"], "->", result["best_val"])


if __name__ == "__main__":
    main()
