"""maggy_tpu: TPU-native asynchronous black-box optimization framework.

A from-scratch JAX/XLA/pjit/Pallas re-design of the capabilities of
maggy (asynchronous hyperparameter optimization, ablation studies, and
distributed training): a driver process schedules asynchronous trials onto
per-trial JAX process groups pinned to TPU sub-slices; gradients flow over
ICI via XLA collectives; a DCN control plane streams heartbeat metrics back
to driver-side optimizers for early stopping and promotion.
"""

__version__ = "0.1.0"

from maggy_tpu.searchspace import Searchspace
from maggy_tpu.trial import Trial
from maggy_tpu.config import (
    LagomConfig,
    OptimizationConfig,
    AblationConfig,
    DistributedConfig,
)
from maggy_tpu.core.executors.context import TrialContext
from maggy_tpu.gang import GangSpec

__all__ = [
    "Searchspace",
    "Trial",
    "LagomConfig",
    "OptimizationConfig",
    "AblationConfig",
    "DistributedConfig",
    "TrialContext",
    "GangSpec",
]
