from maggy_tpu.ablation.ablationstudy import AblationStudy

__all__ = ["AblationStudy"]
