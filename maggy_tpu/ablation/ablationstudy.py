"""User-facing ablation-study specification.

Parity: reference `maggy/ablation/ablationstudy.py` — dataset spec + optional
custom dataset generator (:109-128,151-157), `Features` include/exclude set
(:160-225), `Model` with base/custom model generators (:228-250), `Layers`
include/exclude single layers, layer groups as frozensets, prefix groups
(:253-408), `to_dict` (:130-149).

Redesign: trials carry **declarative** ablation specs ({"ablated_feature":
..., "ablated_layer": ...}) instead of cloudpickled callables
(`loco.py:224-259`) — the executor resolves specs back through this study
object (SURVEY.md §7.3 "Serialization without cloudpickle"). Model surgery
targets Flax modules via `model_generator(ablated_layers=...)` or the
`maggy_tpu.models.surgery` helpers rather than Keras json editing.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, FrozenSet, List, Optional, Set


class Features:
    """Set of input features eligible for leave-one-out ablation."""

    def __init__(self):
        self.included_features: Set[str] = set()

    def include(self, *features: str) -> None:
        for f in self._flatten(features):
            if not isinstance(f, str):
                raise ValueError("Feature names must be strings, got {!r}".format(f))
            self.included_features.add(f)

    def exclude(self, *features: str) -> None:
        for f in self._flatten(features):
            self.included_features.discard(f)

    @staticmethod
    def _flatten(features):
        out = []
        for f in features:
            if isinstance(f, (list, tuple, set)):
                out.extend(f)
            else:
                out.append(f)
        return out

    def list_all(self) -> List[str]:
        return sorted(self.included_features)


class Layers:
    """Model components eligible for ablation: single layers, explicit
    groups, and prefix groups (all by layer NAME within the user's model)."""

    def __init__(self):
        self.included_layers: Set[str] = set()
        self.included_groups: Set[FrozenSet[str]] = set()

    def include(self, *layers: str) -> None:
        for l in Features._flatten(layers):
            if not isinstance(l, str):
                raise ValueError("Layer names must be strings, got {!r}".format(l))
            self.included_layers.add(l)

    def exclude(self, *layers: str) -> None:
        for l in Features._flatten(layers):
            self.included_layers.discard(l)

    def include_groups(self, *groups, prefix: Optional[str] = None) -> None:
        """Add layer groups ablated together; a prefix group ablates every
        layer whose name starts with ``prefix`` (reference
        `ablationstudy.py:300-360`)."""
        if prefix is not None:
            if not isinstance(prefix, str):
                raise ValueError("prefix must be a string")
            self.included_groups.add(frozenset([prefix]))
        for g in groups:
            if not isinstance(g, (list, set, tuple)) or len(g) < 2:
                raise ValueError(
                    "A layer group must be a list/set of >= 2 layer names; "
                    "use include() for single layers or prefix= for prefixes."
                )
            self.included_groups.add(frozenset(g))

    def exclude_groups(self, *groups, prefix: Optional[str] = None) -> None:
        if prefix is not None:
            self.included_groups.discard(frozenset([prefix]))
        for g in groups:
            self.included_groups.discard(frozenset(g))

    def list_all(self) -> List[Any]:
        singles = sorted(self.included_layers)
        groups = sorted(sorted(g) for g in self.included_groups)
        return singles + groups


class Model:
    """The model side of the study: a base generator plus named custom
    variants. Generators are looked up by name at execution time, so trials
    stay declarative."""

    def __init__(self):
        self.base_model_generator: Optional[Callable] = None
        self.custom_model_generators: Dict[str, Callable] = {}
        self.layers = Layers()

    def set_base_model_generator(self, generator: Callable) -> None:
        if not callable(generator):
            raise ValueError("base_model_generator must be callable")
        self.base_model_generator = generator

    def add_custom_model_generator(self, name: str, generator: Callable) -> None:
        if not callable(generator):
            raise ValueError("custom model generator must be callable")
        self.custom_model_generators[name] = generator


class AblationStudy:
    """Declarative spec of a leave-one-component-out study.

    ``dataset_generator(ablated_feature=None)`` must return the training
    data minus the ablated feature; ``model.base_model_generator
    (ablated_layers=frozenset())`` must return the model minus the ablated
    layers (use `maggy_tpu.models.surgery` for Flax Sequential surgery).
    """

    def __init__(
        self,
        training_dataset_name: str = "",
        training_dataset_version: int = 1,
        label_name: str = "",
        dataset_generator: Optional[Callable] = None,
        train_set: Any = None,
    ):
        self.name = training_dataset_name
        self.version = training_dataset_version
        self.label_name = label_name
        self.custom_dataset_generator = dataset_generator
        #: Built-in feature dropping (the local analogue of the reference's
        #: feature-store read, `loco.py:41-80`): a dict of arrays or a
        #: path (.npz/.parquet/parquet dir). When set and no custom
        #: generator is given, each trial's dataset_function returns this
        #: data minus the ablated feature.
        self.train_set = train_set
        self.features = Features()
        self.model = Model()

    def to_dict(self) -> Dict[str, Any]:
        return {
            "training_dataset_name": self.name,
            "training_dataset_version": self.version,
            "label_name": self.label_name,
            "included_features": self.features.list_all(),
            "included_layers": self.model.layers.list_all(),
            "custom_models": sorted(self.model.custom_model_generators),
            "has_custom_dataset_generator": self.custom_dataset_generator is not None,
        }
