"""Ablator plugin contract (reference `maggy/ablation/ablator/abstractablator.py:20-86`)."""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import List, Optional

from maggy_tpu.trial import Trial


class AbstractAblator(ABC):
    """Also satisfies the slice of the controller interface the
    OptimizationDriver drives (get_suggestion/_initialize/_strip_budget), so
    ablation studies reuse the whole HPO scheduling machinery (the reference
    does the same by subclassing the driver, `ablation_driver.py:108-109`)."""

    def __init__(self, ablation_study, final_store: Optional[List[Trial]] = None):
        self.ablation_study = ablation_study
        self.final_store = final_store if final_store is not None else []
        self.trial_buffer: List[Trial] = []
        self.pruner = None
        self.trial_store = {}
        self.searchspace = None
        self.num_trials = 0
        self.direction = "max"

    @abstractmethod
    def get_number_of_trials(self) -> int:
        ...

    @abstractmethod
    def initialize(self) -> None:
        """Fill the trial buffer with the full ablation schedule."""

    @abstractmethod
    def get_trial(self, last_trial: Optional[Trial] = None) -> Optional[Trial]:
        """Pop the next trial, or None when the study is complete."""

    def finalize_experiment(self, trials: List[Trial]) -> None:
        pass

    # ----------------------------------------------- controller-shim methods

    def _initialize(self, exp_dir: Optional[str] = None) -> None:
        self.initialize()

    def _finalize_experiment(self, trials: List[Trial]) -> None:
        self.finalize_experiment(trials)

    def get_suggestion(self, trial: Optional[Trial] = None) -> Optional[Trial]:
        return self.get_trial(trial)

    def init_pruner(self):
        return None

    @staticmethod
    def _strip_budget(params):
        return {k: v for k, v in params.items() if k != "budget"}
