"""LOCO — Leave One Component Out.

Parity: reference `maggy/ablation/ablator/loco.py` — schedule: 1 base trial +
one per included feature + per layer + per layer group + per custom model
(:31-39, :138-194); dataset generator dropping the ablated feature (:41-80);
model generator rebuilding the model minus the ablated layer(s)/group/prefix
(:82-136).

Redesign: trials carry declarative params {"ablated_feature", "ablated_layer",
"model_key"} — hashed by `Trial._compute_id` ablation rules — and the
executor-side resolver (`make_resolver`) maps them back to concrete
``dataset_function``/``model_function`` callables via the study object,
instead of shipping cloudpickled closures over the wire
(`loco.py:224-259`; SURVEY.md §7.3).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional

from maggy_tpu.ablation.ablator.abstractablator import AbstractAblator
from maggy_tpu.trial import Trial


class LOCO(AbstractAblator):
    def get_number_of_trials(self) -> int:
        study = self.ablation_study
        return (
            1
            + len(study.features.included_features)
            + len(study.model.layers.included_layers)
            + len(study.model.layers.included_groups)
            + len(study.model.custom_model_generators)
        )

    def initialize(self) -> None:
        study = self.ablation_study
        # Base trial: nothing ablated.
        self.trial_buffer.append(self._make_trial(None, None, "base"))
        for feature in sorted(study.features.included_features):
            self.trial_buffer.append(self._make_trial(feature, None, "base"))
        for layer in sorted(study.model.layers.included_layers):
            self.trial_buffer.append(self._make_trial(None, layer, "base"))
        for group in sorted(sorted(g) for g in study.model.layers.included_groups):
            self.trial_buffer.append(self._make_trial(None, list(group), "base"))
        for name in sorted(study.model.custom_model_generators):
            self.trial_buffer.append(self._make_trial(None, None, name))

    def _make_trial(self, feature, layer, model_key) -> Trial:
        params: Dict[str, Any] = {
            "ablated_feature": feature if feature is not None else "None",
            "ablated_layer": layer if layer is not None else "None",
            "model_key": model_key,
        }
        return Trial(params, trial_type="ablation")

    def get_trial(self, last_trial: Optional[Trial] = None) -> Optional[Trial]:
        return self.trial_buffer.pop(0) if self.trial_buffer else None

    # ------------------------------------------------- executor-side resolve

    def make_resolver(self):
        """Build the declarative-spec -> callables resolver the trial
        executor applies before invoking the user function."""
        return functools.partial(resolve_ablation_params, self.ablation_study)


def resolve_ablation_params(study, params: Dict[str, Any]) -> Dict[str, Any]:
    """Map {"ablated_feature", "ablated_layer", "model_key"} to concrete
    ``dataset_function`` / ``model_function`` callables.

    The user's train function signature is
    ``train_fn(dataset_function, model_function[, reporter])`` — the same
    shape the reference's executors call (`trial_executor.py:142-146`).
    """
    feature = params.get("ablated_feature", "None")
    layer = params.get("ablated_layer", "None")
    model_key = params.get("model_key", "base")
    feature = None if feature == "None" else feature
    layer = None if layer == "None" else layer

    if study.custom_dataset_generator is not None:
        dataset_function = functools.partial(
            study.custom_dataset_generator, ablated_feature=feature
        )
    else:
        dataset_function = functools.partial(
            default_dataset_generator, study, ablated_feature=feature
        )

    if model_key != "base":
        model_function = study.model.custom_model_generators[model_key]
    else:
        gen = study.model.base_model_generator
        if gen is None:
            raise ValueError("AblationStudy has no base_model_generator.")
        ablated = frozenset() if layer is None else (
            frozenset([layer]) if isinstance(layer, str) else frozenset(layer)
        )
        model_function = functools.partial(gen, ablated_layers=ablated)

    return {
        "dataset_function": dataset_function,
        "model_function": model_function,
        "ablated_feature": feature,
        "ablated_layer": layer,
    }


def default_dataset_generator(study, ablated_feature: Optional[str] = None):
    """Built-in feature dropping from the study's ``train_set`` (dict of
    arrays or an .npz/.parquet path) — the local analogue of the reference
    reading the feature store minus the ablated feature (`loco.py:41-80`)."""
    src = getattr(study, "train_set", None)
    if src is None and getattr(study, "name", ""):
        # The reference resolves (training_dataset_name, version) through
        # the feature store (`loco.py:41-80`); here the same pair resolves
        # through the dataset registry (train/registry.py) — but only if
        # the name is actually registered, so an unregistered study keeps
        # the actionable "no dataset source" error below.
        from maggy_tpu.train.registry import DatasetRegistry

        try:
            reg = DatasetRegistry()
            if study.version in reg.versions(study.name):
                src = "registry://{}@{}".format(study.name, study.version)
        except Exception:  # noqa: BLE001 - registry probe must not mask the error
            pass
    if src is None:
        raise ValueError(
            "No dataset source: pass train_set= (dict of arrays or a "
            "dataset path), training_dataset_name= registered in the "
            "dataset registry, or dataset_generator= to AblationStudy."
        )
    from maggy_tpu.train.data import feature_dropping_generator

    # Cache the generator (and its loaded-path data) per SOURCE, so
    # reassigning study.train_set between runs rebuilds instead of silently
    # serving the previous dataset.
    cached = study.__dict__.get("_feature_dropping_cache")
    if cached is None or cached[0] is not src:
        cached = (src, feature_dropping_generator(src))
        study.__dict__["_feature_dropping_cache"] = cached
    return cached[1](ablated_feature=ablated_feature)
