"""Static concurrency & protocol conformance analysis for maggy_tpu.

Four checkers over the package's AST plus one runtime harness — built
because every real concurrency bug PRs 2-6 shipped fixes for (the
retried-FINAL race, the GET-evict orphaned assignment, the experiment.py
re-entrancy, the run-id TOCTOU) was a lock-discipline or string-vocabulary
drift bug that only a chaos soak could catch *after* it existed:

- **guards** — guarded-by inference: which ``self._x`` attributes are
  written under ``with <lock>``, flagging accesses on paths that do not
  hold it. ``# guarded-by:`` / ``# locked-by:`` / ``# unguarded-ok:``
  annotations seed and silence the inference (docs/analysis.md).
- **lockorder** — the static acquired-while-holding graph across modules,
  cycle detection, and the canonical acquisition order (emitted into
  docs/analysis.md). Paired with the runtime **witness** (witness.py): an
  opt-in instrumented lock wrapper, env-gated like chaos
  (``MAGGY_TPU_LOCK_WITNESS=1``), that records actual acquisition edges
  and fails on any edge the static order forbids.
- **rpcconf** — RPC conformance: every verb in a server's ``_handlers``
  (and every driver ``message_callbacks`` verb) has a producer, and the
  payload keys a handler reads agree with the keys producers send
  (string-key drift is exactly how the retried-FINAL race hid).
- **journalvocab** — journal vocabulary conformance: every span
  phase/event-kind/reason literal emitted through ``telemetry`` appears
  in the shared consumer vocabulary (``telemetry/vocab.py``) consumed by
  replay/derive, trace, monitor and the chaos invariants — and vice
  versa, so an emitter typo can no longer silently vanish from replay,
  Perfetto, and invariant checking at once.

Run ``python -m maggy_tpu.analysis`` (exit 0 = no unsuppressed findings;
a tier-1 test enforces this on every commit). Pure AST: importing this
package never imports the code under analysis.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

from maggy_tpu.analysis.astindex import PackageIndex, parse_package

#: The four checker names, in report order.
CHECKERS = ("guards", "lockorder", "rpcconf", "journalvocab")


class Finding:
    """One analyzer finding, pointing at a file:line."""

    __slots__ = ("checker", "path", "line", "message", "suppressed", "reason")

    def __init__(self, checker: str, path: str, line: int, message: str,
                 suppressed: bool = False, reason: Optional[str] = None):
        self.checker = checker
        self.path = path
        self.line = int(line)
        self.message = message
        self.suppressed = suppressed
        self.reason = reason

    def to_dict(self) -> Dict[str, Any]:
        return {"checker": self.checker, "path": self.path,
                "line": self.line, "message": self.message,
                "suppressed": self.suppressed, "reason": self.reason}

    def __repr__(self):
        tag = " [suppressed: {}]".format(self.reason) if self.suppressed \
            else ""
        return "{}:{}: [{}] {}{}".format(self.path, self.line, self.checker,
                                         self.message, tag)


def package_root() -> str:
    """Filesystem root of the installed maggy_tpu package."""
    import maggy_tpu

    return os.path.dirname(os.path.abspath(maggy_tpu.__file__))


def analyze(index: PackageIndex,
            checkers=CHECKERS) -> Dict[str, List[Finding]]:
    """Run the selected checkers over a parsed index. Returns
    checker -> findings (suppressed ones included, flagged)."""
    from maggy_tpu.analysis import guards, journalvocab, lockorder, rpcconf

    runners = {
        "guards": guards.check,
        "lockorder": lockorder.check,
        "rpcconf": rpcconf.check,
        "journalvocab": journalvocab.check,
    }
    return {name: runners[name](index) for name in checkers
            if name in runners}


def run_analysis(root: Optional[str] = None,
                 checkers=CHECKERS) -> Dict[str, Any]:
    """Parse + analyze the package; returns the full report dict
    (``findings`` = unsuppressed, ``suppressed`` = annotated-away,
    ``summary`` = counts per checker, ``lock_order`` = the canonical
    order for docs/witness consumers)."""
    from maggy_tpu.analysis import lockorder

    root = root or package_root()
    index = parse_package(root)
    results = analyze(index, checkers=checkers)
    findings = [f for fs in results.values() for f in fs if not f.suppressed]
    suppressed = [f for fs in results.values() for f in fs if f.suppressed]
    report: Dict[str, Any] = {
        "root": root,
        "findings": findings,
        "suppressed": suppressed,
        "summary": {name: sum(1 for f in fs if not f.suppressed)
                    for name, fs in results.items()},
        "num_locks": len(index.lock_decls()),
    }
    if "lockorder" in checkers:
        graph = lockorder.build_graph(index)
        report["lock_order"] = lockorder.canonical_order(graph)
        report["lock_edges"] = sorted(
            "{} -> {}".format(a, b) for (a, b) in graph.edges)
    return report


def analyze_paths(paths: List[str],
                  checkers=CHECKERS) -> Dict[str, List[Finding]]:
    """Analyze an explicit file set (fixture tests)."""
    index = parse_package(None, paths=paths)
    return analyze(index, checkers=checkers)


__all__ = ["Finding", "CHECKERS", "analyze", "analyze_paths",
           "run_analysis", "package_root", "parse_package", "PackageIndex"]
