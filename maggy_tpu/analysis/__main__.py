"""``python -m maggy_tpu.analysis`` — run the concurrency & protocol
conformance checkers over the installed package.

    python -m maggy_tpu.analysis                 # exit 0 = clean
    python -m maggy_tpu.analysis --json          # machine-readable report
    python -m maggy_tpu.analysis --write-docs    # refresh docs/analysis.md
    python -m maggy_tpu.analysis --checkers guards,lockorder

Exit codes: 0 = no unsuppressed findings; 1 = findings (each printed as
``path:line: [checker] message``); suppressed findings are listed with
their written reasons under ``--verbose`` so deliberate exceptions stay
auditable.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from maggy_tpu.analysis import CHECKERS, run_analysis

#: Markers bounding the generated lock-order section in docs/analysis.md.
DOCS_BEGIN = "<!-- BEGIN GENERATED LOCK ORDER (python -m maggy_tpu.analysis --write-docs) -->"
DOCS_END = "<!-- END GENERATED LOCK ORDER -->"


def render_lock_order(report) -> str:
    lines = ["", "The canonical acquisition order (acquire earlier-listed "
                 "locks first; generated from the static "
                 "acquired-while-holding graph):", ""]
    for i, name in enumerate(report.get("lock_order", []), 1):
        lines.append("{:2d}. `{}`".format(i, name))
    lines += ["", "Observed acquired-while-holding edges:", ""]
    for edge in report.get("lock_edges", []):
        lines.append("- `{}`".format(edge))
    lines.append("")
    return "\n".join(lines)


def write_docs(report, docs_path: str) -> bool:
    with open(docs_path, "r") as f:
        text = f.read()
    if DOCS_BEGIN not in text or DOCS_END not in text:
        return False
    head, rest = text.split(DOCS_BEGIN, 1)
    _, tail = rest.split(DOCS_END, 1)
    new = head + DOCS_BEGIN + "\n" + render_lock_order(report) \
        + DOCS_END + tail
    if new != text:
        with open(docs_path, "w") as f:
            f.write(new)
    return True


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m maggy_tpu.analysis",
        description="Static concurrency & protocol conformance analysis.")
    ap.add_argument("--json", action="store_true",
                    help="print the full report as JSON")
    ap.add_argument("--verbose", action="store_true",
                    help="also list suppressed findings with their reasons")
    ap.add_argument("--checkers", default=",".join(CHECKERS),
                    help="comma-separated subset of: " + ", ".join(CHECKERS))
    ap.add_argument("--root", default=None,
                    help="package root to analyze (default: installed "
                         "maggy_tpu)")
    ap.add_argument("--write-docs", metavar="DOCS_MD", nargs="?",
                    const="docs/analysis.md", default=None,
                    help="refresh the generated lock-order section of "
                         "docs/analysis.md (default path when flag given "
                         "bare)")
    args = ap.parse_args(argv)

    checkers = tuple(c.strip() for c in args.checkers.split(",") if c)
    unknown = [c for c in checkers if c not in CHECKERS]
    if unknown:
        ap.error("unknown checker(s): {}".format(", ".join(unknown)))
    report = run_analysis(root=args.root, checkers=checkers)

    if args.write_docs is not None:
        path = args.write_docs
        if not os.path.exists(path):
            print("docs file not found: {}".format(path), file=sys.stderr)
            return 2
        if not write_docs(report, path):
            print("docs file has no generated-section markers",
                  file=sys.stderr)
            return 2

    if args.json:
        out = dict(report)
        out["findings"] = [f.to_dict() for f in report["findings"]]
        out["suppressed"] = [f.to_dict() for f in report["suppressed"]]
        print(json.dumps(out, indent=2))
    else:
        for f in report["findings"]:
            print(repr(f))
        if args.verbose:
            for f in report["suppressed"]:
                print(repr(f))
        counts = ", ".join("{}: {}".format(k, v)
                           for k, v in sorted(report["summary"].items()))
        print("maggy_tpu.analysis: {} finding(s) ({}); {} suppressed with "
              "reasons; {} locks, {} order edges".format(
                  len(report["findings"]), counts,
                  len(report["suppressed"]), report.get("num_locks", 0),
                  len(report.get("lock_edges", []))))
    return 1 if report["findings"] else 0


if __name__ == "__main__":
    sys.exit(main())
