"""Shared AST index for the analysis checkers.

Parses every module of the package ONCE (pure ``ast`` + ``tokenize`` —
nothing under analysis is imported) and exposes:

- per-module comment annotations (``# guarded-by:``, ``# locked-by:``,
  ``# unguarded-ok:``, ``# lock-order-ok:``, ``# rpc-ok:``,
  ``# vocab-ok:``, ``# lock:`` — see docs/analysis.md);
- per-class lock declarations (``self.x = threading.Lock()`` and module
  globals), with ``Condition(other_lock)`` tracked as an alias;
- per-class attribute accesses annotated with the set of locks held at
  the access site (lexical ``with`` nesting + ``.acquire()``
  approximation + ``# locked-by:`` method contracts);
- lock acquisition events with the held-set at acquisition (the raw
  material of the acquired-while-holding graph) and a lightweight call
  graph so edges crossing method calls are seen;
- instance-attribute type inference (``self.x = ClassName(...)``) so
  ``self.server.reservations.lock`` style acquisitions resolve to the
  owning class.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from typing import Any, Dict, List, Optional, Set, Tuple

#: Annotation tags recognized in comments: ``# <tag>: <value>``.
ANNOTATION_TAGS = ("guarded-by", "locked-by", "unguarded-ok",
                   "lock-order-ok", "rpc-ok", "vocab-ok", "lock")

_ANNOT_RE = re.compile(
    r"#\s*(" + "|".join(ANNOTATION_TAGS) + r")\s*:\s*(.*?)\s*(?:#|$)")

#: Mutating method names on containers: calling one on an attribute
#: counts as a WRITE of that attribute.
MUTATORS = frozenset({
    "append", "extend", "insert", "remove", "pop", "popitem", "clear",
    "update", "setdefault", "add", "discard", "appendleft", "popleft",
    "sort", "reverse",
})

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}


class Annotation:
    __slots__ = ("tag", "value", "line")

    def __init__(self, tag: str, value: str, line: int):
        self.tag = tag
        self.value = value
        self.line = line


class LockDecl:
    """One lock allocation site: ``owner`` is the class name (or the
    module name for globals), ``attr`` the attribute/global name."""

    __slots__ = ("owner", "attr", "kind", "path", "line", "alias_of")

    def __init__(self, owner: str, attr: str, kind: str, path: str,
                 line: int, alias_of: Optional[str] = None):
        self.owner = owner
        self.attr = attr
        self.kind = kind
        self.path = path
        self.line = line
        self.alias_of = alias_of  # Condition(self.X) -> X

    @property
    def name(self) -> str:
        return "{}.{}".format(self.owner, self.attr)


class Access:
    """One read/write of ``self.<attr>`` inside a method."""

    __slots__ = ("attr", "kind", "method", "line", "held", "in_init")

    def __init__(self, attr: str, kind: str, method: str, line: int,
                 held: frozenset, in_init: bool):
        self.attr = attr
        self.kind = kind  # "read" | "write"
        self.method = method
        self.line = line
        self.held = held
        self.in_init = in_init


class Acquisition:
    """One lock acquisition (``with`` entry or ``.acquire()``)."""

    __slots__ = ("lock", "line", "func", "held")

    def __init__(self, lock: str, line: int, func: str, held: frozenset):
        self.lock = lock
        self.line = line
        self.func = func
        self.held = held


class Call:
    """A resolvable-ish call made while possibly holding locks.
    ``args_from_params``: callee-arg-position -> caller param name, for
    the rpc payload-flow pass."""

    __slots__ = ("callee", "line", "func", "held", "args_from_params")

    def __init__(self, callee: str, line: int, func: str, held: frozenset,
                 args_from_params: Dict[int, str]):
        self.callee = callee
        self.line = line
        self.func = func
        self.held = held
        self.args_from_params = args_from_params


class ClassInfo:
    def __init__(self, name: str, module: "ModuleInfo", node: ast.ClassDef):
        self.name = name
        self.module = module
        self.node = node
        self.bases = [_name_of(b) for b in node.bases]
        self.methods: Dict[str, ast.FunctionDef] = {}
        self.locks: Dict[str, LockDecl] = {}
        # attr -> (lock name, decl line) from `# guarded-by:` annotations.
        self.guard_annotations: Dict[str, Tuple[str, int]] = {}
        # attr -> first-assignment line in __init__ (declaration site).
        self.attr_decl_lines: Dict[str, int] = {}
        # attrs whose declaration line carries `# unguarded-ok:`.
        self.exempt_attrs: Dict[str, str] = {}
        self.accesses: List[Access] = []
        # Whole-class exemption: `# guarded-by: Owner._lock` on the class
        # line documents external synchronization.
        self.external_guard: Optional[str] = None
        # attr -> constructed class name (self.x = ClassName(...)).
        self.attr_types: Dict[str, str] = {}


class ModuleInfo:
    def __init__(self, path: str, modname: str, tree: ast.Module,
                 annotations: Dict[int, List[Annotation]], text: str):
        self.path = path
        self.modname = modname
        self.tree = tree
        self.annotations = annotations
        self.text = text
        self.classes: Dict[str, ClassInfo] = {}
        self.module_locks: Dict[str, LockDecl] = {}

    def annotation(self, line: int, tag: str) -> Optional[Annotation]:
        for ann in self.annotations.get(line, []):
            if ann.tag == tag:
                return ann
        return None

    def annotation_near(self, line: int, tag: str,
                        back: int = 1) -> Optional[Annotation]:
        """Annotation on ``line`` or up to ``back`` lines above it."""
        for ln in range(line, line - back - 1, -1):
            ann = self.annotation(ln, tag)
            if ann is not None:
                return ann
        return None


class PackageIndex:
    def __init__(self, root: Optional[str]):
        self.root = root
        self.modules: Dict[str, ModuleInfo] = {}
        self.classes: Dict[str, List[ClassInfo]] = {}
        self.acquisitions: List[Acquisition] = []
        self.calls: List[Call] = []
        # func qualname -> FunctionDef (Class.method / modname.func).
        self.functions: Dict[str, ast.FunctionDef] = {}
        self.func_module: Dict[str, ModuleInfo] = {}
        # method name -> owning qualnames (for unique-name resolution).
        self.method_owners: Dict[str, List[str]] = {}

    # --------------------------------------------------------------- lookups

    def lock_decls(self) -> List[LockDecl]:
        out = []
        for mod in self.modules.values():
            out.extend(mod.module_locks.values())
            for cls in mod.classes.values():
                out.extend(cls.locks.values())
        return out

    def decl_by_site(self) -> Dict[Tuple[str, int], LockDecl]:
        """(abspath, line) -> decl; the witness maps runtime allocation
        frames through this."""
        return {(os.path.abspath(d.path), d.line): d
                for d in self.lock_decls()}

    def classes_with_lock_attr(self, attr: str) -> List[ClassInfo]:
        return [c for cs in self.classes.values() for c in cs
                if attr in c.locks]

    def resolve_method(self, name: str) -> Optional[str]:
        """Qualname of ``name`` if exactly one class (or module) defines
        it, else None."""
        owners = self.method_owners.get(name, [])
        return owners[0] if len(owners) == 1 else None

    def class_info(self, name: str) -> Optional[ClassInfo]:
        lst = self.classes.get(name, [])
        return lst[0] if len(lst) == 1 else None

    def mro_methods(self, cls: ClassInfo) -> Dict[str, ast.FunctionDef]:
        """Methods including (package-local, by-name) base classes;
        subclass wins."""
        out: Dict[str, ast.FunctionDef] = {}
        for base in reversed(cls.bases):
            base_cls = self.class_info(base) if base else None
            if base_cls is not None:
                out.update(self.mro_methods(base_cls))
        out.update(cls.methods)
        return out


# ------------------------------------------------------------------ parsing


def _name_of(node) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _comment_annotations(text: str) -> Dict[int, List[Annotation]]:
    out: Dict[int, List[Annotation]] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(text).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _ANNOT_RE.search(tok.string)
            if m:
                ann = Annotation(m.group(1), m.group(2).strip(),
                                 tok.start[0])
                out.setdefault(tok.start[0], []).append(ann)
    except tokenize.TokenError:
        pass
    return out


def _lock_ctor_call(node) -> Optional[Tuple[str, Optional[str]]]:
    """(kind, alias_attr) when ``node`` is ``threading.<Lock...>(...)``.
    ``alias_attr`` is set for ``Condition(self.X)`` / ``Condition(X)``."""
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    name = None
    if isinstance(fn, ast.Attribute) and \
            isinstance(fn.value, ast.Name) and fn.value.id == "threading":
        name = fn.attr
    elif isinstance(fn, ast.Name):
        name = fn.id
    if name not in _LOCK_CTORS:
        return None
    alias = None
    if name == "Condition" and node.args:
        arg = node.args[0]
        if isinstance(arg, ast.Attribute) and \
                isinstance(arg.value, ast.Name) and arg.value.id == "self":
            alias = arg.attr
        elif isinstance(arg, ast.Name):
            alias = arg.id
    return name, alias


def parse_package(root: Optional[str],
                  paths: Optional[List[str]] = None) -> PackageIndex:
    index = PackageIndex(root)
    files: List[Tuple[str, str]] = []
    if paths is not None:
        for p in paths:
            files.append((p, os.path.splitext(os.path.basename(p))[0]))
    else:
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                full = os.path.join(dirpath, fn)
                rel = os.path.relpath(full, os.path.dirname(root))
                modname = rel[:-3].replace(os.sep, ".")
                if modname.endswith(".__init__"):
                    modname = modname[:-9]
                files.append((full, modname))
    for path, modname in files:
        with open(path, "r") as f:
            text = f.read()
        try:
            tree = ast.parse(text, filename=path)
        except SyntaxError:
            continue
        mod = ModuleInfo(path, modname, tree, _comment_annotations(text),
                         text)
        index.modules[modname] = mod
        _index_module(index, mod)
    for mod in index.modules.values():
        _collect_accesses(index, mod)
    return index


def _index_module(index: PackageIndex, mod: ModuleInfo) -> None:
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            ctor = _lock_ctor_call(node.value)
            if ctor is not None:
                name = node.targets[0].id
                mod.module_locks[name] = LockDecl(
                    mod.modname, name, ctor[0], mod.path, node.lineno,
                    alias_of=ctor[1])
        elif isinstance(node, ast.FunctionDef):
            qual = "{}.{}".format(mod.modname, node.name)
            index.functions[qual] = node
            index.func_module[qual] = mod
            index.method_owners.setdefault(node.name, []).append(qual)
        elif isinstance(node, ast.ClassDef):
            cls = ClassInfo(node.name, mod, node)
            mod.classes[node.name] = cls
            index.classes.setdefault(node.name, []).append(cls)
            ann = mod.annotation(node.lineno, "guarded-by")
            if ann is not None:
                cls.external_guard = ann.value
            for item in node.body:
                if isinstance(item, ast.FunctionDef):
                    cls.methods[item.name] = item
                    qual = "{}.{}".format(node.name, item.name)
                    index.functions[qual] = item
                    index.func_module[qual] = mod
                    index.method_owners.setdefault(
                        item.name, []).append(qual)
                elif isinstance(item, ast.Assign) and \
                        len(item.targets) == 1 and \
                        isinstance(item.targets[0], ast.Name):
                    # Class-level lock attribute (EnvSing._lock style).
                    ctor = _lock_ctor_call(item.value)
                    if ctor is not None:
                        name = item.targets[0].id
                        cls.locks[name] = LockDecl(
                            node.name, name, ctor[0], mod.path,
                            item.lineno, alias_of=ctor[1])
            # Lock attrs + guard annotations + attr types from EVERY
            # method (locks are usually made in __init__ but not always).
            for mname, fnode in cls.methods.items():
                for stmt in ast.walk(fnode):
                    if isinstance(stmt, ast.Assign) and \
                            len(stmt.targets) == 1:
                        tgt, value = stmt.targets[0], stmt.value
                    elif isinstance(stmt, ast.AnnAssign):
                        # self.x: T = ... carries annotations the same
                        # way an untyped assignment does.
                        tgt, value = stmt.target, stmt.value
                    else:
                        continue
                    if not (isinstance(tgt, ast.Attribute) and
                            isinstance(tgt.value, ast.Name) and
                            tgt.value.id == "self"):
                        continue
                    ctor = _lock_ctor_call(value) if value is not None \
                        else None
                    if ctor is not None:
                        cls.locks[tgt.attr] = LockDecl(
                            cls.name, tgt.attr, ctor[0], mod.path,
                            stmt.lineno, alias_of=ctor[1])
                        continue
                    if isinstance(value, ast.Call):
                        cname = _name_of(value.func)
                        if cname and cname[:1].isupper():
                            cls.attr_types.setdefault(tgt.attr, cname)
                    if mname == "__init__":
                        cls.attr_decl_lines.setdefault(tgt.attr,
                                                       stmt.lineno)
                    ann = mod.annotation(stmt.lineno, "guarded-by")
                    if ann is not None:
                        cls.guard_annotations.setdefault(
                            tgt.attr, (ann.value, stmt.lineno))
                    ann = mod.annotation(stmt.lineno, "unguarded-ok")
                    if ann is not None and mname == "__init__":
                        cls.exempt_attrs.setdefault(tgt.attr, ann.value)


# ----------------------------------------------------- held-lock collection


class _HeldVisitor(ast.NodeVisitor):
    """Walks one function body tracking the lexically-held lock set.

    Lock references resolve to package-wide names:
    - ``self.X`` where class defines lock X           -> "Class.X"
    - bare ``X`` where the module defines global lock -> "module.X"
    - ``<expr>.Y`` where Y is a lock attr             -> owner via
      attr-type inference / var-name heuristic / ``# lock:`` annotation,
      else "?.Y" (recorded, excluded from order edges).
    Condition aliases collapse onto their underlying lock.
    """

    def __init__(self, index: PackageIndex, mod: ModuleInfo,
                 cls: Optional[ClassInfo], func: ast.FunctionDef,
                 qual: str):
        self.index = index
        self.mod = mod
        self.cls = cls
        self.func = func
        self.qual = qual
        self.held: Tuple[str, ...] = ()
        ann = mod.annotation_near(func.lineno, "locked-by", back=1)
        if ann is not None:
            for lock in ann.value.split(","):
                self.held = self.held + (self._canon_self_lock(
                    lock.strip()),)
        self.in_init = func.name == "__init__"

    # -- lock reference resolution ----------------------------------------

    def _canon_self_lock(self, attr: str) -> str:
        if "." in attr:
            return attr  # already Owner.attr
        cls = self.cls
        if cls is not None and attr in cls.locks:
            decl = cls.locks[attr]
            if decl.alias_of and decl.alias_of in cls.locks:
                return "{}.{}".format(cls.name, decl.alias_of)
            return "{}.{}".format(cls.name, attr)
        if attr in self.mod.module_locks:
            decl = self.mod.module_locks[attr]
            if decl.alias_of and decl.alias_of in self.mod.module_locks:
                return "{}.{}".format(self.mod.modname, decl.alias_of)
            return "{}.{}".format(self.mod.modname, attr)
        return "?." + attr

    def _resolve_lock_expr(self, node, line: int) -> Optional[str]:
        ann = self.mod.annotation(line, "lock")
        if isinstance(node, ast.Name):
            if node.id in self.mod.module_locks:
                return self._canon_self_lock(node.id)
            return ann.value if ann is not None else None
        if not isinstance(node, ast.Attribute):
            return None
        attr = node.attr
        base = node.value
        owners = self.index.classes_with_lock_attr(attr)
        if not owners:
            return ann.value if ann is not None else None
        if isinstance(base, ast.Name) and base.id == "self":
            if self.cls is not None and attr in self.cls.locks:
                return self._canon_self_lock(attr)
            # self.X in a mixin whose lock lives on the composed class.
        if ann is not None:
            return ann.value
        if len(owners) == 1:
            return "{}.{}".format(owners[0].name, attr)
        # Ambiguous attr name (.lock on Trial/Reservations/Reporter):
        # try the holder expression's inferred type, then the var-name ~
        # class-name heuristic.
        base_name = _name_of(base)
        if base_name:
            for c in ({} if self.cls is None
                      else [self.cls]):
                typ = c.attr_types.get(base_name)
                if typ and any(o.name == typ for o in owners):
                    return "{}.{}".format(typ, attr)
            for o in owners:
                if base_name.lower() == o.name.lower():
                    return "{}.{}".format(o.name, attr)
        return "?." + attr

    # -- traversal ---------------------------------------------------------

    def visit_FunctionDef(self, node):
        if node is not self.func:
            return  # nested defs analyzed separately (fresh held set)
        for stmt in node.body:
            self.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_With(self, node):
        added = []
        for item in node.items:
            ctx = item.context_expr
            target = ctx
            # with lock / with cond / with self.x.lock — strip no calls;
            # ``lock.acquire()`` handled in visit_Call.
            lock = self._resolve_lock_expr(target, node.lineno)
            if lock is not None:
                self._note_acquire(lock, node.lineno)
                added.append(lock)
            else:
                self.visit(ctx)
        self.held = self.held + tuple(added)
        for stmt in node.body:
            self.visit(stmt)
        if added:
            self.held = self.held[:len(self.held) - len(added)]

    def visit_Call(self, node):
        fn = node.func
        # lock.acquire(...): treat the REST of the enclosing function as
        # held (approximation — release is almost always in a finally).
        if isinstance(fn, ast.Attribute) and fn.attr == "acquire":
            lock = self._resolve_lock_expr(fn.value, node.lineno)
            if lock is not None:
                self._note_acquire(lock, node.lineno)
                self.held = self.held + (lock,)
        callee = None
        args_from_params: Dict[int, str] = {}
        params = {a.arg for a in self.func.args.args}
        for i, arg in enumerate(node.args):
            if isinstance(arg, ast.Name) and arg.id in params:
                args_from_params[i] = arg.id
        if isinstance(fn, ast.Name):
            callee = fn.id
        elif isinstance(fn, ast.Attribute):
            callee = fn.attr
        if callee:
            self.index.calls.append(Call(
                callee, node.lineno, self.qual,
                frozenset(self.held), args_from_params))
        self.generic_visit(node)

    def _note_acquire(self, lock: str, line: int) -> None:
        held = frozenset(h for h in self.held if h != lock)
        self.index.acquisitions.append(
            Acquisition(lock, line, self.qual, held))

    # -- attribute accesses -------------------------------------------------

    def _note_access(self, attr: str, kind: str, line: int) -> None:
        if self.cls is None:
            return
        self.cls.accesses.append(Access(
            attr, kind, self.func.name, line,
            frozenset(self.held), self.in_init))

    def visit_Attribute(self, node):
        if isinstance(node.value, ast.Name) and node.value.id == "self":
            kind = "write" if isinstance(node.ctx,
                                         (ast.Store, ast.Del)) else "read"
            self._note_access(node.attr, kind, node.lineno)
        self.generic_visit(node)

    def visit_Subscript(self, node):
        # self.x[k] = v  /  del self.x[k]  => WRITE of x (and a read).
        tgt = node.value
        if isinstance(tgt, ast.Attribute) and \
                isinstance(tgt.value, ast.Name) and tgt.value.id == "self" \
                and isinstance(node.ctx, (ast.Store, ast.Del)):
            self._note_access(tgt.attr, "write", node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        tgt = node.target
        if isinstance(tgt, ast.Attribute) and \
                isinstance(tgt.value, ast.Name) and tgt.value.id == "self":
            self._note_access(tgt.attr, "write", node.lineno)
        elif isinstance(tgt, ast.Subscript):
            inner = tgt.value
            if isinstance(inner, ast.Attribute) and \
                    isinstance(inner.value, ast.Name) and \
                    inner.value.id == "self":
                self._note_access(inner.attr, "write", node.lineno)
        self.generic_visit(node)


def _collect_accesses(index: PackageIndex, mod: ModuleInfo) -> None:
    for cls in mod.classes.values():
        for mname, fnode in cls.methods.items():
            qual = "{}.{}".format(cls.name, mname)
            v = _HeldVisitor(index, mod, cls, fnode, qual)
            v.visit(fnode)
            _upgrade_mutator_calls(cls, fnode)
    for node in mod.tree.body:
        if isinstance(node, ast.FunctionDef):
            qual = "{}.{}".format(mod.modname, node.name)
            v = _HeldVisitor(index, mod, None, node, qual)
            v.visit(node)


def _upgrade_mutator_calls(cls: ClassInfo, fnode: ast.FunctionDef) -> None:
    """``self.x.append(v)`` records a read of x at that line; upgrade it
    to a write when the called method mutates."""
    mut_lines: Dict[Tuple[str, int], bool] = {}
    for node in ast.walk(fnode):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in MUTATORS:
            tgt = node.func.value
            if isinstance(tgt, ast.Attribute) and \
                    isinstance(tgt.value, ast.Name) and \
                    tgt.value.id == "self":
                mut_lines[(tgt.attr, node.lineno)] = True
    if not mut_lines:
        return
    for acc in cls.accesses:
        if acc.kind == "read" and (acc.attr, acc.line) in mut_lines:
            acc.kind = "write"
