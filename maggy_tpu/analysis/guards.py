"""Checker 1: guarded-by inference.

For every class that owns ``threading`` locks, decide which instance
attributes those locks guard, then flag accesses on code paths that do
not hold the guard:

- **Annotated attributes** (``# guarded-by: <lock>`` on the attribute's
  ``__init__`` assignment): strict — every read AND write outside
  ``__init__`` must hold the lock, unless the line carries
  ``# unguarded-ok: <reason>``.
- **Inferred attributes** (no annotation): an attribute written at least
  twice under one common lock (outside ``__init__``) is presumed guarded
  by it; any lock-free WRITE is flagged (reads are too often benignly
  racy to infer on — annotate to get read checking).

``# locked-by: <lock>`` on a method declares a caller-holds-the-lock
contract (the held set starts with that lock). A ``# guarded-by:`` on the
``class`` line documents external synchronization (e.g. fleet
``ExperimentEntry`` guarded by the scheduler's lock) and exempts the
whole class. Accesses made by package-local subclasses count toward the
defining class's attributes, so an inherited structure cannot dodge its
guard by being touched from a child class.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from maggy_tpu.analysis.astindex import Access, ClassInfo, PackageIndex

#: Attribute write threshold for inferring a guard without annotation.
MIN_LOCKED_WRITES = 2


def _canon_lock(cls: ClassInfo, value: str) -> Optional[str]:
    """'_store_lock' -> 'Cls._store_lock' (following Condition aliases);
    'Owner.attr' passes through; unknown -> None."""
    if "." in value:
        return value
    decl = cls.locks.get(value)
    if decl is None:
        return None
    if decl.alias_of and decl.alias_of in cls.locks:
        return "{}.{}".format(cls.name, decl.alias_of)
    return "{}.{}".format(cls.name, value)


def _subclasses(index: PackageIndex, cls: ClassInfo) -> List[ClassInfo]:
    out, frontier = [], {cls.name}
    changed = True
    while changed:
        changed = False
        for cands in index.classes.values():
            for c in cands:
                if c in out or c is cls:
                    continue
                if any(b in frontier for b in c.bases if b):
                    out.append(c)
                    frontier.add(c.name)
                    changed = True
    return out


def _gather_accesses(index: PackageIndex,
                     cls: ClassInfo) -> List[Tuple[ClassInfo, Access]]:
    pairs = [(cls, a) for a in cls.accesses]
    for sub in _subclasses(index, cls):
        # A subclass that re-declares the attribute in its own __init__
        # owns it separately (e.g. both servers define self.driver).
        pairs.extend((sub, a) for a in sub.accesses
                     if a.attr not in sub.attr_decl_lines)
    return pairs


def check(index: PackageIndex) -> List["Finding"]:
    from maggy_tpu.analysis import Finding

    findings: List[Finding] = []
    for mod in index.modules.values():
        for cls in mod.classes.values():
            if not cls.locks and not cls.guard_annotations:
                continue
            if cls.external_guard is not None:
                continue
            findings.extend(_check_class(index, cls))
    return findings


def _check_class(index: PackageIndex, cls: ClassInfo) -> List["Finding"]:
    from maggy_tpu.analysis import Finding

    mod = cls.module
    findings: List[Finding] = []
    pairs = _gather_accesses(index, cls)
    by_attr: Dict[str, List[Tuple[ClassInfo, Access]]] = {}
    for owner, acc in pairs:
        if acc.attr in cls.locks or acc.attr in cls.methods:
            continue
        by_attr.setdefault(acc.attr, []).append((owner, acc))

    def emit(owner: ClassInfo, acc: Access, msg: str) -> None:
        # On the access line or a comment just above it.
        ann = owner.module.annotation_near(acc.line, "unguarded-ok", back=2)
        if ann is not None and not ann.value:
            findings.append(Finding(
                "guards", owner.module.path, acc.line,
                "unguarded-ok suppression without a reason "
                "({}.{})".format(cls.name, acc.attr)))
            return
        findings.append(Finding(
            "guards", owner.module.path, acc.line, msg,
            suppressed=ann is not None,
            reason=ann.value if ann is not None else None))

    for attr, accs in sorted(by_attr.items()):
        if attr in cls.exempt_attrs:
            continue
        annotated = cls.guard_annotations.get(attr)
        if annotated is not None:
            lock = _canon_lock(cls, annotated[0])
            if lock is None:
                findings.append(Finding(
                    "guards", mod.path, annotated[1],
                    "guarded-by names unknown lock {!r} for {}.{}".format(
                        annotated[0], cls.name, attr)))
                continue
            for owner, acc in accs:
                if acc.in_init or lock in acc.held:
                    continue
                emit(owner, acc,
                     "{} of {}.{} without holding {} "
                     "(guarded-by annotation)".format(
                         acc.kind, cls.name, attr, lock))
            continue
        # Inference: all non-init locked writes share a common lock?
        writes = [(o, a) for o, a in accs
                  if a.kind == "write" and not a.in_init]
        locked = [(o, a) for o, a in writes if a.held]
        if len(locked) < MIN_LOCKED_WRITES:
            continue
        common = frozenset.intersection(*[a.held for _, a in locked])
        if not common:
            continue
        lock = sorted(common)[0]
        for owner, acc in writes:
            if acc.held:
                continue
            emit(owner, acc,
                 "write of {}.{} without holding {} ({} of {} writes "
                 "hold it — inferred guard; annotate guarded-by/"
                 "unguarded-ok to settle)".format(
                     cls.name, attr, lock, len(locked), len(writes)))
    return findings
