"""Checker 4: journal vocabulary conformance.

``telemetry/vocab.py`` is the single home of every string the journal
speaks. This checker verifies three directions, all statically:

1. **emit -> vocab**: every literal span phase (``trial_event(tid,
   "phase")``), event kind (``.event("kind", ...)``) and ``reason=``
   kwarg emitted anywhere in the package appears in the vocabulary;
2. **vocab -> emit**: every ``SPAN_PHASES`` / ``EVENT_KINDS`` /
   ``REQUEUE_REASONS`` entry is emitted by at least one call site (no
   orphan vocabulary — an entry nothing emits is a dead consumer match);
3. **consume -> vocab**: every literal a consumer matches against a
   journal field (``ev.get("phase") == "..."``, membership in a
   ``*_PHASES`` constant, aliases of such fields) appears in the
   vocabulary — a consumer typo matches nothing, silently.

``# vocab-ok: <reason>`` on the emit/consume line suppresses.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from maggy_tpu.analysis.astindex import ModuleInfo, PackageIndex

#: Journal fields whose compared literals belong to a vocab family.
#: ``kind`` is the CHAOS fault kind (the ``ev`` field carries the event
#: kind; consumers holding ``ev.get("ev")`` in a variable are tracked by
#: alias, whatever the variable is called).
_FIELD_FAMILY = {"phase": "phase", "ev": "kind", "reason": "reason",
                 "kind": "chaos_kind",
                 "status": "health_status", "check": "health_check"}

#: Module-level constant-name suffix -> family (consumer tables like
#: trace._INSTANT_PHASES, harness._REQUEUE_KINDS).
_CONST_FAMILY = (("PHASES", "phase"), ("REASONS", "reason"),
                 ("KINDS", "chaos_kind"), ("CHECKS", "health_check"),
                 ("STATUSES", "health_status"))

#: Emitter call method names.
_EMIT_EVENT = ("event", "_event")


class Vocab:
    def __init__(self):
        self.sets: Dict[str, Set[str]] = {}
        self.mod: Optional[ModuleInfo] = None
        self.lines: Dict[str, int] = {}  # entry -> decl line (span/kind)

    def family(self, name: str) -> Set[str]:
        if name == "phase":
            return (self.sets.get("ALL_PHASES") or
                    set().union(*[v for k, v in self.sets.items()
                                  if k.endswith("PHASES")] or [set()]))
        if name == "kind":
            return self.sets.get("EVENT_KINDS", set())
        if name == "reason":
            return (self.sets.get("ALL_REASONS") or
                    set().union(*[v for k, v in self.sets.items()
                                  if k.endswith("REASONS")] or [set()]))
        if name == "health_status":
            return self.sets.get("HEALTH_STATUSES", set())
        if name == "health_check":
            return self.sets.get("HEALTH_CHECKS", set())
        if name == "chaos_kind":
            return self.sets.get("CHAOS_KINDS", set())
        return set()


def _load_vocab(index: PackageIndex) -> Optional[Vocab]:
    for mod in index.modules.values():
        names = {}
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                lits = _literal_set(node.value)
                if lits is not None:
                    names[node.targets[0].id] = (lits, node.lineno)
        if "SPAN_PHASES" in names and "EVENT_KINDS" in names:
            vocab = Vocab()
            vocab.mod = mod
            for k, (lits, line) in names.items():
                vocab.sets[k] = lits
                for entry in lits:
                    vocab.lines.setdefault(entry, line)
            # Synthesize the unions when vocab.py computes them (the
            # computed ALL_PHASES is a BinOp, not a literal).
            if "ALL_PHASES" not in vocab.sets:
                vocab.sets["ALL_PHASES"] = set().union(
                    *[v for k, v in vocab.sets.items()
                      if k.endswith("PHASES")] or [set()])
            if "ALL_REASONS" not in vocab.sets:
                vocab.sets["ALL_REASONS"] = set().union(
                    *[v for k, v in vocab.sets.items()
                      if k.endswith("REASONS")] or [set()])
            return vocab
    return None


def _literal_set(node) -> Optional[Set[str]]:
    """Flat tuple/set/frozenset/list of string constants -> set."""
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id in ("frozenset", "set") and node.args:
        return _literal_set(node.args[0])
    if isinstance(node, (ast.Tuple, ast.Set, ast.List)):
        out = set()
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                out.add(el.value)
            else:
                return None
        return out
    return None


# ------------------------------------------------------------------ emitters


def _collect_emits(index: PackageIndex, vocab_mod
                   ) -> List[Tuple[str, str, ModuleInfo, int]]:
    """(family, literal, module, line) for every literal emit site."""
    out = []
    for mod in index.modules.values():
        if mod is vocab_mod or _is_meta(mod):
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = node.func.attr if isinstance(node.func, ast.Attribute) \
                else (node.func.id if isinstance(node.func, ast.Name)
                      else None)
            if name == "trial_event":
                if len(node.args) >= 2 and _is_str(node.args[1]):
                    out.append(("phase", node.args[1].value, mod,
                                node.lineno))
                for kw in node.keywords:
                    if kw.arg == "reason" and _is_str(kw.value):
                        out.append(("reason", kw.value.value, mod,
                                    node.lineno))
            elif name in _EMIT_EVENT:
                if node.args and _is_str(node.args[0]):
                    out.append(("kind", node.args[0].value, mod,
                                node.lineno))
                    kind = node.args[0].value
                    for kw in node.keywords:
                        if kw.arg == "phase" and _is_str(kw.value):
                            out.append(("phase", kw.value.value, mod,
                                        node.lineno))
                        elif kw.arg == "reason" and _is_str(kw.value):
                            out.append(("reason", kw.value.value, mod,
                                        node.lineno))
                        elif kind == "health" and kw.arg == "status" \
                                and _is_str(kw.value):
                            out.append(("health_status", kw.value.value,
                                        mod, node.lineno))
                        elif kind == "health" and kw.arg == "check" \
                                and _is_str(kw.value):
                            out.append(("health_check", kw.value.value,
                                        mod, node.lineno))
            elif name == "mark":
                # SpanTracker.mark(trial, "phase") — the facade's inner
                # edge; literal phases here are emits too.
                if len(node.args) >= 2 and _is_str(node.args[1]):
                    out.append(("phase", node.args[1].value, mod,
                                node.lineno))
        # Raw journal records: dict literals carrying an "ev" key (the
        # Telemetry facade's internal _record paths).
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Dict):
                continue
            for k, v in zip(node.keys, node.values):
                if k is not None and _is_str(k) and k.value == "ev" \
                        and _is_str(v):
                    out.append(("kind", v.value, mod, node.lineno))
                elif k is not None and _is_str(k) and k.value == "phase" \
                        and _is_str(v) and any(
                            kk is not None and _is_str(kk)
                            and kk.value == "ev"
                            for kk in node.keys):
                    out.append(("phase", v.value, mod, node.lineno))
    return out


def _is_str(node) -> bool:
    return isinstance(node, ast.Constant) and isinstance(node.value, str)


def _is_meta(mod: ModuleInfo) -> bool:
    """The analyzer's own modules hold field-name/vocabulary PATTERN
    tables (e.g. ``_FIELD_FAMILY``), not emit/consume sites — linting
    them against the vocabulary is self-referential noise."""
    return mod.modname.startswith("maggy_tpu.analysis")


# ----------------------------------------------------------------- consumers


class _ConsumerVisitor(ast.NodeVisitor):
    """Collects literals compared against journal fields within one
    function: direct ``x.get("phase") == "lit"`` / ``x["phase"] ==``,
    membership tests, and single-hop aliases (``phase = ev.get("phase")``,
    tuple unpack included)."""

    def __init__(self, mod: ModuleInfo, sink: List):
        self.mod = mod
        self.sink = sink
        self.aliases: Dict[str, str] = {}  # var -> family

    def _field_of(self, node) -> Optional[str]:
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "get" and node.args and \
                _is_str(node.args[0]):
            return _FIELD_FAMILY.get(node.args[0].value)
        if isinstance(node, ast.Subscript) and \
                isinstance(node.slice, ast.Constant) and \
                isinstance(node.slice.value, str):
            return _FIELD_FAMILY.get(node.slice.value)
        if isinstance(node, ast.Name):
            return self.aliases.get(node.id)
        return None

    def visit_Assign(self, node):
        tgts = node.targets
        if len(tgts) == 1 and isinstance(tgts[0], ast.Tuple) and \
                isinstance(node.value, ast.Tuple) and \
                len(tgts[0].elts) == len(node.value.elts):
            pairs = zip(tgts[0].elts, node.value.elts)
        else:
            pairs = [(t, node.value) for t in tgts]
        for tgt, val in pairs:
            if isinstance(tgt, ast.Name):
                fam = self._field_of(val)
                if fam is not None:
                    self.aliases[tgt.id] = fam
        self.generic_visit(node)

    def visit_Compare(self, node):
        sides = [node.left] + list(node.comparators)
        fams = [self._field_of(s) for s in sides]
        fam = next((f for f in fams if f), None)
        if fam is not None:
            for s, op in zip(sides[1:], node.ops):
                if isinstance(op, (ast.Eq, ast.NotEq)) and _is_str(s):
                    self.sink.append((fam, s.value, self.mod, s.lineno))
                elif isinstance(op, (ast.In, ast.NotIn)) and \
                        isinstance(s, (ast.Tuple, ast.Set, ast.List)):
                    for el in s.elts:
                        if _is_str(el):
                            self.sink.append((fam, el.value, self.mod,
                                              el.lineno))
            if _is_str(sides[0]) and any(
                    isinstance(op, (ast.In, ast.NotIn))
                    for op in node.ops):
                pass  # "lit" in field-valued container: not a vocab use
        self.generic_visit(node)


def _collect_consumes(index: PackageIndex, vocab_mod
                      ) -> List[Tuple[str, str, ModuleInfo, int]]:
    out: List[Tuple[str, str, ModuleInfo, int]] = []
    for mod in index.modules.values():
        if mod is vocab_mod or _is_meta(mod):
            continue
        # Functions (module + methods): fresh alias scope each.
        funcs = [n for n in ast.walk(mod.tree)
                 if isinstance(n, ast.FunctionDef)]
        for fn in funcs:
            v = _ConsumerVisitor(mod, out)
            for stmt in fn.body:
                v.visit(stmt)
        # Module-level vocabulary tables (trace._INSTANT_PHASES etc.).
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                cname = node.targets[0].id
                for suffix, fam in _CONST_FAMILY:
                    if cname.endswith(suffix):
                        lits = _literal_set(node.value)
                        if lits:
                            out.extend((fam, lit, mod, node.lineno)
                                       for lit in sorted(lits))
                        break
    return out


# -------------------------------------------------------------------- check


def check(index: PackageIndex) -> List["Finding"]:
    from maggy_tpu.analysis import Finding

    findings: List[Finding] = []
    vocab = _load_vocab(index)
    if vocab is None:
        # No vocabulary in scope (fixture sets without one): nothing to
        # conform to — report that loudly for the package run, quietly
        # skip for single-file fixtures that have no emitters either.
        emits_exist = any(_collect_emits(index, None))
        if emits_exist:
            any_mod = next(iter(index.modules.values()))
            findings.append(Finding(
                "journalvocab", any_mod.path, 1,
                "no vocabulary module found (SPAN_PHASES/EVENT_KINDS) "
                "but telemetry emit sites exist"))
        return findings

    def emit_finding(mod: ModuleInfo, line: int, msg: str) -> None:
        ann = mod.annotation_near(line, "vocab-ok", back=2)
        if ann is not None and not ann.value:
            findings.append(Finding(
                "journalvocab", mod.path, line,
                "vocab-ok suppression without a reason"))
            return
        findings.append(Finding(
            "journalvocab", mod.path, line, msg,
            suppressed=ann is not None,
            reason=ann.value if ann is not None else None))

    emits = _collect_emits(index, vocab.mod)
    emitted_by_family: Dict[str, Set[str]] = {}
    for fam, lit, mod, line in emits:
        emitted_by_family.setdefault(fam, set()).add(lit)
        if lit not in vocab.family(fam):
            emit_finding(mod, line,
                         "emitted {} {!r} is not in the journal "
                         "vocabulary (telemetry/vocab.py)".format(fam, lit))

    # Orphan vocabulary: core families must be emitted somewhere.
    for set_name, fam in (("SPAN_PHASES", "phase"),
                          ("EVENT_KINDS", "kind"),
                          ("REQUEUE_REASONS", "reason")):
        for entry in sorted(vocab.sets.get(set_name, set())):
            if entry not in emitted_by_family.get(fam, set()):
                emit_finding(vocab.mod, vocab.lines.get(entry, 1),
                             "vocabulary entry {!r} ({}) is never emitted "
                             "by any call site".format(entry, set_name))

    for fam, lit, mod, line in _collect_consumes(index, vocab.mod):
        if lit not in vocab.family(fam):
            emit_finding(mod, line,
                         "consumer matches {} {!r} which is not in the "
                         "journal vocabulary — the match can never "
                         "fire".format(fam, lit))
    return findings
