"""Checker 2 (static half): the acquired-while-holding graph.

Every lock acquisition that happens while another lock is held adds the
edge ``held -> acquired``; edges also cross method calls (a bounded
fixpoint computes each function's may-acquire set, so ``with A: self.m()``
where ``m`` takes B yields A -> B). Cycles in the graph are the static
deadlock signal; the acyclic graph's topological order is the package's
canonical lock order, emitted into docs/analysis.md and consumed by the
runtime witness (witness.py) as its forbidden-edge oracle.

``# lock-order-ok: <reason>`` on the inner acquisition (or the call that
reaches it) suppresses that site's edges from cycle checking — for edges
proven unreachable-together at runtime. Lock names are canonical
``Owner.attr``; acquisitions that cannot be attributed statically
(``?.attr``) are excluded from the graph rather than guessed.
"""

from __future__ import annotations

from typing import Dict, FrozenSet, List, Set, Tuple

from maggy_tpu.analysis.astindex import PackageIndex

#: Call-graph fixpoint depth bound (defensive; the graph converges fast).
MAX_ROUNDS = 20


class LockGraph:
    def __init__(self):
        # (held, acquired) -> list of "path:line [via func]" example sites.
        self.edges: Dict[Tuple[str, str], List[str]] = {}
        self.suppressed: Dict[Tuple[str, str], str] = {}
        self.nodes: Set[str] = set()

    def add(self, held: str, acquired: str, site: str,
            suppressed_reason=None) -> None:
        if held == acquired or held.startswith("?.") \
                or acquired.startswith("?."):
            return
        key = (held, acquired)
        self.edges.setdefault(key, [])
        if len(self.edges[key]) < 4:
            self.edges[key].append(site)
        if suppressed_reason is not None:
            self.suppressed.setdefault(key, suppressed_reason)
        self.nodes.update(key)

    def active_edges(self) -> List[Tuple[str, str]]:
        return [e for e in self.edges if e not in self.suppressed]


def _resolve_callee(index: PackageIndex, call) -> str:
    """Qualname of the call's target: same-class method first (the caller
    is ``Class.method``), else the package-wide unique definition."""
    owner = call.func.split(".")[0]
    cls = index.class_info(owner)
    if cls is not None:
        mro = index.mro_methods(cls)
        if call.callee in mro:
            # The defining class may be a base; find it for the qualname.
            for cand_name in [owner] + cls.bases:
                cand = index.class_info(cand_name) if cand_name else None
                if cand is not None and call.callee in cand.methods:
                    return "{}.{}".format(cand.name, call.callee)
            return "{}.{}".format(owner, call.callee)
    return index.resolve_method(call.callee) or ""


def build_graph(index: PackageIndex) -> LockGraph:
    graph = LockGraph()
    for decl in index.lock_decls():
        if decl.alias_of is None:
            graph.nodes.add(decl.name)

    def site_of(func: str, line: int) -> str:
        mod = index.func_module.get(func)
        path = mod.path if mod is not None else "?"
        return "{}:{} [{}]".format(path, line, func)

    def suppression(func: str, line: int):
        mod = index.func_module.get(func)
        if mod is None:
            return None
        # On the acquisition line or a comment just above it.
        ann = mod.annotation_near(line, "lock-order-ok", back=2)
        return ann.value if ann is not None else None

    # Direct lexical edges.
    direct: Dict[str, Set[str]] = {}
    for acq in index.acquisitions:
        direct.setdefault(acq.func, set()).add(acq.lock)
        for held in acq.held:
            graph.add(held, acq.lock, site_of(acq.func, acq.line),
                      suppressed_reason=suppression(acq.func, acq.line))

    # may-acquire fixpoint over the (name-resolved) call graph.
    calls_of: Dict[str, Set[str]] = {}
    for call in index.calls:
        callee = _resolve_callee(index, call)
        if callee and callee in index.functions:
            calls_of.setdefault(call.func, set()).add(callee)
    may: Dict[str, Set[str]] = {f: set(direct.get(f, ()))
                                for f in index.functions}
    for _ in range(MAX_ROUNDS):
        changed = False
        for f, callees in calls_of.items():
            acc = may.setdefault(f, set())
            before = len(acc)
            for g in callees:
                acc |= may.get(g, set())
            changed |= len(acc) != before
        if not changed:
            break

    # Call-crossing edges: holding H while calling g that may acquire L.
    for call in index.calls:
        if not call.held:
            continue
        callee = _resolve_callee(index, call)
        if not callee:
            continue
        for lock in sorted(may.get(callee, ())):
            for held in call.held:
                graph.add(held, lock,
                          site_of(call.func, call.line) + " -> " + callee,
                          suppressed_reason=suppression(call.func,
                                                        call.line))
    return graph


def _cycles(edges: List[Tuple[str, str]]) -> List[List[str]]:
    """Strongly connected components with >1 node (Tarjan, iterative)."""
    adj: Dict[str, List[str]] = {}
    for a, b in edges:
        adj.setdefault(a, []).append(b)
        adj.setdefault(b, [])
    idx: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v0):
        work = [(v0, iter(adj[v0]))]
        idx[v0] = low[v0] = counter[0]
        counter[0] += 1
        stack.append(v0)
        on.add(v0)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in idx:
                    idx[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    work.append((w, iter(adj[w])))
                    advanced = True
                    break
                if w in on:
                    low[v] = min(low[v], idx[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == idx[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1:
                    sccs.append(sorted(comp))

    for v in sorted(adj):
        if v not in idx:
            strongconnect(v)
    return sccs


def canonical_order(graph: LockGraph) -> List[str]:
    """Deterministic topological order over ALL known locks (isolated
    locks included, ordered by name after their constrained peers' tiers).
    Cycles are broken by name so the order is always total — the cycle
    itself is reported separately."""
    edges = graph.active_edges()
    indeg: Dict[str, int] = {n: 0 for n in graph.nodes}
    adj: Dict[str, Set[str]] = {n: set() for n in graph.nodes}
    for a, b in edges:
        if b not in adj[a]:
            adj[a].add(b)
            indeg[b] += 1
    order: List[str] = []
    ready = sorted(n for n, d in indeg.items() if d == 0)
    while ready:
        n = ready.pop(0)
        order.append(n)
        for m in sorted(adj[n]):
            indeg[m] -= 1
            if indeg[m] == 0:
                ready.append(m)
        ready.sort()
    for n in sorted(graph.nodes):
        if n not in order:  # cycle member — break by name
            order.append(n)
    return order


def check(index: PackageIndex) -> List["Finding"]:
    from maggy_tpu.analysis import Finding

    graph = build_graph(index)
    findings: List[Finding] = []
    for key, reason in sorted(graph.suppressed.items()):
        if not reason:
            findings.append(Finding(
                "lockorder", graph.edges[key][0].split(":")[0], 0,
                "lock-order-ok suppression without a reason on edge "
                "{} -> {}".format(*key)))
    for comp in _cycles(graph.active_edges()):
        sites = []
        for a, b in graph.edges:
            if a in comp and b in comp:
                sites.append("{} -> {} at {}".format(
                    a, b, graph.edges[(a, b)][0]))
        path, line = "?", 0
        if sites:
            loc = sites[0].rsplit(" at ", 1)[1]
            path = loc.split(":")[0]
            try:
                line = int(loc.split(":")[1].split(" ")[0])
            except (IndexError, ValueError):
                line = 0
        findings.append(Finding(
            "lockorder", path, line,
            "lock-order cycle among {{{}}}: {}".format(
                ", ".join(comp), "; ".join(sorted(sites)))))
    return findings
