"""Checker 3: RPC conformance.

The wire vocabulary is stringly typed — a verb or payload key that
drifts between the producing and consuming side fails SILENTLY (the
retried-FINAL race hid behind exactly such a drift). Statically:

- every verb registered in a server's ``_handlers`` table (and every
  driver ``message_callbacks`` verb) must have at least one producer — a
  ``{"type": <verb>, ...}`` dict literal somewhere outside that verb's
  own handler (reply literals do not count as producers);
- payload-key agreement per verb: a key the handler reads via
  ``msg["k"]`` must be sent by some producer (a ``.get("k")`` read is
  only checked when every producer is a closed literal — ``**spread``
  producers may carry anything); a key producers send that no consumer
  ever reads is dead vocabulary and flagged too. Reads FLOW through
  calls: a handler passing ``msg`` to a driver method is credited with
  that method's reads (bounded-depth, package-local resolution).
- every server class must time its dispatches (``rpc.handle_ms.<verb>``
  in ``handle_message``) — the static pin behind the runtime
  TestVerbTimingConformance.

``# rpc-ok: <reason>`` on the registration line, a producer literal's
line, or a read line suppresses (with a written reason).

Wire augmentation: ``Client._request`` stamps ``partition_id`` and
``task_attempt`` onto every outgoing payload; those keys (and ``type``)
are exempt from key-agreement in both directions.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from maggy_tpu.analysis.astindex import ModuleInfo, PackageIndex

#: Keys the transport injects / every frame carries.
WIRE_KEYS = frozenset({"type", "partition_id", "task_attempt"})

_FLOW_DEPTH = 4


class Producer:
    __slots__ = ("verb", "keys", "open", "mod", "line", "func")

    def __init__(self, verb, keys, open_, mod, line, func):
        self.verb = verb
        self.keys = keys
        self.open = open_  # had a **spread — may send more keys
        self.mod = mod
        self.line = line
        self.func = func


class Consumer:
    """One handler/callback function for a verb."""

    __slots__ = ("verb", "qual", "node", "mod", "param", "reg_line")

    def __init__(self, verb, qual, node, mod, param, reg_line):
        self.verb = verb
        self.qual = qual
        self.node = node  # FunctionDef or Lambda
        self.mod = mod
        self.param = param
        self.reg_line = reg_line


def _enclosing_functions(tree) -> List[Tuple[ast.AST, ast.AST]]:
    """(func_node, parent_stack top) pairs — used to attribute dict
    literals to their enclosing function."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            out.append(node)
    return out


def _collect_producers(index: PackageIndex) -> List[Producer]:
    producers: List[Producer] = []
    for mod in index.modules.values():
        # Map each dict literal to its enclosing function qual (class
        # methods get Class.method, module funcs get mod.func).
        func_ranges: List[Tuple[int, int, str]] = []
        for cname, cls in mod.classes.items():
            for mname, fn in cls.methods.items():
                func_ranges.append((fn.lineno, _end(fn),
                                    "{}.{}".format(cname, mname)))
        for node in mod.tree.body:
            if isinstance(node, ast.FunctionDef):
                func_ranges.append((node.lineno, _end(node),
                                    "{}.{}".format(mod.modname,
                                                   node.name)))

        def enclosing(line: int) -> str:
            best = ""
            best_span = None
            for lo, hi, qual in func_ranges:
                if lo <= line <= hi:
                    span = hi - lo
                    if best_span is None or span < best_span:
                        best, best_span = qual, span
            return best

        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Dict):
                continue
            verb = None
            keys: Set[str] = set()
            open_ = False
            for k, v in zip(node.keys, node.values):
                if k is None:
                    open_ = True  # **spread
                    continue
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    keys.add(k.value)
                    if k.value == "type" and isinstance(v, ast.Constant) \
                            and isinstance(v.value, str):
                        verb = v.value
            if verb is None:
                continue
            producers.append(Producer(verb, keys - {"type"}, open_, mod,
                                      node.lineno, enclosing(node.lineno)))
        # var["k"] = ... augmentation of a literal assigned to a local:
        # credit the key to every producer literal assigned in the same
        # function to that name (the heartbeat's payload["rstats"]).
        for fn_node in _enclosing_functions(mod.tree):
            if isinstance(fn_node, ast.Lambda):
                continue
            assigns: Dict[str, List[int]] = {}
            for st in ast.walk(fn_node):
                if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                        and isinstance(st.targets[0], ast.Name) \
                        and isinstance(st.value, ast.Dict):
                    assigns.setdefault(st.targets[0].id,
                                       []).append(st.value.lineno)
            if not assigns:
                continue
            for st in ast.walk(fn_node):
                if isinstance(st, ast.Subscript) \
                        and isinstance(st.ctx, ast.Store) \
                        and isinstance(st.value, ast.Name) \
                        and st.value.id in assigns \
                        and isinstance(st.slice, ast.Constant) \
                        and isinstance(st.slice.value, str):
                    lines = assigns[st.value.id]
                    for p in producers:
                        if p.mod is mod and p.line in lines:
                            p.keys.add(st.slice.value)
    return producers


def _end(node) -> int:
    return getattr(node, "end_lineno", node.lineno)


def _handler_tables(index: PackageIndex) -> List[Consumer]:
    """Registered verbs from ``self._handlers[...]`` / ``.update(...)``
    and ``self.message_callbacks.update(...)`` across all classes."""
    consumers: List[Consumer] = []
    for mod in index.modules.values():
        for cname, cls in mod.classes.items():
            for mname, fn in cls.methods.items():
                for node in ast.walk(fn):
                    if isinstance(node, ast.Assign) and \
                            len(node.targets) == 1 and \
                            isinstance(node.targets[0], ast.Subscript):
                        sub = node.targets[0]
                        table = _table_name(sub.value)
                        if table and isinstance(sub.slice, ast.Constant):
                            verb = sub.slice.value
                            consumers.append(_consumer_for(
                                index, mod, cname, verb, node.value,
                                node.lineno))
                    elif isinstance(node, ast.Call) and \
                            isinstance(node.func, ast.Attribute) and \
                            node.func.attr == "update":
                        table = _table_name(node.func.value)
                        if table:
                            for kw in node.keywords:
                                if kw.arg is None:
                                    continue
                                consumers.append(_consumer_for(
                                    index, mod, cname, kw.arg, kw.value,
                                    node.lineno))
    return [c for c in consumers if c is not None]


def _table_name(node) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self" \
            and node.attr in ("_handlers", "message_callbacks"):
        return node.attr
    return None


def _consumer_for(index, mod, cname, verb, value,
                  reg_line) -> Optional[Consumer]:
    if isinstance(value, ast.Lambda):
        param = value.args.args[0].arg if value.args.args else None
        return Consumer(verb, "{}.<lambda:{}>".format(cname, verb),
                        value, mod, param, reg_line)
    if isinstance(value, ast.Attribute) and \
            isinstance(value.value, ast.Name) and value.value.id == "self":
        cls = index.class_info(cname)
        fn = index.mro_methods(cls).get(value.attr) if cls else None
        if fn is None:
            return None
        # Parameter holding the message: first non-self arg.
        args = [a.arg for a in fn.args.args if a.arg != "self"]
        param = args[0] if args else None
        owner = cname
        if value.attr not in (cls.methods if cls else {}):
            for base in (cls.bases if cls else []):
                bcls = index.class_info(base) if base else None
                if bcls is not None and value.attr in bcls.methods:
                    owner = bcls.name
                    break
        qual = "{}.{}".format(owner, value.attr)
        fmod = index.func_module.get(qual, mod)
        return Consumer(verb, qual, fn, fmod, param, reg_line)
    return None


def _reads_of(index: PackageIndex, qual: str, node, param: Optional[str],
              depth: int, seen: Set[Tuple[str, str]]
              ) -> Tuple[Dict[str, List[Tuple[ModuleInfo, int]]],
                         Dict[str, List[Tuple[ModuleInfo, int]]]]:
    """(hard_reads, soft_reads): key -> [(module, line)]. Hard =
    ``param["k"]`` subscripts (KeyError on absence); soft = ``.get`` /
    ``.pop`` with a default path. Flows into package-local callees that
    receive the param positionally."""
    hard: Dict[str, List[Tuple[ModuleInfo, int]]] = {}
    soft: Dict[str, List[Tuple[ModuleInfo, int]]] = {}
    if param is None or node is None or depth <= 0 or \
            (qual, param) in seen:
        return hard, soft
    seen = seen | {(qual, param)}
    mod = index.func_module.get(qual)
    body = node.body if isinstance(node.body, list) else [node.body]
    for st in body:
        for sub in ast.walk(st):
            if isinstance(sub, ast.Subscript) and \
                    isinstance(sub.value, ast.Name) and \
                    sub.value.id == param and \
                    isinstance(sub.slice, ast.Constant) and \
                    isinstance(sub.slice.value, str):
                hard.setdefault(sub.slice.value, []).append(
                    (mod, sub.lineno))
            elif isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr in ("get", "pop") and \
                    isinstance(sub.func.value, ast.Name) and \
                    sub.func.value.id == param and sub.args and \
                    isinstance(sub.args[0], ast.Constant) and \
                    isinstance(sub.args[0].value, str):
                soft.setdefault(sub.args[0].value, []).append(
                    (mod, sub.lineno))
    # Flow through calls passing the param positionally.
    for call in index.calls:
        if call.func != qual:
            continue
        positions = [i for i, name in call.args_from_params.items()
                     if name == param]
        if not positions:
            continue
        from maggy_tpu.analysis.lockorder import _resolve_callee

        callee = _resolve_callee(index, call)
        fn = index.functions.get(callee)
        if fn is None:
            continue
        params = [a.arg for a in fn.args.args]
        offset = 1 if params and params[0] == "self" else 0
        for pos in positions:
            if pos + offset < len(params):
                h, s = _reads_of(index, callee, fn,
                                 params[pos + offset], depth - 1, seen)
                for k, v in h.items():
                    hard.setdefault(k, []).extend(v)
                for k, v in s.items():
                    soft.setdefault(k, []).extend(v)
    return hard, soft


def check(index: PackageIndex) -> List["Finding"]:
    from maggy_tpu.analysis import Finding

    findings: List[Finding] = []
    producers = _collect_producers(index)
    consumers = _handler_tables(index)
    verbs = sorted({c.verb for c in consumers})
    handler_quals: Dict[str, Set[str]] = {}
    for c in consumers:
        handler_quals.setdefault(c.verb, set()).add(c.qual)

    def emit(mod: ModuleInfo, line: int, msg: str) -> None:
        # Annotation may sit on the flagged line or a comment just above
        # it (multi-line reasons span two comment lines).
        ann = mod.annotation_near(line, "rpc-ok", back=2)
        if ann is not None and not ann.value:
            findings.append(Finding("rpcconf", mod.path, line,
                                    "rpc-ok suppression without a reason"))
            return
        findings.append(Finding(
            "rpcconf", mod.path, line, msg,
            suppressed=ann is not None,
            reason=ann.value if ann is not None else None))

    reads_by_verb: Dict[str, Tuple[dict, dict]] = {}
    for verb in verbs:
        hard: Dict[str, list] = {}
        soft: Dict[str, list] = {}
        for c in consumers:
            if c.verb != verb:
                continue
            h, s = _reads_of(index, c.qual, c.node, c.param,
                             _FLOW_DEPTH, set())
            for k, v in h.items():
                hard.setdefault(k, []).extend(v)
            for k, v in s.items():
                soft.setdefault(k, []).extend(v)
        reads_by_verb[verb] = (hard, soft)

    for verb in verbs:
        verb_producers = [
            p for p in producers if p.verb == verb
            and p.func not in handler_quals.get(verb, set())
            and not _is_lambda_reply(p, verb)]
        reg = next(c for c in consumers if c.verb == verb)
        if not verb_producers:
            emit(reg.mod, reg.reg_line,
                 "verb {} is registered but has no producer ({{\"type\": "
                 "\"{}\"}} literal) anywhere in the package".format(
                     verb, verb))
            continue
        sent: Set[str] = set(WIRE_KEYS)
        all_closed = True
        for p in verb_producers:
            sent |= p.keys
            all_closed &= not p.open
        hard, soft = reads_by_verb[verb]
        for key in sorted(hard):
            if key in sent:
                continue
            mod, line = hard[key][0]
            emit(mod, line,
                 "handler for {} indexes msg[{!r}] but no producer sends "
                 "it (KeyError on delivery)".format(verb, key))
        if all_closed:
            for key in sorted(soft):
                if key in sent or key in hard:
                    continue
                mod, line = soft[key][0]
                emit(mod, line,
                     "handler for {} reads key {!r} that no producer "
                     "sends".format(verb, key))
        read_keys = set(hard) | set(soft)
        for p in verb_producers:
            for key in sorted(p.keys - read_keys - WIRE_KEYS):
                emit(p.mod, p.line,
                     "producer of {} sends key {!r} that no handler or "
                     "callback ever reads (dead vocabulary)".format(
                         verb, key))

    # Dispatch timing: every class registering _handlers must go through
    # a handle_message that records rpc.handle_ms.<verb>.
    seen_classes = set()
    for c in consumers:
        cname = c.qual.split(".")[0].split("<")[0]
        if cname in seen_classes:
            continue
        seen_classes.add(cname)
        cls = index.class_info(cname)
        if cls is None:
            continue
        if not any(
            isinstance(n, ast.Constant) and isinstance(n.value, str)
            and "rpc.handle_ms." in n.value
            for fn in index.mro_methods(cls).values()
            for n in ast.walk(fn)
        ):
            # Driver classes register message_callbacks, not wire verbs —
            # only classes with a _handlers table need the timer.
            if any(_registers_wire_handlers(cls, index)):
                emit(cls.module, cls.node.lineno,
                     "server class {} has a _handlers table but no "
                     "rpc.handle_ms.<verb> dispatch timing".format(cname))
    return findings


def _registers_wire_handlers(cls, index) -> List[bool]:
    out = []
    for fn in index.mro_methods(cls).values():
        for node in ast.walk(fn):
            if isinstance(node, (ast.Assign, ast.Call)):
                tgt = node.targets[0].value if (
                    isinstance(node, ast.Assign) and node.targets and
                    isinstance(node.targets[0], ast.Subscript)) else (
                    node.func.value if isinstance(node, ast.Call) and
                    isinstance(node.func, ast.Attribute) and
                    node.func.attr == "update" else None)
                if tgt is not None and _table_name(tgt) == "_handlers":
                    out.append(True)
    return out


def _is_lambda_reply(p: Producer, verb: str) -> bool:
    """A literal inside a lambda registered for the same verb (the QUERY
    reply) encloses in ``_register_handlers`` itself — reply, not
    producer."""
    return p.func.endswith("._register_handlers")
