"""Checker 2 (runtime half): the lock-order witness.

The static lock-order graph (lockorder.py) sees every *possible*
acquired-while-holding edge; the witness sees the edges that *actually
happen*. Installed, it interposes on ``threading.Lock`` / ``RLock`` /
``Condition`` construction: locks allocated from a maggy_tpu source
line are wrapped (the allocation site resolves to its static
declaration through ``PackageIndex.decl_by_site``, so the runtime lock
carries the same canonical ``Owner.attr`` name the static graph uses);
locks allocated anywhere else pass through untouched, so jax/stdlib
internals pay nothing.

Every acquisition of a wrapped lock while another wrapped lock is held
records the edge ``held -> acquired``; an edge the static canonical
order forbids (the holder sorts *after* the acquiree) is a **violation**
— the dynamic face of a lock-order cycle, caught the first time the two
locks actually interleave rather than the first time they deadlock.

Opt-in and env-gated like chaos: set ``MAGGY_TPU_LOCK_WITNESS=1`` (or
call :func:`install` directly) *before* the objects under test build
their locks — module-import-time locks predate installation and stay
unwrapped (documented in docs/analysis.md). The chaos soaks
(``python -m maggy_tpu.chaos``) install it so every invariant run
doubles as a dynamic race check; one tier-1 test runs a full experiment
under it and asserts zero forbidden edges.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Dict, List, Optional, Tuple

#: Env var arming the witness (mirrors MAGGY_TPU_CHAOS gating style).
ENV_VAR = "MAGGY_TPU_LOCK_WITNESS"

#: The real factories, bound at import so install/uninstall can't lose
#: them however many times they run.
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition


def enabled_by_env() -> bool:
    return os.environ.get(ENV_VAR, "").lower() in ("1", "true", "yes", "on")


class Violation:
    """One forbidden acquisition edge: ``held`` sorts after ``acquired``
    in the canonical order, yet a thread acquired ``acquired`` while
    holding ``held``."""

    __slots__ = ("held", "acquired", "site", "thread")

    def __init__(self, held: str, acquired: str, site: str, thread: str):
        self.held = held
        self.acquired = acquired
        self.site = site
        self.thread = thread

    def __repr__(self):
        return ("lock-order violation: acquired {} while holding {} "
                "(canonical order says {} first) at {} [{}]".format(
                    self.acquired, self.held, self.acquired, self.site,
                    self.thread))


class Witness:
    """Per-process edge recorder + forbidden-edge checker."""

    def __init__(self, order: List[str]):
        #: canonical name -> position; edges between named locks are
        #: checked, edges involving site-named (``rel/path.py:NN``) locks
        #: are recorded but can't be forbidden (the static graph excludes
        #: them from ordering too).
        self.positions: Dict[str, int] = {n: i for i, n in enumerate(order)}
        self._mu = _REAL_LOCK()  # real lock: guards edges/violations
        self.edges: Dict[Tuple[str, str], str] = {}  # edge -> example site
        self.violations: List[Violation] = []
        self._tls = threading.local()

    # -- per-thread held stack -------------------------------------------

    def _held(self) -> List[Tuple[int, str]]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def note_acquire(self, lock_id: int, name: str) -> None:
        stack = self._held()
        site = self._call_site()
        thread = threading.current_thread().name
        with self._mu:
            for _, held_name in stack:
                if held_name == name:
                    continue  # two instances of one decl: unordered
                edge = (held_name, name)
                if edge not in self.edges:
                    self.edges[edge] = site
                # Checked per OCCURRENCE, not per first-seen edge: the
                # env-armed witness is shared across soaks, and a soak
                # counts only violations recorded after its own install
                # point — dedup here would hide a repeat offense from
                # every soak but the first.
                ph = self.positions.get(held_name)
                pa = self.positions.get(name)
                if ph is not None and pa is not None and ph > pa:
                    self.violations.append(
                        Violation(held_name, name, site, thread))
        stack.append((lock_id, name))

    def note_release(self, lock_id: int) -> None:
        stack = self._held()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] == lock_id:
                del stack[i]
                return

    @staticmethod
    def _call_site() -> str:
        # First frame outside this module and threading: the acquire site.
        f = sys._getframe(2)
        skip = (__file__, threading.__file__)
        while f is not None and f.f_code.co_filename in skip:
            f = f.f_back
        if f is None:
            return "?"
        return "{}:{}".format(f.f_code.co_filename, f.f_lineno)

    # -- reporting --------------------------------------------------------

    def snapshot(self) -> Dict[str, object]:
        with self._mu:
            return {
                "edges": sorted("{} -> {}".format(a, b)
                                for (a, b) in self.edges),
                "edge_count": len(self.edges),
                "violations": [repr(v) for v in self.violations],
            }

    def check(self) -> None:
        """Raise if any forbidden edge was observed."""
        with self._mu:
            if self.violations:
                raise AssertionError(
                    "lock-order witness: {} forbidden edge(s):\n{}".format(
                        len(self.violations),
                        "\n".join(repr(v) for v in self.violations)))


class _WitnessLock:
    """Wraps one real Lock/RLock, reporting acquisitions to the witness.

    Implements the full Condition-backing protocol (``_release_save`` /
    ``_acquire_restore`` / ``_is_owned``) so ``threading.Condition(
    wrapped_rlock)`` — the fleet scheduler's wake condition — keeps its
    reentrancy semantics through the wrapper.
    """

    __slots__ = ("_inner", "_name", "_witness", "_reentrant", "_tls")

    def __init__(self, inner, name: str, witness: Witness, reentrant: bool):
        self._inner = inner
        self._name = name
        self._witness = witness
        self._reentrant = reentrant
        self._tls = threading.local()

    def _depth(self) -> int:
        return getattr(self._tls, "depth", 0)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            d = self._depth()
            self._tls.depth = d + 1
            if d == 0:  # reentrant re-acquire adds no edge
                self._witness.note_acquire(id(self), self._name)
        return got

    def release(self) -> None:
        self._inner.release()
        d = self._depth()
        self._tls.depth = max(0, d - 1)
        if d <= 1:
            self._witness.note_release(id(self))

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self) -> bool:
        return self._inner.locked()

    # Condition protocol (threading.Condition probes these with getattr).

    def _release_save(self):
        d = self._depth()
        self._tls.depth = 0
        self._witness.note_release(id(self))
        if hasattr(self._inner, "_release_save"):
            return (self._inner._release_save(), d)
        self._inner.release()
        return (None, d)

    def _acquire_restore(self, state):
        saved, d = state
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(saved)
        else:
            self._inner.acquire()
        self._tls.depth = d
        self._witness.note_acquire(id(self), self._name)

    def _is_owned(self) -> bool:
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        return self._depth() > 0

    def __repr__(self):
        return "<WitnessLock {} of {!r}>".format(self._name,
                                                 self._inner)


class _Installed:
    """Module state for one install(): the witness plus the site map."""

    def __init__(self, witness: Witness,
                 decls: Dict[Tuple[str, int], object], root: str):
        self.witness = witness
        self.decls = decls
        self.root = os.path.abspath(root) + os.sep


_active: Optional[_Installed] = None


def _site_name(inst: _Installed) -> Optional[str]:
    """Canonical name for a lock allocated at the caller's caller, or
    None when the allocation is outside the package (pass through)."""
    f = sys._getframe(2)
    path = os.path.abspath(f.f_code.co_filename)
    decl = inst.decls.get((path, f.f_lineno))
    if decl is not None:
        # Condition(self.X) aliases collapse onto the underlying lock.
        alias = getattr(decl, "alias_of", None)
        owner = getattr(decl, "owner", "?")
        return "{}.{}".format(owner, alias) if alias \
            else getattr(decl, "name", None)
    if path.startswith(inst.root):
        return "{}:{}".format(os.path.relpath(path, inst.root), f.f_lineno)
    return None


def _make_lock(*a, **kw):
    inst = _active
    inner = _REAL_LOCK(*a, **kw)
    if inst is None:
        return inner
    name = _site_name(inst)
    if name is None:
        return inner
    return _WitnessLock(inner, name, inst.witness, reentrant=False)


def _make_rlock(*a, **kw):
    inst = _active
    inner = _REAL_RLOCK(*a, **kw)
    if inst is None:
        return inner
    name = _site_name(inst)
    if name is None:
        return inner
    return _WitnessLock(inner, name, inst.witness, reentrant=True)


def install(root: Optional[str] = None) -> Witness:
    """Compute the static oracle, patch the threading factories, return
    the live witness. Idempotent: a second install returns the active
    witness."""
    global _active
    if _active is not None:
        return _active.witness
    from maggy_tpu.analysis import package_root, parse_package
    from maggy_tpu.analysis.lockorder import build_graph, canonical_order

    root = root or package_root()
    index = parse_package(root)
    order = canonical_order(build_graph(index))
    inst = _Installed(Witness(order), index.decl_by_site(), root)
    _active = inst
    threading.Lock = _make_lock
    threading.RLock = _make_rlock
    return inst.witness


def uninstall() -> Optional[Witness]:
    """Restore the real factories; returns the retired witness (its
    recorded edges/violations stay readable). Already-wrapped locks keep
    working — their witness just stops gaining new allocations."""
    global _active
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    inst, _active = _active, None
    return inst.witness if inst is not None else None


def active_witness() -> Optional[Witness]:
    return _active.witness if _active is not None else None


def maybe_install() -> Optional[Witness]:
    """Install iff the env arms it (the chaos CLI / soak entry point)."""
    return install() if enabled_by_env() else active_witness()
