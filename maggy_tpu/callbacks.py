"""Training-loop callbacks that stream metrics to the driver.

Parity: reference `maggy/callbacks.py` — `KerasBatchEnd`/`KerasEpochEnd`
report a chosen metric via `reporter.broadcast` at batch/epoch boundaries
(:20-66). The TPU-native loop is a plain Python loop over jitted steps, so
callbacks are simple objects invoked by `maggy_tpu.train.Trainer.fit` or by
user loops; a Keras-compatible shim is provided for tf.keras users.
"""

from __future__ import annotations

from typing import Optional


class BatchEnd:
    """Report ``metric`` every batch; step = global batch index."""

    def __init__(self, reporter, metric: str = "loss"):
        self.reporter = reporter
        self.metric = metric
        self._step = -1

    def __call__(self, logs: dict, step: Optional[int] = None) -> None:
        value = logs.get(self.metric)
        if value is None:
            return
        self._step = step if step is not None else self._step + 1
        self.reporter.broadcast(float(value), step=self._step)


class EpochEnd(BatchEnd):
    """Report ``metric`` once per epoch; step = epoch index."""


def keras_reporter_callbacks(reporter, batch_metric: Optional[str] = None,
                             epoch_metric: Optional[str] = "acc"):
    """tf.keras-compatible callbacks (the reference's KerasBatchEnd /
    KerasEpochEnd shapes). Gated: requires tensorflow."""
    from tensorflow import keras  # noqa: PLC0415

    cbs = []
    if batch_metric:
        class _Batch(keras.callbacks.Callback):
            def __init__(self):
                super().__init__()
                self._step = -1

            def on_train_batch_end(self, batch, logs=None):
                if logs and batch_metric in logs:
                    self._step += 1
                    reporter.broadcast(float(logs[batch_metric]), step=self._step)

        cbs.append(_Batch())
    if epoch_metric:
        class _Epoch(keras.callbacks.Callback):
            def on_epoch_end(self, epoch, logs=None):
                if logs and epoch_metric in logs:
                    reporter.broadcast(float(logs[epoch_metric]), step=epoch)

        cbs.append(_Epoch())
    return cbs
