"""Chaos engine: deterministic fault injection + journal-checked recovery.

The fault-tolerance claims (runner death survived, trials requeued, RPC
retried, preemption tolerated) become first-class, seeded inputs instead
of incidental races:

- ``plan``      — declarative JSON ``FaultPlan`` (kill/stall/preempt a
                  runner, drop/delay/sever messages, fail env writes),
                  expanded deterministically from one seed;
- ``injectors`` — the ``ChaosEngine`` behind no-op-by-default hook points
                  in the RPC server/client, runner pools, heartbeat
                  bookkeeping, and the environment's write paths; armed
                  via ``config.chaos`` or ``MAGGY_TPU_CHAOS=<plan.json>``;
- ``harness``   — soak runner that executes a lagom experiment under a
                  plan, journals every injection, then replays the
                  telemetry journal and asserts the recovery invariants
                  (no lost trial, no duplicate FINAL, bounded requeue,
                  experiment completes);
- CLI           — ``python -m maggy_tpu.chaos --seed 7 [--plan p.json]``.

See docs/chaos.md.
"""

from maggy_tpu.chaos.injectors import (ChaosEngine, ChaosKilled,
                                       active_engine, arm, disarm)
from maggy_tpu.chaos.plan import KINDS, RUNNER_KINDS, FaultPlan, FaultSpec

__all__ = [
    "FaultPlan", "FaultSpec", "KINDS", "RUNNER_KINDS",
    "ChaosEngine", "ChaosKilled", "active_engine", "arm", "disarm",
    # lazy (import cycle: harness pulls in the experiment stack, which
    # pulls in the RPC layer, which imports chaos.injectors):
    "default_plan", "run_soak", "check_invariants", "assert_invariants",
]

_HARNESS_NAMES = ("default_plan", "run_soak", "check_invariants",
                  "assert_invariants")


def __getattr__(name):
    if name in _HARNESS_NAMES:
        from maggy_tpu.chaos import harness

        return getattr(harness, name)
    raise AttributeError("module {!r} has no attribute {!r}".format(
        __name__, name))
