"""``python -m maggy_tpu.chaos`` — run a deterministic chaos soak.

Executes a real local lagom experiment (closed-form trials over the
thread pool) under a fault plan, prints the invariant report as JSON, and
exits non-zero if any recovery invariant is violated. With no ``--plan``
the standard soak runs: a runner killed mid-trial, a false preemption,
5% METRIC drops, and every 5th FINAL's reply severed.

    python -m maggy_tpu.chaos --seed 7
    python -m maggy_tpu.chaos --plan my_plan.json --trials 20 --workers 4
    python -m maggy_tpu.chaos --show-schedule --seed 7   # no experiment

``--show-schedule`` prints the plan's deterministic decision expansion
(the fingerprint): run it twice with the same seed and diff the output to
see the same-plan-same-schedule guarantee directly.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m maggy_tpu.chaos",
        description="Deterministic fault-injection soak against a real "
                    "local lagom run.")
    ap.add_argument("--plan", help="path to a FaultPlan JSON (default: the "
                                   "built-in kill+preempt+drop+sever soak)")
    ap.add_argument("--seed", type=int, default=None,
                    help="plan seed (default: the plan file's embedded "
                         "seed, or 7 for the built-in plan)")
    ap.add_argument("--trials", type=int, default=12)
    ap.add_argument("--workers", type=int, default=3)
    ap.add_argument("--pool", default="thread",
                    choices=["thread", "process"],
                    help="runner substrate (process = real SIGKILL/SIGSTOP)")
    ap.add_argument("--hb-loss-timeout", type=float, default=0.6,
                    help="seconds of heartbeat silence before a runner is "
                         "declared lost")
    ap.add_argument("--show-schedule", action="store_true",
                    help="print the plan's deterministic decision "
                         "expansion and exit (no experiment)")
    args = ap.parse_args(argv)

    from maggy_tpu.chaos import harness
    from maggy_tpu.chaos.plan import FaultPlan

    if args.plan:
        plan = FaultPlan.load(args.plan)
        # A reproduction run must honor the plan file's embedded seed;
        # only an EXPLICIT --seed overrides it.
        if args.seed is not None:
            plan.seed = args.seed
    else:
        plan = harness.default_plan(seed=7 if args.seed is None
                                    else args.seed)

    if args.show_schedule:
        print(json.dumps({"seed": plan.seed,
                          "schedule": plan.fingerprint()}, indent=2))
        return 0

    if args.pool == "process":
        # The train fn must be module-level picklable for spawn.
        train_fn = harness._soak_train_fn
    else:
        train_fn = None
    report = harness.run_soak(
        plan=plan, seed=plan.seed, train_fn=train_fn,
        num_trials=args.trials, workers=args.workers, pool=args.pool,
        hb_loss_timeout=args.hb_loss_timeout)
    print(json.dumps(report, indent=2, default=str))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
