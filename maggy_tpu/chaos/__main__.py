"""``python -m maggy_tpu.chaos`` — run a deterministic chaos soak.

Executes a real local lagom experiment (closed-form trials over the
thread pool) under a fault plan, prints the invariant report as JSON, and
exits non-zero if any recovery invariant is violated. With no ``--plan``
the standard soak runs: a runner killed mid-trial, a false preemption,
5% METRIC drops, and every 5th FINAL's reply severed.

    python -m maggy_tpu.chaos --seed 7
    python -m maggy_tpu.chaos --plan my_plan.json --trials 20 --workers 4
    python -m maggy_tpu.chaos --stall                    # health-engine soak
    python -m maggy_tpu.chaos --piggyback                # hand-off soak
    python -m maggy_tpu.chaos --preempt                  # preemption soak
    python -m maggy_tpu.chaos --agent                    # agent-kill soak
    python -m maggy_tpu.chaos --sink                     # sink-kill soak
    python -m maggy_tpu.chaos --driver                   # driver-kill soak
    python -m maggy_tpu.chaos --fork                     # fork-kill soak
    python -m maggy_tpu.chaos --goodput                  # fault-free ledger soak
    python -m maggy_tpu.chaos --vmap                     # vectorized-block soak
    python -m maggy_tpu.chaos --show-schedule --seed 7   # no experiment

``--preempt`` runs the graceful-preemption soak: a mid-trial trial is
preempted through the driver (the fleet scheduler's checkpoint-assisted
mechanism); invariant 7 asserts exactly one FINAL and a resume from the
acked checkpoint step, never step 0.

``--stall`` runs the straggler soak instead: one runner frozen mid-trial
below the heartbeat-loss bound, asserting the live health engine flags
it (invariant 5, docs/telemetry.md).

``--piggyback`` kills a runner between receiving a TRIAL piggybacked on
its FINAL reply and that trial's first heartbeat: the assignment exists
only in the reservation table at kill time, and the soak asserts the
trial is requeued exactly once (invariant 6) — no lost trial, no
duplicate FINAL, no double requeue.

``--show-schedule`` prints the plan's deterministic decision expansion
(the fingerprint): run it twice with the same seed and diff the output to
see the same-plan-same-schedule guarantee directly.

Every soak additionally runs under the lock-order witness
(maggy_tpu.analysis.witness) unless ``--no-witness``: the acquisition
edges the experiment actually takes are checked against the static
canonical lock order (docs/analysis.md), and any forbidden edge is
reported alongside the invariant violations — an invariant run doubles
as a dynamic race check.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m maggy_tpu.chaos",
        description="Deterministic fault-injection soak against a real "
                    "local lagom run.")
    ap.add_argument("--plan", help="path to a FaultPlan JSON (default: the "
                                   "built-in kill+preempt+drop+sever soak)")
    ap.add_argument("--seed", type=int, default=None,
                    help="plan seed (default: the plan file's embedded "
                         "seed, or 7 for the built-in plan)")
    ap.add_argument("--trials", type=int, default=12)
    ap.add_argument("--workers", type=int, default=3)
    ap.add_argument("--pool", default="thread",
                    choices=["thread", "process"],
                    help="runner substrate (process = real SIGKILL/SIGSTOP)")
    ap.add_argument("--hb-loss-timeout", type=float, default=None,
                    help="seconds of heartbeat silence before a runner is "
                         "declared lost (default 0.6; with --stall the "
                         "default rises to 10 so the loss scan stays "
                         "blind to the stall — an explicit value is "
                         "honored either way)")
    ap.add_argument("--stall", action="store_true",
                    help="run the straggler soak: a runner stalled below "
                         "the loss bound; the health engine must flag it "
                         "(invariant 5)")
    ap.add_argument("--piggyback", action="store_true",
                    help="run the pipelined hand-off soak: a runner killed "
                         "between receiving a piggybacked TRIAL and its "
                         "first heartbeat; the trial must be requeued "
                         "exactly once (invariant 6)")
    ap.add_argument("--preempt", action="store_true",
                    help="run the graceful-preemption soak: a mid-trial "
                         "checkpoint-assisted preemption (the fleet "
                         "scheduler's mechanism) — exactly one FINAL, and "
                         "the trial resumes from its checkpoint step, not "
                         "step 0 (invariant 7)")
    ap.add_argument("--gang", action="store_true",
                    help="run the gang-revocation soak: a mixed 1-chip + "
                         "4-chip-fsdp ASHA sweep with one member of the "
                         "first assembled gang killed mid-trial — the "
                         "whole gang lease must be revoked and the trial "
                         "requeued exactly once (invariant 8); run under "
                         "JAX_PLATFORMS=cpu with "
                         "XLA_FLAGS=--xla_force_host_platform_device_"
                         "count=8")
    ap.add_argument("--fork", action="store_true",
                    help="run the checkpoint-forking soak: an ASHA sweep "
                         "whose promotions fork their rung parents' "
                         "checkpoints, with the runner holding the first "
                         "forked trial killed at dispatch — the trial "
                         "must requeue exactly once and resume from the "
                         "SAME fork point, genealogy intact; plus one "
                         "fork across lagom(..., resume=True) driver "
                         "failover (invariant 14)")
    ap.add_argument("--goodput", action="store_true",
                    help="run the fault-free goodput-ledger control soak "
                         "(invariant 15's clean half): with zero faults "
                         "injected the chip-time fold must book ~zero "
                         "rework and keep the unaccounted residual at or "
                         "under 5% of held chip-time")
    ap.add_argument("--vmap", action="store_true",
                    help="run the vectorized-block soak: a vmap_lanes=4 "
                         "sweep with the runner holding the first "
                         "assembled K-lane block killed mid-block — "
                         "every live lane must requeue exactly once as "
                         "an individual scalar trial (non-leader lanes "
                         "with reason vmap_block_lost), no phantom "
                         "FINALs, no lane lost to the block seam "
                         "(invariant 16)")
    ap.add_argument("--agent", action="store_true",
                    help="run the remote-agent soak: real agent daemon "
                         "processes (python -m maggy_tpu.fleet agent) "
                         "serve leases over sockets and one is SIGKILLed "
                         "mid-lease — the lease must be revoked "
                         "(reason=agent_lost) and the trial requeued "
                         "exactly once (invariant 11)")
    ap.add_argument("--driver", action="store_true",
                    help="run the driver-failover soak: a real driver "
                         "process SIGKILLed mid-sweep over surviving "
                         "runner-agent processes, restarted with "
                         "resume=True — journal replay must rebuild the "
                         "control plane and the sweep must complete with "
                         "no trial lost, no duplicate FINAL, and no "
                         "completed trial re-run (invariant 13)")
    ap.add_argument("--sink", action="store_true",
                    help="run the journal-sink soak: tenants ship their "
                         "telemetry through the fleet's journal sink, "
                         "the sink is killed mid-soak and restarted — "
                         "shippers must degrade to local journals and "
                         "re-ship on reconnect with zero lost events, "
                         "zero duplicates per event id, and zero "
                         "experiment failures (invariant 12)")
    ap.add_argument("--show-schedule", action="store_true",
                    help="print the plan's deterministic decision "
                         "expansion and exit (no experiment)")
    ap.add_argument("--obs", action="store_true",
                    help="run the soak with the observability plane on "
                         "(invariant 9): a concurrent scraper asserts "
                         "/metrics + /status + /healthz stay responsive "
                         "and truthful under the faults, and a stalled "
                         "partition's first health flag must journal "
                         "exactly one profile_captured artifact")
    ap.add_argument("--no-witness", action="store_true",
                    help="disable the runtime lock-order witness "
                         "(maggy_tpu.analysis.witness; on by default so "
                         "every soak doubles as a dynamic race check — "
                         "forbidden acquisition edges are reported "
                         "alongside invariant violations)")
    args = ap.parse_args(argv)

    from maggy_tpu.chaos import harness
    from maggy_tpu.chaos.plan import FaultPlan

    modes = [m for m in ("stall", "piggyback", "preempt", "gang", "agent",
                         "sink", "driver", "fork", "goodput", "vmap")
             if getattr(args, m)]
    if args.plan and modes:
        ap.error("--{} uses a built-in plan; drop --plan".format(modes[0]))
    if len(modes) > 1:
        ap.error("pick one of --stall / --piggyback / --preempt / --gang "
                 "/ --agent / --sink / --driver / --fork / --goodput "
                 "/ --vmap")
    if args.vmap:
        # The vmap soak owns its whole config (float-only searchspace so
        # every trial is program-compatible, vmap_lanes=4, 2 workers) —
        # delegate wholesale.
        report = harness.run_vmap_soak(
            seed=7 if args.seed is None else args.seed,
            num_trials=args.trials,
            lock_witness=not args.no_witness)
        print(json.dumps(report, indent=2, default=str))
        return 0 if report["ok"] else 1
    if args.goodput:
        # The goodput control soak owns its whole config (an EMPTY
        # fault plan — the gate is on the ledger, not a recovery) —
        # delegate wholesale.
        report = harness.run_goodput_soak(
            seed=7 if args.seed is None else args.seed,
            num_trials=args.trials, workers=args.workers,
            lock_witness=not args.no_witness)
        print(json.dumps(report, indent=2, default=str))
        return 0 if report["ok"] else 1
    if args.fork:
        # The fork soak owns its whole config (forking ASHA sweep +
        # checkpointing train fn + the synthetic driver-failover half) —
        # delegate wholesale.
        report = harness.run_fork_soak(
            seed=7 if args.seed is None else args.seed,
            lock_witness=not args.no_witness)
        print(json.dumps(report, indent=2, default=str))
        return 0 if report["ok"] else 1
    if args.driver:
        # The driver soak owns its whole topology (driver + runner-agent
        # SUBPROCESSES; the kill is harness-injected — SIGKILL takes the
        # chaos engine down with the process it targets, so no in-process
        # plan can record it) — delegate wholesale.
        from maggy_tpu.chaos.driver_soak import run_driver_soak

        report = run_driver_soak(seed=7 if args.seed is None else args.seed,
                                 lock_witness=not args.no_witness)
        print(json.dumps(report, indent=2, default=str))
        return 0 if report["ok"] else 1
    if args.sink:
        # The sink soak owns its whole topology (a fleet whose sink
        # tenant is detached/re-attached mid-run; the kill is
        # harness-injected — the sink is fleet infrastructure no
        # experiment plan can target) — delegate wholesale.
        from maggy_tpu.fleet.soak import run_sink_soak

        report = run_sink_soak(seed=7 if args.seed is None else args.seed,
                               lock_witness=not args.no_witness)
        print(json.dumps(report, indent=2, default=str))
        return 0 if report["ok"] else 1
    if args.agent:
        # The agent soak owns its whole topology (a fleet with real
        # agent subprocesses; the kill is harness-injected, not a
        # plan.py fault — the plan's pool-level kill cannot reach an
        # agent in another OS process) — delegate wholesale.
        from maggy_tpu.fleet.soak import run_agent_soak

        report = run_agent_soak(trials=min(args.trials, 6),
                                seed=7 if args.seed is None else args.seed,
                                lock_witness=not args.no_witness)
        print(json.dumps(report, indent=2, default=str))
        return 0 if report["ok"] else 1
    if args.plan:
        plan = FaultPlan.load(args.plan)
        # A reproduction run must honor the plan file's embedded seed;
        # only an EXPLICIT --seed overrides it.
        if args.seed is not None:
            plan.seed = args.seed
    elif args.stall:
        plan = harness.stall_plan(seed=7 if args.seed is None
                                  else args.seed)
    elif args.piggyback:
        plan = harness.piggyback_plan(seed=7 if args.seed is None
                                      else args.seed)
    elif args.preempt:
        plan = harness.preempt_plan(seed=7 if args.seed is None
                                    else args.seed)
    elif args.gang:
        plan = harness.gang_plan(seed=7 if args.seed is None
                                 else args.seed)
    else:
        plan = harness.default_plan(seed=7 if args.seed is None
                                    else args.seed)

    if args.show_schedule:
        print(json.dumps({"seed": plan.seed,
                          "schedule": plan.fingerprint()}, indent=2))
        return 0

    if args.gang:
        # The gang soak owns its whole config (mixed ASHA sweep over an
        # 8-runner fleet with GangSpec budgets) — delegate wholesale.
        report = harness.run_gang_soak(
            seed=plan.seed, num_trials=args.trials,
            lock_witness=not args.no_witness)
        print(json.dumps(report, indent=2, default=str))
        return 0 if report["ok"] else 1
    if args.preempt:
        # The preempt soak needs a checkpointing, ctx-aware trial so the
        # resume provably restarts from the checkpoint step.
        train_fn = harness.ckpt_train_fn
    elif args.pool == "process":
        # The train fn must be module-level picklable for spawn.
        train_fn = harness._soak_train_fn
    else:
        train_fn = None
    hb_loss = args.hb_loss_timeout
    soak_kwargs = dict(hb_loss_timeout=0.6 if hb_loss is None else hb_loss)
    if args.stall:
        # The loss scan should stay blind to the stall (that's the
        # point), so the DEFAULT loss bound rises above the stall and
        # the watchdog tightens — but an explicit --hb-loss-timeout is
        # the operator's call and is honored as given.
        soak_kwargs = dict(
            hb_loss_timeout=10.0 if hb_loss is None else hb_loss,
            config_overrides={"health_hang_factor": 10.0,
                              "health_interval_s": 0.1})
    report = harness.run_soak(
        plan=plan, seed=plan.seed, train_fn=train_fn,
        num_trials=args.trials, workers=args.workers, pool=args.pool,
        lock_witness=not args.no_witness, obs=args.obs, **soak_kwargs)
    print(json.dumps(report, indent=2, default=str))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
