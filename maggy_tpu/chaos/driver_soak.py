"""Chaos invariant 13: SIGKILL the driver mid-sweep, restart, recover.

The driver is the last single point of failure the chaos suite had not
killed: runners, agents, and the journal sink all die and recover
(invariants 1-12), but a dead driver used to take the trial store,
reservations, and optimizer state with it. Crash-only recovery (PR 14,
core/driver/recovery.py) makes the journal the recovery source of truth
— this soak proves it with REAL processes:

1. a driver process (``python -m maggy_tpu.chaos.driver_soak --child``)
   runs a seeded remote-pool sweep, fsync-armed journal, witness on;
2. runner agents (``python -m maggy_tpu.runner``) join over the socket
   and survive the driver (their retry horizon is raised via
   MAGGY_TPU_CLIENT_MAX_RETRIES so they outlive the restart window);
3. once the journal shows progress, the harness SIGKILLs the driver and
   appends the ``kill_driver`` chaos record to the now-quiesced journal
   (harness-injected like kill_agent/kill_sink — the fault kills the
   process that owns the chaos engine, so no in-process plan can record
   it);
4. a new driver child restarts with ``resume=True``: it adopts the run
   dir (``.driver_epoch.N``), comes back on the same secret and port,
   replays the journal, re-adopts the surviving runners, and finishes
   the sweep;
5. the harness replays the final journal through ``check_invariants``:
   invariant 13 (no trial lost, no duplicate FINAL, completed trials
   never re-run, every kill followed by a recovered incarnation) plus
   the standard suite, and aggregates the children's lock-order witness
   snapshots (zero forbidden edges).

``python -m maggy_tpu.chaos --driver`` runs it; ``bench.py --failover``
wraps it with an MTTR gate and a replayed-vs-uninterrupted parity check.
"""

from __future__ import annotations

import glob
import json
import os
import signal
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

#: The soak's fixed app id: resume must find the same run dir across
#: driver incarnations (MAGGY_TPU_APP_ID pins it for the children).
APP_ID = "driversoak"

#: Seconds a surviving runner keeps retrying a dead control plane — must
#: cover driver restart (spawn + jax import + replay). 20 retries at the
#: 2 s backoff cap is ~35 s.
CHILD_CLIENT_RETRIES = 20


def failover_train_fn(lr, units, reporter=None):
    """Module-level (agents import it by dotted path) paced trial:
    ~3-4 s of heartbeating steps so a driver kill lands mid-trial and the
    surviving runner's FINAL arrives AFTER the restart — the retried-
    FINAL-across-incarnations path the soak exists to exercise."""
    import time as _time

    acc = 1.0 - ((lr - 0.1) ** 2 + ((units - 32) / 64.0) ** 2)
    for step in range(24):
        _time.sleep(0.15)
        if reporter is not None:
            reporter.broadcast(acc * (step + 1) / 24.0, step=step)
    return {"metric": acc}


# ---------------------------------------------------------------- children


def child_main(argv: Optional[List[str]] = None) -> int:
    """One driver incarnation (``--child``): run the soak's sweep over a
    remote runner pool; with ``--resume``, adopt and recover the
    interrupted run. Dumps a lock-order witness snapshot next to the
    base dir so the parent can aggregate edges/violations."""
    import argparse

    ap = argparse.ArgumentParser(prog="python -m maggy_tpu.chaos.driver_soak")
    ap.add_argument("--child", action="store_true", required=True)
    ap.add_argument("--base-dir", required=True)
    ap.add_argument("--trials", type=int, default=8)
    ap.add_argument("--workers", type=int, default=3)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--resume", action="store_true")
    # Above the runner-client's 2 s retry-backoff cap with margin: a
    # surviving runner's first post-restart contact must land inside the
    # recovered record's one liveness window, or a false loss would
    # requeue a live runner's trial (correct but adoption-less).
    ap.add_argument("--hb-loss-timeout", type=float, default=6.0)
    args = ap.parse_args(argv)

    # Witness first: locks constructed after install are wrapped.
    from maggy_tpu.analysis import witness as _witness

    wit = _witness.install() if _witness.enabled_by_env() else None

    from maggy_tpu import OptimizationConfig, Searchspace, experiment, util

    util.apply_platform_env()
    config = OptimizationConfig(
        name="driver_soak", num_trials=args.trials,
        optimizer="randomsearch",
        searchspace=Searchspace(lr=("DOUBLE", [0.0, 0.2]),
                                units=("INTEGER", [8, 64])),
        direction="max", num_workers=args.workers, pool="remote",
        bind_host="127.0.0.1", hb_interval=0.25,
        hb_loss_timeout=args.hb_loss_timeout, seed=args.seed,
        es_policy="none", experiment_dir=args.base_dir,
        resume=args.resume)
    rc = 0
    try:
        result = experiment.lagom(failover_train_fn, config)
        print(json.dumps({"ok": True,
                          "num_trials": result.get("num_trials"),
                          "best_val": result.get("best_val")}), flush=True)
    except BaseException as e:  # noqa: BLE001 - the parent reads the verdict
        print(json.dumps({"ok": False, "error": repr(e)}), flush=True)
        rc = 1
    if wit is not None:
        snap = wit.snapshot()
        with open(os.path.join(args.base_dir,
                               "witness_{}.json".format(os.getpid())),
                  "w") as f:
            json.dump({"edge_count": snap["edge_count"],
                       "violations": snap["violations"]}, f)
    return rc


# ----------------------------------------------------------------- harness


def _child_env(lock_witness: bool) -> Dict[str, str]:
    env = dict(os.environ)
    env["MAGGY_TPU_APP_ID"] = APP_ID
    env["JAX_PLATFORMS"] = "cpu"
    env["MAGGY_TPU_JOURNAL_FSYNC"] = "1"
    env["MAGGY_TPU_CLIENT_MAX_RETRIES"] = str(CHILD_CLIENT_RETRIES)
    if lock_witness:
        env["MAGGY_TPU_LOCK_WITNESS"] = "1"
    else:
        env.pop("MAGGY_TPU_LOCK_WITNESS", None)
    return env


def _spawn_driver(base_dir: str, trials: int, workers: int, seed: int,
                  resume: bool, env: Dict[str, str]) -> subprocess.Popen:
    argv = [sys.executable, "-m", "maggy_tpu.chaos.driver_soak", "--child",
            "--base-dir", base_dir, "--trials", str(trials),
            "--workers", str(workers), "--seed", str(seed)]
    if resume:
        argv.append("--resume")
    return subprocess.Popen(argv, env=env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT)


def _spawn_runner(ticket: str, env: Dict[str, str]) -> subprocess.Popen:
    return subprocess.Popen(
        [sys.executable, "-m", "maggy_tpu.runner", "--ticket", ticket,
         "--wait-ticket", "120",
         "--train", "maggy_tpu.chaos.driver_soak:failover_train_fn"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)


def _journal_path(base_dir: str) -> str:
    from maggy_tpu.telemetry import JOURNAL_NAME

    return os.path.join(base_dir, "{}_0".format(APP_ID), JOURNAL_NAME)


def _finalized_count(journal: str) -> int:
    from maggy_tpu.telemetry.journal import _parse_jsonl

    if not os.path.exists(journal):
        return 0
    try:
        with open(journal) as f:
            events = _parse_jsonl(f.read())
    except OSError:
        return 0
    return sum(1 for ev in events
               if ev.get("ev") == "trial" and ev.get("phase") == "finalized")


def _append_kill_record(journal: str, n_finalized: int) -> float:
    """Harness-injected fault record: the dead driver's journal is
    quiescent, so the parent appends the ``kill_driver`` chaos event
    directly. The leading newline starts a fresh line past any torn tail
    the killed flusher left (the parser skips the torn fragment, and the
    restarted driver's first full-rewrite flush repairs the file)."""
    t0 = time.time()
    record = {"t": t0, "ev": "chaos", "kind": "kill_driver",
              "injected_by": "harness", "finalized_at_kill": n_finalized}
    with open(journal, "a") as f:
        f.write("\n" + json.dumps(record) + "\n")
    return t0


def _drain(proc: subprocess.Popen) -> str:
    try:
        out = proc.stdout.read() if proc.stdout else b""
        return out.decode(errors="replace")
    except Exception:  # noqa: BLE001 - diagnostics only
        return ""


def run_driver_soak(trials: int = 6, workers: int = 3, seed: int = 7,
                    kills: int = 1, base_dir: Optional[str] = None,
                    lock_witness: bool = True,
                    progress_per_kill: int = 1,
                    restart_timeout_s: float = 240.0) -> Dict[str, Any]:
    """Run the kill_driver soak end to end; returns the invariant report
    (``check_invariants`` shape + ``failover``/``witness`` blocks)."""
    import tempfile

    from maggy_tpu.chaos.harness import check_invariants
    from maggy_tpu.telemetry import read_events

    base_dir = base_dir or tempfile.mkdtemp(prefix="maggy_driver_soak_")
    env = _child_env(lock_witness)
    journal = _journal_path(base_dir)
    ticket = os.path.join(base_dir, "{}_0".format(APP_ID),
                          "runner_ticket.json")
    runners: List[subprocess.Popen] = []
    driver: Optional[subprocess.Popen] = None
    kill_times: List[float] = []
    child_logs: List[str] = []
    try:
        driver = _spawn_driver(base_dir, trials, workers, seed,
                               resume=False, env=env)
        deadline = time.monotonic() + restart_timeout_s
        while not os.path.exists(ticket):
            if driver.poll() is not None:
                raise RuntimeError(
                    "driver child exited before publishing the runner "
                    "ticket:\n" + _drain(driver))
            if time.monotonic() > deadline:
                raise TimeoutError("no runner ticket after {}s".format(
                    restart_timeout_s))
            time.sleep(0.2)
        for _ in range(workers):
            runners.append(_spawn_runner(ticket, env))

        done = 0
        for k in range(kills):
            # Wait for fresh progress past the last kill, then SIGKILL
            # mid-sweep. If the sweep finishes first the soak verified
            # nothing — fail loudly below.
            want = done + progress_per_kill
            deadline = time.monotonic() + restart_timeout_s
            while _finalized_count(journal) < want:
                if driver.poll() is not None:
                    raise RuntimeError(
                        "driver child finished before kill {} — the soak "
                        "raced the schedule; raise trials or trial "
                        "length:\n{}".format(k + 1, _drain(driver)))
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        "no sweep progress before kill {} after "
                        "{}s".format(k + 1, restart_timeout_s))
                time.sleep(0.2)
            done = _finalized_count(journal)
            driver.send_signal(signal.SIGKILL)
            driver.wait(timeout=30)
            child_logs.append(_drain(driver))
            kill_times.append(_append_kill_record(journal, done))
            driver = _spawn_driver(base_dir, trials, workers, seed,
                                   resume=True, env=env)

        out, _ = driver.communicate(timeout=restart_timeout_s)
        child_logs.append(out.decode(errors="replace") if out else "")
        final_rc = driver.returncode
        driver = None
        # Runner agents observe GSTOP and exit on their own.
        for proc in runners:
            try:
                proc.wait(timeout=60)
            except subprocess.TimeoutExpired:
                proc.kill()
    finally:
        for proc in ([driver] if driver is not None else []) + runners:
            if proc.poll() is None:
                proc.kill()

    events = read_events(journal)
    report = check_invariants(events)
    if final_rc != 0:
        report["violations"].append(
            "recovered driver exited non-zero ({}): {}".format(
                final_rc, (child_logs[-1] or "")[-2000:]))
    if report["failover"]["kills"] != kills:
        report["violations"].append(
            "kill accounting: {} kill_driver record(s) journaled for {} "
            "kill(s)".format(report["failover"]["kills"], kills))
    if len(report["failover"]["driver_epochs"]) < kills + 1:
        report["violations"].append(
            "missing incarnations: {} driver_epoch event(s) for {} "
            "kill(s)".format(len(report["failover"]["driver_epochs"]),
                             kills))
    # Witness aggregation across both incarnations.
    if lock_witness:
        edges = 0
        wit_violations: List[str] = []
        for path in sorted(glob.glob(os.path.join(base_dir,
                                                  "witness_*.json"))):
            with open(path) as f:
                snap = json.load(f)
            edges += int(snap.get("edge_count") or 0)
            wit_violations.extend(snap.get("violations") or [])
        report["witness"] = {"edge_count": edges,
                             "violations": wit_violations}
        if edges == 0:
            report["violations"].append(
                "lock-order witness recorded zero edges: the children "
                "never armed it — the soak's race check ran nothing")
        report["violations"].extend(
            "lock-order witness: " + v for v in wit_violations)
    report["ok"] = not report["violations"]
    # Separate block: must not collide with check_invariants' own keys
    # (notably the "trials" lifecycle-count dict).
    report.update(journal=journal, base_dir=base_dir,
                  kill_times=kill_times,
                  soak={"kills": kills, "seed": seed, "trials": trials,
                        "workers": workers})
    return report


if __name__ == "__main__":
    sys.exit(child_main())
