"""Chaos soak harness: run a lagom experiment under a fault plan, then
replay the telemetry journal and assert the recovery invariants the
framework's fault-tolerance story rests on.

The invariants (checked OFFLINE over journal events, so they are also
checkable against any soak artifact after the fact):

1.  **No trial lost** — every trial the driver committed to (``queued``)
    has a terminal ``finalized`` event (errored trials finalize with the
    ``error`` flag; requeued trials finalize after re-running).
2.  **No duplicate FINAL** — at most one ``finalized`` event per trial
    (the driver must swallow the duplicate FINAL a falsely-declared-lost
    runner eventually sends).
3.  **Bounded requeue** — every injected runner-death fault (kill /
    preemption / over-long stall) that disturbed a running trial is
    followed by that trial's ``requeued`` event within the bound
    (hb_loss_timeout + scan tick + grace), and the fault→requeue latency
    is measured and reported.
4.  **Experiment completes** — the journal carries the experiment's
    ``finalized`` lifecycle event.
5.  **Stall is flagged** — every injected ``stall_runner`` fault is
    followed by a health-engine ``raised`` event (hang or straggler, see
    telemetry/health.py) for the stalled partition within the bound
    (startup_factor x hang threshold + 2 health-check intervals + 3 s
    grace — the worst case: a stall landing on a still-compiling trial
    is judged at the longer startup leash). This is the closed loop
    between PR 2's fault injection and this PR's live health monitoring:
    a stall the heartbeat-loss scan is too coarse to see must still
    surface.
6.  **Exactly-once requeue** — N runner-death faults naming a trial
    produce exactly N requeues of it. The case that motivated it: with
    the pipelined hand-off (config.prefetch), a runner can die holding a
    TRIAL it received piggybacked on its FINAL reply, before that
    trial's first heartbeat — the assignment exists only in the
    reservation table, and recovery must neither lose it nor requeue it
    twice (``piggyback_plan``, ``python -m maggy_tpu.chaos
    --piggyback``).
7.  **Preemption resumes from the checkpoint** — every injected
    ``preempt_trial`` fault (the fleet scheduler's graceful
    checkpoint-assisted preemption, exercised standalone) is followed by
    the trial's ``preempted`` ack; a trial that had checkpointed must
    later carry a ``resumed`` edge whose ``from_step`` equals the
    preempted checkpoint step (never step 0) — and invariants 1/2 still
    hold: exactly one FINAL, no lost trial. A trial that never
    checkpointed simply requeues from scratch. The fleet-level half of
    the invariant — no admitted experiment starves past the fair-share
    bound — is checked against the fleet journal by
    ``maggy_tpu.fleet.soak.run_fleet_soak`` (queue-wait bound over
    ``replay_fleet_journal``). ``preempt_plan``, ``python -m
    maggy_tpu.chaos --preempt``.
8.  **Gang revocation is whole and exactly-once** — every injected
    ``kill_gang_member`` fault (one non-leader member of an assembled
    gang killed mid-trial) whose detection won the race against the
    trial's FINAL is followed by the WHOLE gang's release
    (``gang_released``), the trial's requeue with reason
    ``gang_member_lost`` exactly once, and a later re-assembly
    (``gang_assembled``) on a fresh gang — and invariants 1/2 still
    hold: the revoked leader's in-flight FINAL must be dropped, never
    double-finalized. A trial that outran detection is the benign
    completed_before_detection outcome. ``gang_plan``, ``python -m
    maggy_tpu.chaos --gang``.
14. **Checkpoint forks survive runner death** — every injected
    ``kill_fork`` fault (the runner a FORKED trial — ASHA promotion /
    PBT exploit resuming a parent's checkpoint — was just dispatched
    to, killed at the ``forked_from`` edge) is followed by the trial's
    exactly-once requeue AND a re-dispatch that resumes from the SAME
    fork point (``resumed`` with ``from_step`` == the forked step —
    never a silent from-scratch restart), with the genealogy edge
    journaled exactly once per span. The failover half — one fork
    across ``lagom(..., resume=True)`` — is checked by
    ``run_fork_soak`` (``python -m maggy_tpu.chaos --fork``): the
    replayed journal must rebuild ``forked_from`` from the queued edge.

13. **Driver failover is lossless** — over a MULTI-INCARNATION journal
    (``driver_epoch`` events mark each (re)started driver), every
    ``kill_driver`` fault must be followed by a later incarnation
    (``driver_epoch``) AND a journal-replay reconstruction marker
    (experiment phase ``recovered``); across the whole journal no trial
    is lost, none double-finalizes, and a COMPLETED trial (successful
    ``finalized``) never re-runs (no later ``running`` edge) — an
    acknowledged FINAL is durable past the crash (the FINAL-path
    barrier), so recovery re-runs only genuinely unfinished work. The
    soak lives in chaos/driver_soak.py (``python -m maggy_tpu.chaos
    --driver``): a real driver process SIGKILLed mid-sweep over
    surviving runner-agent processes, restarted with ``resume=True``.

16. **A vectorized block dies as a unit and recovers as individuals** —
    when a runner-death fault lands while a K-lane vmap block
    (config.vmap_lanes > 1) is in flight, every lane that had not
    already finalized must be requeued EXACTLY once — non-leader lanes
    with reason ``vmap_block_lost``, the leader through the ordinary
    scalar LOST path — and re-run scalar to its own FINAL. No phantom
    FINALs out of the dead block (invariant 2), no lane falling through
    the block seam (invariant 1), no lane double-requeued by racing
    recovery paths. ``vmap_plan``, ``python -m maggy_tpu.chaos
    --vmap``.

9.  **The observability plane survives the faults** — with
    ``run_soak(obs=True)`` the experiment runs with the obs HTTP server
    on (config.obs_port=0) while a scraper polls /metrics, /status and
    /healthz throughout the soak: every scrape after the server comes up
    must answer (a stalled runner or a killed worker must never wedge
    the endpoints — they read only lock-brief snapshots), /healthz must
    report 503 while a stall flag is active (the plane reports
    TRUTHFULLY under duress), and — via ``check_invariants`` over the
    journal — the first straggler/hang flag per stalled partition must
    have produced exactly ONE ``profile_captured`` artifact (the
    health-triggered capture fires once per partition, bounded by the
    run-wide rate limit).
"""

from __future__ import annotations

import glob
import os
from typing import Any, Callable, Dict, List, Optional

from maggy_tpu.chaos.plan import FaultPlan, FaultSpec

#: Fault kinds that imply the affected trial must be requeued. A graceful
#: preempt_trial requeues through the preempted-FINAL ack (reason
#: "preempted") — unless the trial outran the STOP and finalized first,
#: the benign completed_before_detection outcome. ``kill_agent``
#: (invariant 11, harness-injected by fleet/soak.run_agent_soak) extends
#: the exactly-once-requeue contract to AGENT scope: a remote agent
#: SIGKILLed mid-lease can never deliver its FINAL, so the experiment's
#: slot-reclaim liveness must requeue the trial exactly once — and the
#: fleet side must revoke the lease (checked from fleet.jsonl by the
#: soak, not here: this checker sees one experiment's journal).
#: ``kill_fork`` (invariant 14) kills the runner a FORKED trial was just
#: dispatched to: same exactly-once-requeue contract, plus the fork-
#: specific resume checks below.
_REQUEUE_KINDS = ("kill_runner", "fake_preemption", "preempt_trial",
                  "kill_gang_member", "kill_agent", "kill_fork")


def _obs_scrape_loop(stop_evt, stats: Dict[str, Any]) -> None:
    """Soak-side scraper (invariant 9): poll every obs route until the
    soak ends, recording latency, failures, and whether /healthz ever
    reported unhealthy. A failure only counts while the process obs
    server is still up — the teardown race at experiment end is not a
    responsiveness violation."""
    import json as _json
    import time as _time
    import urllib.error
    import urllib.request

    from maggy_tpu.telemetry import obs as obs_mod

    base = None
    while not stop_evt.is_set():
        server = obs_mod.active_server()
        if server is None:
            if base is not None:
                return  # server came and went: the experiment is over
            _time.sleep(0.01)
            continue
        if base is None:
            base = "http://{}:{}".format(*server.address)
        t0 = _time.monotonic()
        try:
            urllib.request.urlopen(base + "/metrics", timeout=5).read()
            body = urllib.request.urlopen(base + "/status", timeout=5).read()
            stats["last_status"] = _json.loads(body)
            try:
                urllib.request.urlopen(base + "/healthz", timeout=5).read()
            except urllib.error.HTTPError as e:
                if e.code == 503:
                    stats["unhealthy_seen"] += 1
                else:
                    raise
            stats["scrape_ms"].append((_time.monotonic() - t0) * 1e3)
            stats["scrapes"] += 1
        except Exception as e:  # noqa: BLE001 - every failure mode is the finding
            if obs_mod.active_server() is not None:
                stats["failures"].append(repr(e))
        _time.sleep(0.03)


def default_plan(seed: int = 7) -> FaultPlan:
    """The standard soak: one runner killed mid-trial, one runner falsely
    preempted (alive but declared lost — the duplicate-FINAL race), 5% of
    METRIC heartbeats dropped, and every 5th FINAL's reply withheld
    (at-least-once delivery). Four fault kinds; the mid-trial kill fires
    on the 2nd trial to reach ``running`` so the schedule is already
    warm."""
    return FaultPlan([
        FaultSpec("kill_runner", trigger={"on_phase": "running", "nth": 2}),
        FaultSpec("fake_preemption", trigger={"on_phase": "first_metric",
                                              "nth": 6},
                  duration_s=2.0),
        FaultSpec("drop_msg", target={"verb": "METRIC"},
                  trigger={"probability": 0.05}),
        FaultSpec("sever_conn", target={"verb": "FINAL"},
                  trigger={"every_nth": 5}),
    ], seed=seed)


def piggyback_plan(seed: int = 7, nth: int = 4) -> FaultPlan:
    """A runner killed immediately after RECEIVING a piggybacked TRIAL —
    in the window between the hand-off and the trial's first heartbeat.
    With prefetch on (the default), the ``running`` edge is journaled
    while the FINAL reply carrying the assignment is still being written,
    so an on_phase=running kill condemns the runner at exactly that
    window: the assignment sits in the reservation table, the runner's
    beats go silent before the trial ever heartbeats, and recovery must
    requeue it EXACTLY once (no lost trial, no duplicate FINAL, no
    double requeue — invariant 6). ``nth`` defaults past the initial
    registration GETs (3 workers → edges 1-3 are REG-path) so the killed
    edge is a piggybacked one."""
    return FaultPlan([
        FaultSpec("kill_runner", trigger={"on_phase": "running",
                                          "nth": nth}),
    ], seed=seed)


def stall_plan(seed: int = 7, duration_s: float = 2.0) -> FaultPlan:
    """One runner frozen mid-trial for ``duration_s`` — the straggler/hang
    soak. Pair with ``hb_loss_timeout`` ABOVE the stall duration so the
    loss scan stays blind: the stall must be caught by the health engine's
    hang watchdog, which is exactly the invariant this plan exercises."""
    return FaultPlan([
        FaultSpec("stall_runner", trigger={"on_phase": "first_metric",
                                           "nth": 2},
                  duration_s=duration_s),
    ], seed=seed)


def preempt_plan(seed: int = 7, nth: int = 2) -> FaultPlan:
    """Graceful checkpoint-assisted preemption (invariant 7): the Nth
    trial to reach ``first_metric`` is preempted through the driver's
    ``preempt_partition`` — the same mechanism the fleet scheduler uses,
    minus the eviction. Pair with ``ckpt_train_fn``: it checkpoints every
    step BEFORE broadcasting, so when the preempt-flagged STOP lands the
    acked checkpoint step is >= 1 and the resume provably does not
    restart from step 0."""
    return FaultPlan([
        FaultSpec("preempt_trial", trigger={"on_phase": "first_metric",
                                            "nth": nth}),
    ], seed=seed)


def gang_plan(seed: int = 7, nth: int = 1) -> FaultPlan:
    """One non-leader member of the Nth assembled gang killed right
    after assembly (invariant 8): the member's heartbeats go silent
    mid-trial, the driver must revoke the WHOLE gang lease — healthy
    members (and the still-computing leader, via a reservation-level
    preempt STOP) return to the pool — and the trial requeues with
    reason ``gang_member_lost`` exactly once, then reassembles a fresh
    gang around the dead chip."""
    return FaultPlan([
        FaultSpec("kill_gang_member",
                  trigger={"on_phase": "gang_assembled", "nth": nth}),
    ], seed=seed)


def gang_soak_train_fn(lr, budget=1, gang=None, reporter=None, ctx=None):
    """Gang soak trial: the pack soak's sharded MLP, slowed to
    heartbeating paced steps — ~1.6 s busy for 1-chip trials, ~4 s for
    gang trials — so member-loss detection (hb_loss_timeout, 1 s in the
    soak) lands mid-gang-trial with margin instead of racing the FINAL."""
    from maggy_tpu.gang import reference_gang_loss

    del budget, gang
    g = ctx.gang.to_dict() if ctx is not None and ctx.gang is not None \
        else None
    chips = len(g["chips"]) if g and isinstance(g.get("chips"), list) else 1
    return {"metric": reference_gang_loss(lr, g, reporter=reporter,
                                          steps=100 if chips > 1 else 40)}


def run_gang_soak(seed: int = 7, num_trials: int = 10, workers: int = 8,
                  gang_chips: int = 4,
                  base_dir: Optional[str] = None,
                  lock_witness: Optional[bool] = None) -> Dict[str, Any]:
    """The gang chaos soak: the pack soak's mixed ASHA sweep (1-chip
    rung-0 trials + ``gang_chips``-chip fsdp promotions on a
    ``workers``-runner thread fleet) under ``gang_plan`` — one member of
    the first assembled gang killed mid-trial. Asserts invariant 8 on
    top of the standard suite, and fails loudly if the fault never
    produced a revocation (a soak that raced every FINAL verified
    nothing)."""
    from maggy_tpu import Searchspace
    from maggy_tpu.gang import GangSpec
    from maggy_tpu.optimizers import Asha

    # The soak's topology IS the fixture: ``workers`` runners ≈ chips by
    # index, so the process needs >= gang_chips jax devices. Force the
    # 8-fake-device CPU proxy (same as bench --pack / tests/conftest)
    # while the backend is still uninitialized — without it a bare CPU
    # host has ONE device, every gang trial dies instantly on a missing
    # chip, and the kill always "loses the race": the soak verifies
    # nothing.
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count={}".format(
                workers)).strip()
    import jax

    if jax.device_count() < workers:
        raise RuntimeError(
            "gang soak needs >= {} jax devices (the placer spans every "
            "runner's chip) but the backend has {}; set XLA_FLAGS="
            "--xla_force_host_platform_device_count={} before jax "
            "initializes".format(workers, jax.device_count(), workers))

    plan = gang_plan(seed)
    # hb_loss_timeout rides ABOVE the jit-compile stalls 8 concurrently
    # tracing runner threads inflict on each other's heartbeat cadence
    # (0.3 s thrashes every partition with false losses) while staying
    # well under the ~4 s gang trial so the member kill is detected
    # mid-trial.
    report = run_soak(
        plan=plan, seed=seed, train_fn=gang_soak_train_fn,
        num_trials=num_trials, workers=workers, pool="thread",
        hb_interval=0.05, hb_loss_timeout=1.0, base_dir=base_dir,
        lock_witness=lock_witness,
        config_overrides=dict(
            optimizer=Asha(reduction_factor=gang_chips, resource_min=1,
                           resource_max=gang_chips, seed=seed),
            searchspace=Searchspace(lr=("DOUBLE", [0.05, 0.2])),
            chips_per_budget={1: GangSpec(1),
                              gang_chips: GangSpec(gang_chips,
                                                   strategy="fsdp")},
        ))
    revoked = [r for r in report.get("gang_revocations", [])
               if r.get("outcome") == "revoked"]
    if not revoked:
        report["violations"].append(
            "gang fault never produced a revocation: every "
            "kill_gang_member injection lost the race to the trial's "
            "FINAL — the soak exercised nothing (raise the trial length "
            "or lower hb_loss_timeout)")
        report["ok"] = False
    return report


def fork_plan(seed: int = 7, nth: int = 1) -> FaultPlan:
    """Checkpoint-forking soak (invariant 14): the runner the Nth FORKED
    trial is dispatched to is killed (``on_phase: forked_from`` — the
    genealogy edge carries both the trial and the chosen runner). The
    assignment exists in the reservation table at kill time; the
    slot-reclaim liveness must requeue the trial EXACTLY once, and the
    re-dispatch must resume from the SAME fork point — the forked state
    survives its runner's death."""
    return FaultPlan([
        FaultSpec("kill_fork", trigger={"on_phase": "forked_from",
                                        "nth": nth}),
    ], seed=seed)


def fork_ckpt_train_fn(lr, budget=1, reporter=None, ctx=None):
    """Forking-soak trial: ASHA budget-scaled, checkpointing every step
    (TrialCheckpointer's ``checkpoints/<step>/`` layout, written
    directly — no orbax import), resuming from ``ctx.resume_step`` —
    which, for a PROMOTED trial, is the FORK POINT the driver staged
    from the rung parent's checkpoint. The per-step metric is a pure
    function of (lr, step), so a forked trial's trajectory is
    step-for-step identical to its parent's continuation — the parity
    bench.py --fork asserts."""
    import json as _json
    import os as _os
    import time as _time

    steps = max(1, int(round(4 * budget)))
    start = 0
    if ctx is not None and ctx.resume_step is not None:
        state_path = _os.path.join(ctx.trial_dir, "checkpoints",
                                   str(ctx.resume_step), "state.json")
        with open(state_path) as f:
            start = int(_json.load(f)["step"]) + 1
    metric = None
    for step in range(start, steps):
        _time.sleep(0.05)
        metric = fork_step_metric(lr, step)
        if ctx is not None:
            step_dir = _os.path.join(ctx.trial_dir, "checkpoints",
                                     str(step))
            _os.makedirs(step_dir, exist_ok=True)
            with open(_os.path.join(step_dir, "state.json"), "w") as f:
                _json.dump({"step": step}, f)
        if reporter is not None:
            reporter.broadcast(metric, step=step)
    if metric is None:
        metric = fork_step_metric(lr, steps - 1)
    return {"metric": metric}


def fork_step_metric(lr, step: int) -> float:
    """The soak trial's closed-form per-step metric: depends ONLY on
    (lr, step), so fork parity is decidable offline — a forked child's
    step-k metric must equal what its parent WOULD have produced at
    step k."""
    return 1.0 - (lr - 0.1) ** 2 * (1.0 + 1.0 / (1.0 + step))


def run_fork_soak(seed: int = 7, num_trials: int = 4, workers: int = 2,
                  base_dir: Optional[str] = None,
                  lock_witness: Optional[bool] = None) -> Dict[str, Any]:
    """The checkpoint-forking chaos soak (invariant 14), two halves:

    1. **Runner death mid-fork**: an ASHA sweep whose promotions FORK
       their rung parents' checkpoints runs under ``fork_plan`` — the
       runner the first forked trial lands on is killed. The trial must
       requeue exactly once and its re-dispatch must resume from the
       SAME fork point, genealogy (the once-per-span ``forked_from``
       edge) intact.
    2. **Driver failover mid-fork** (the PR-14 follow-up): a
       synthetically interrupted run whose journal holds an in-flight
       FORKED promotion is resumed through the real ``lagom(...,
       resume=True)`` path — the replayed journal must rebuild
       ``forked_from`` + ``resume_step`` from the queued edge and the
       fork must complete resuming from the same point.

    Both halves run under the lock-order witness (like every soak)."""
    from maggy_tpu import Searchspace
    from maggy_tpu.optimizers import Asha

    plan = fork_plan(seed)
    report = run_soak(
        plan=plan, seed=seed, train_fn=fork_ckpt_train_fn,
        num_trials=num_trials, workers=workers, pool="thread",
        hb_interval=0.05, hb_loss_timeout=0.6, base_dir=base_dir,
        lock_witness=lock_witness,
        config_overrides=dict(
            optimizer=Asha(reduction_factor=2, resource_min=1,
                           resource_max=2, seed=seed),
            searchspace=Searchspace(lr=("DOUBLE", [0.05, 0.2])),
        ))
    killed = [r for r in report.get("forks", [])
              if r.get("outcome") == "resumed_from_fork"]
    if not [ce for ce in _chaos_of(report, "kill_fork")]:
        report["violations"].append(
            "fork fault never fired: the sweep produced no forked_from "
            "dispatch to kill — the soak exercised nothing")
        report["ok"] = False
    elif not killed:
        # The per-kill violations are already in the report; this is the
        # exercised-nothing guard's counterpart.
        report["ok"] = not report["violations"]
    failover = _run_fork_failover_half(seed)
    report["fork_failover"] = failover
    if failover["violations"]:
        report["violations"].extend(
            "fork failover: " + v for v in failover["violations"])
        report["ok"] = False
    return report


def _chaos_of(report: Dict[str, Any], kind: str) -> List[Dict[str, Any]]:
    return [r for r in report.get("recoveries", [])
            if r.get("kind") == kind]


def _run_fork_failover_half(seed: int) -> Dict[str, Any]:
    """Half 2 of the fork soak: one fork across ``lagom(...,
    resume=True)`` driver failover. Builds what a crashed forking driver
    leaves on disk — two finalized rung-0 trials (artifacts +
    checkpoints) and one IN-FLIGHT forked promotion whose queued edge
    carries ``forked_from``/``resume_step`` — then resumes through the
    real lagom path and checks the journal: the fork completed exactly
    once, resumed from the same fork point, lineage rebuilt."""
    import json as _json
    import os as _os
    import tempfile as _tempfile
    import time as _time

    from maggy_tpu import OptimizationConfig, Searchspace, experiment
    from maggy_tpu.optimizers import Asha
    from maggy_tpu.telemetry import JOURNAL_NAME, read_events, replay_journal
    from maggy_tpu.trial import Trial

    base = _tempfile.mkdtemp(prefix="maggy_fork_failover_")
    app_id = "forkfail"
    run_dir = _os.path.join(base, "{}_0".format(app_id))
    p1 = {"lr": 0.1, "budget": 1}
    p2 = {"lr": 0.18, "budget": 1}
    t1, t2 = Trial(p1).trial_id, Trial(p2).trial_id
    child_params = {"lr": 0.1, "budget": 2}
    child = Trial(child_params).trial_id
    fork_step = 3  # the parent's last checkpointed step (4 x budget 1)
    child_info = {"sample_type": "promoted", "rung": 1, "parent": t1,
                  "forked_from": {"trial": t1, "step": fork_step},
                  "resume_step": fork_step}
    t0 = _time.time() - 60
    events = [
        {"t": t0, "ev": "driver_epoch", "epoch": 1},
        {"t": t0, "ev": "experiment", "phase": "start", "name": "forksoak"},
        {"t": t0 + 0.1, "ev": "runner", "phase": "registered",
         "partition": 0},
        {"t": t0 + 0.1, "ev": "runner", "phase": "registered",
         "partition": 1},
    ]
    for tid, params, pid in ((t1, p1, 0), (t2, p2, 1)):
        events += [
            {"t": t0 + 0.2, "ev": "trial", "trial": tid,
             "span": "span-" + tid[:6], "phase": "queued", "params": params,
             "trial_type": "optimization",
             "info": {"sample_type": "random", "rung": 0}},
            {"t": t0 + 0.3, "ev": "trial", "trial": tid,
             "span": "span-" + tid[:6], "phase": "running",
             "partition": pid, "epoch": 0},
            {"t": t0 + 1.0, "ev": "trial", "trial": tid,
             "span": "span-" + tid[:6], "phase": "finalized",
             "partition": pid},
        ]
    events += [
        {"t": t0 + 1.2, "ev": "trial", "trial": child,
         "span": "span-child", "phase": "queued", "params": child_params,
         "trial_type": "optimization", "info": child_info},
        {"t": t0 + 1.3, "ev": "trial", "trial": child,
         "span": "span-child", "phase": "assigned", "partition": 0},
        {"t": t0 + 1.3, "ev": "trial", "trial": child,
         "span": "span-child", "phase": "forked_from", "partition": 0,
         "parent": t1, "step": fork_step},
        {"t": t0 + 1.4, "ev": "trial", "trial": child,
         "span": "span-child", "phase": "running", "partition": 0,
         "epoch": 0},
    ]
    _os.makedirs(run_dir, exist_ok=True)
    with open(_os.path.join(run_dir, JOURNAL_NAME), "w") as f:
        for ev in events:
            f.write(_json.dumps(ev) + "\n")
    for tid, params, metric in ((t1, p1, 0.9), (t2, p2, 0.5)):
        done = Trial(params, info_dict={"sample_type": "random", "rung": 0})
        done.status = Trial.FINALIZED
        done.final_metric = metric
        _os.makedirs(_os.path.join(run_dir, tid), exist_ok=True)
        with open(_os.path.join(run_dir, tid, "trial.json"), "w") as f:
            f.write(done.to_json())
        for step in range(4):
            step_dir = _os.path.join(run_dir, tid, "checkpoints",
                                     str(step))
            _os.makedirs(step_dir, exist_ok=True)
            with open(_os.path.join(step_dir, "state.json"), "w") as f:
                _json.dump({"step": step}, f)
    for name, payload in (
            (".run_claim", {}),
            ("experiment.json", {"name": "forksoak", "state": "RUNNING"}),
            (".driver_epoch.1", {}),
            ("driver_state.json", {"secret": "ab" * 16,
                                   "host": "127.0.0.1", "port": 0,
                                   "driver_epoch": 1})):
        with open(_os.path.join(run_dir, name), "w") as f:
            _json.dump(payload, f)

    old_app = experiment.APP_ID
    experiment.APP_ID = app_id
    try:
        config = OptimizationConfig(
            name="forksoak", num_trials=2,
            optimizer=Asha(reduction_factor=2, resource_min=1,
                           resource_max=2, seed=seed),
            searchspace=Searchspace(lr=("DOUBLE", [0.05, 0.2])),
            direction="max", num_workers=2, seed=seed, es_policy="none",
            experiment_dir=base, resume=True, hb_interval=0.05,
            hb_loss_timeout=1.0)
        result = experiment.lagom(fork_ckpt_train_fn, config)
    finally:
        experiment.APP_ID = old_app
    events = read_events(_os.path.join(run_dir, JOURNAL_NAME))
    violations: List[str] = []
    report = check_invariants(events)
    violations.extend(report["violations"])
    resumed = [ev for ev in events
               if ev.get("ev") == "trial" and ev.get("trial") == child
               and ev.get("phase") == "resumed"]
    if not resumed:
        violations.append(
            "recovered fork never resumed: the re-dispatched child "
            "carries no resumed edge")
    elif any(ev.get("from_step") != fork_step for ev in resumed):
        violations.append(
            "recovered fork lost its fork point: resumed from_step {} "
            "!= staged step {}".format(
                [ev.get("from_step") for ev in resumed], fork_step))
    fork_edges = [ev for ev in events
                  if ev.get("ev") == "trial" and ev.get("trial") == child
                  and ev.get("phase") == "forked_from"]
    if len(fork_edges) != 1:
        violations.append(
            "fork lineage not exactly-once across incarnations: {} "
            "forked_from edges for the child".format(len(fork_edges)))
    recovered = [ev for ev in events
                 if ev.get("ev") == "experiment"
                 and ev.get("phase") == "recovered"]
    if not recovered or not recovered[0].get("forks"):
        violations.append(
            "recovery did not report the rebuilt fork lineage "
            "(recovered event missing forks count)")
    derived = replay_journal(_os.path.join(run_dir, JOURNAL_NAME))
    return {"violations": violations,
            "result": {"num_trials": result.get("num_trials"),
                       "best_val": result.get("best_val")},
            "fork": derived.get("fork") or {},
            "journal": _os.path.join(run_dir, JOURNAL_NAME)}


def ckpt_train_fn(lr, units, reporter=None, ctx=None):
    """Soak trial that checkpoints each step (TrialCheckpointer's
    ``checkpoints/<step>/`` layout, written directly so the soak never
    pays the orbax import) and resumes from ``ctx.resume_step`` after a
    preemption — the cooperative half of checkpoint-assisted preemption."""
    import json as _json
    import os as _os
    import time as _time

    acc = 1.0 - ((lr - 0.1) ** 2 + ((units - 32) / 64.0) ** 2)
    start = 0
    if ctx is not None and ctx.resume_step is not None:
        state_path = _os.path.join(ctx.trial_dir, "checkpoints",
                                   str(ctx.resume_step), "state.json")
        with open(state_path) as f:
            start = int(_json.load(f)["step"]) + 1
    for step in range(start, 8):
        _time.sleep(0.05)
        if ctx is not None:
            step_dir = _os.path.join(ctx.trial_dir, "checkpoints", str(step))
            _os.makedirs(step_dir, exist_ok=True)
            with open(_os.path.join(step_dir, "state.json"), "w") as f:
                _json.dump({"step": step}, f)
        if reporter is not None:
            reporter.broadcast(acc * (step + 1) / 8.0, step=step)
    return {"metric": acc}


def _soak_train_fn(lr, units, reporter=None):
    """Closed-form stand-in trial: long enough (~0.3 s) that faults land
    mid-trial, heartbeating every step."""
    import time as _time

    acc = 1.0 - ((lr - 0.1) ** 2 + ((units - 32) / 64.0) ** 2)
    for step in range(6):
        _time.sleep(0.05)
        if reporter is not None:
            reporter.broadcast(acc * (step + 1) / 6.0, step=step)
    return {"metric": acc}


def run_soak(plan: Optional[FaultPlan] = None, seed: int = 7,
             train_fn: Optional[Callable] = None, num_trials: int = 12,
             workers: int = 3, pool: str = "thread",
             hb_interval: float = 0.05, hb_loss_timeout: float = 0.6,
             base_dir: Optional[str] = None,
             requeue_grace_s: float = 5.0,
             config_overrides: Optional[Dict[str, Any]] = None,
             lock_witness: Optional[bool] = None,
             obs: bool = False
             ) -> Dict[str, Any]:
    """Execute one soak and return its report (see ``check_invariants``).

    The experiment runs under a private base dir; the journal is read
    back from disk (NOT from the live telemetry object) so the report is
    derived from the same artifact an offline replay would use.
    ``config_overrides`` merges extra OptimizationConfig fields (e.g.
    ``health_hang_factor`` to tighten the hang watchdog for a stall
    soak).

    ``lock_witness`` arms the runtime lock-order witness
    (maggy_tpu.analysis.witness) for the soak, so the invariant run
    doubles as a dynamic race check: every acquired-while-holding edge
    the experiment actually takes is recorded, and any edge the static
    canonical order forbids is reported alongside the invariant
    violations. ``None`` defers to MAGGY_TPU_LOCK_WITNESS (the chaos
    CLI passes True by default). Installation happens before the driver
    builds its locks; if this call installed the witness (rather than
    finding it already active), it uninstalls on the way out.

    ``obs`` arms invariant 9: the soak runs with the observability
    server on (an ephemeral port unless config_overrides says
    otherwise) and a concurrent scraper; the report gains an ``obs``
    block and any unresponsive endpoint, untruthful /healthz, or
    missing/duplicated health-triggered ``profile_captured`` artifact
    is a violation."""
    import tempfile
    import threading

    from maggy_tpu.analysis import witness as _witness

    wit = None
    wit_installed_here = False
    wit_pre_violations = 0
    if lock_witness or (lock_witness is None and _witness.enabled_by_env()):
        wit_installed_here = _witness.active_witness() is None
        wit = _witness.install()
        wit_pre_violations = len(wit.violations)

    from maggy_tpu import OptimizationConfig, Searchspace, experiment
    from maggy_tpu.core import rpc
    from maggy_tpu.telemetry import JOURNAL_NAME, read_events

    plan = plan if plan is not None else default_plan(seed)
    train_fn = train_fn or _soak_train_fn
    base_dir = base_dir or tempfile.mkdtemp(prefix="maggy_chaos_")
    kwargs = dict(
        name="chaos_soak", num_trials=num_trials, optimizer="randomsearch",
        searchspace=Searchspace(lr=("DOUBLE", [0.0, 0.2]),
                                units=("INTEGER", [8, 64])),
        direction="max", num_workers=workers, pool=pool,
        hb_interval=hb_interval, hb_loss_timeout=hb_loss_timeout,
        seed=seed, es_policy="none", experiment_dir=base_dir,
        chaos=plan,
    )
    if obs:
        kwargs["obs_port"] = 0
    kwargs.update(config_overrides or {})
    config = OptimizationConfig(**kwargs)
    obs_stats: Dict[str, Any] = {"scrapes": 0, "failures": [],
                                 "scrape_ms": [], "unhealthy_seen": 0,
                                 "last_status": None}
    obs_stop = threading.Event()
    obs_thread = None
    if obs:
        obs_thread = threading.Thread(
            target=_obs_scrape_loop, args=(obs_stop, obs_stats),
            daemon=True, name="chaos-obs-scraper")
        obs_thread.start()
    # Bound for invariant 5 (stall -> health flag): the WORST-case hang
    # threshold (startup window, in case the plan stalls a trial before
    # its first metric) + health-check interval + grace for the
    # scheduling jitter in between. Derived from the health module's own
    # constants so the watchdog and its verifier cannot silently diverge.
    from maggy_tpu.telemetry.health import (DEFAULT_HANG_FACTOR,
                                            DEFAULT_STARTUP_FACTOR,
                                            default_interval_s)

    hang_s = getattr(config, "health_hang_factor",
                     DEFAULT_HANG_FACTOR) * hb_interval
    health_interval = getattr(config, "health_interval_s", None) \
        or default_interval_s(hb_interval)
    stall_flag_bound_s: Optional[float] = \
        DEFAULT_STARTUP_FACTOR * hang_s + 2 * health_interval + 3.0
    if not getattr(config, "health", True):
        # No health engine, nothing can flag a stall: the invariant is
        # vacuous, not violated.
        stall_flag_bound_s = None
    retry0 = rpc.CLIENT_METRICS.counter("rpc.client.retries").value
    try:
        result = experiment.lagom(train_fn, config)
    finally:
        if obs_thread is not None:
            obs_stop.set()
            obs_thread.join(timeout=5)
        if wit is not None and wit_installed_here \
                and not _witness.enabled_by_env():
            _witness.uninstall()
    retries = rpc.CLIENT_METRICS.counter("rpc.client.retries").value - retry0
    exp_dirs = sorted(d for d in glob.glob(os.path.join(base_dir, "*"))
                      if os.path.isdir(d))
    journal = os.path.join(exp_dirs[-1], JOURNAL_NAME)
    events = read_events(journal)
    report = check_invariants(
        events, requeue_bound_s=hb_loss_timeout + requeue_grace_s,
        stall_flag_bound_s=stall_flag_bound_s)
    # A soak that injected NOTHING verified nothing: a plan whose specs
    # never matched (wrong verb, unreachable nth) must fail loudly, not
    # report the recovery invariants as held.
    if plan.specs and report["faults"]["injected"] == 0:
        report["violations"].append(
            "no faults injected: the plan has {} spec(s) but the journal "
            "records zero chaos events — the soak exercised "
            "nothing".format(len(plan.specs)))
        report["ok"] = False
    # Best-trial semantics must survive the chaos: the reported best is
    # the max over the finalized trial artifacts on disk (direction=max).
    import json as _json

    metrics = []
    for td in glob.glob(os.path.join(exp_dirs[-1], "*", "trial.json")):
        with open(td) as f:
            d = _json.load(f)
        if d.get("final_metric") is not None:
            metrics.append(float(d["final_metric"]))
    best = result.get("best_val")
    if metrics and (best is None or abs(max(metrics) - best) > 1e-9):
        report["violations"].append(
            "best-trial mismatch: result.best_val={} but max finalized "
            "trial metric on disk is {}".format(best, max(metrics)))
        report["ok"] = False
    if obs:
        # Invariant 9, live half: the endpoints answered throughout the
        # soak and /healthz told the truth while the fleet was degraded
        # (the journal half — profile_captured — lives in
        # check_invariants).
        from maggy_tpu.telemetry.spans import _dist_stats

        report["obs"] = {
            "scrapes": obs_stats["scrapes"],
            "failures": obs_stats["failures"],
            "scrape_ms": _dist_stats(obs_stats["scrape_ms"]),
            "unhealthy_seen": obs_stats["unhealthy_seen"],
        }
        if obs_stats["scrapes"] == 0:
            report["violations"].append(
                "obs endpoints never answered: the soak scraped zero "
                "successful /metrics+/status+/healthz rounds")
        if obs_stats["failures"]:
            report["violations"].append(
                "obs endpoints unresponsive under faults: {} scrape "
                "failure(s), first: {}".format(
                    len(obs_stats["failures"]), obs_stats["failures"][0]))
        stalled = report["faults"]["by_kind"].get("stall_runner", 0)
        if stalled and report["health"]["raised"] > 0 \
                and obs_stats["unhealthy_seen"] == 0:
            report["violations"].append(
                "obs healthz untruthful: health flags were raised during "
                "the stall soak but /healthz never reported 503")
        report["ok"] = not report["violations"]
    report.update(
        journal=journal, result={"num_trials": result.get("num_trials"),
                                 "best_val": result.get("best_val"),
                                 "lost_runners": result.get("lost_runners", 0)},
        client_retries=retries,
        schedule_fingerprint=plan.fingerprint(),
    )
    if wit is not None:
        # Witness violations count from this soak's install point, so a
        # shared (env-armed, multi-soak) witness doesn't re-report an
        # earlier soak's edges as this soak's failure.
        snap = wit.snapshot()
        new = snap["violations"][wit_pre_violations:]
        report["witness"] = {"edge_count": snap["edge_count"],
                             "violations": new}
        if new:
            report["violations"].extend(
                "lock-order witness: " + v for v in new)
            report["ok"] = False
    return report


def run_goodput_soak(seed: int = 7, num_trials: int = 12,
                     workers: int = 3,
                     lock_witness: Optional[bool] = None
                     ) -> Dict[str, Any]:
    """Fault-free control soak for the chip-time ledger (invariant 15's
    other half): with NO faults injected, the goodput fold over the
    journal must book (a) ~zero rework chip-time — rework exists only
    where a seam exists — and (b) an ``unaccounted`` residual at or
    under 5% of held chip-time, proving the taxonomy closes on a clean
    run. An empty FaultPlan legitimately skips run_soak's
    nothing-injected check (that check guards plans WITH specs)."""
    report = run_soak(plan=FaultPlan([], seed), seed=seed,
                      num_trials=num_trials, workers=workers,
                      # Generous loss bound: a slow CI host must not
                      # manufacture a heartbeat-loss seam (and thus
                      # legitimate rework) in the fault-free control.
                      hb_loss_timeout=2.0,
                      lock_witness=lock_witness)
    gp = report.get("goodput") or {}
    rework_s = ((gp.get("buckets") or {}).get("rework") or 0.0)
    if rework_s > 1e-6:
        report["violations"].append(
            "rework in a fault-free soak: the ledger books {:.3f}s "
            "rework chip-time with zero faults injected (trials: "
            "{})".format(rework_s,
                         (report.get("rework") or {}).get("trials")))
    unaccounted = gp.get("unaccounted_fraction")
    if not gp:
        report["violations"].append(
            "no goodput ledger: the fold over the soak journal came "
            "back empty")
    elif unaccounted is None or unaccounted > 0.05:
        report["violations"].append(
            "unaccounted chip-time {} exceeds the 5% bound in a "
            "fault-free soak: the taxonomy leaks".format(unaccounted))
    report["ok"] = not report["violations"]
    return report


def vmap_plan(seed: int = 7, nth: int = 4) -> FaultPlan:
    """Vectorized-block soak (invariant 16): the runner holding the first
    assembled K-lane block is killed at a lane's ``running`` edge. With 2
    workers the first dispatch per runner precedes the prefetch queue
    (running edges 1-2 are scalar), so edges 3+ are the first block's
    leader + lanes — ``nth`` defaults onto a NON-leader lane of that
    block, the case where the chaos event names a lane while the
    reservation (and thus the LOST scan) names the leader."""
    return FaultPlan([
        FaultSpec("kill_runner", trigger={"on_phase": "running",
                                          "nth": nth}),
    ], seed=seed)


def vmap_soak_train_fn(lr, lanes=None, reporter=None):
    """Vmap soak trial: a heartbeat-paced closed-form quadratic, ~1.5 s
    busy, lanes-capable. The scalar branch is mandatory — the first
    dispatch per runner always precedes the prefetch queue, and every
    requeued lane re-runs scalar (the recovery path under test)."""
    import time as _time

    if lanes is None:
        for step in range(30):
            reporter.broadcast(1.0 - (lr - 0.1) ** 2 + 1e-3 * step,
                               step=step)
            _time.sleep(0.05)
        return 1.0 - (lr - 0.1) ** 2
    lrs = [h["lr"] for h in lanes.hparams]
    for step in range(30):
        vals = [1.0 - (l - 0.1) ** 2 + 1e-3 * step for l in lrs]
        reporter.broadcast_lanes(vals, step=step)
        for i in lanes.take_stopped():
            lanes.retire(i, float(vals[i]))
        _time.sleep(0.05)
    return {tid: 1.0 - (l - 0.1) ** 2
            for tid, l in zip(lanes.trial_ids, lrs)}


def run_vmap_soak(seed: int = 7, num_trials: int = 12, workers: int = 2,
                  lanes: int = 4,
                  base_dir: Optional[str] = None,
                  lock_witness: Optional[bool] = None) -> Dict[str, Any]:
    """The vectorized-block chaos soak: a float-only sweep (every trial
    program-compatible, so blocks assemble as soon as the prefetch queue
    fills) with ``vmap_lanes=lanes`` on a 2-runner thread fleet, under
    ``vmap_plan`` — the runner holding the first assembled block killed
    mid-block. Asserts invariant 16 on top of the standard suite, and
    fails loudly if the kill never tore a block (a kill that landed on a
    scalar trial verified nothing)."""
    from maggy_tpu import Searchspace

    plan = vmap_plan(seed)
    report = run_soak(
        plan=plan, seed=seed, train_fn=vmap_soak_train_fn,
        num_trials=num_trials, workers=workers, pool="thread",
        hb_interval=0.05, hb_loss_timeout=0.6, base_dir=base_dir,
        lock_witness=lock_witness,
        config_overrides=dict(
            searchspace=Searchspace(lr=("DOUBLE", [0.0, 0.2])),
            vmap_lanes=lanes,
        ))
    torn = [r for r in report.get("vmap_blocks", [])
            if r.get("outcome") == "requeued"]
    if not torn:
        report["violations"].append(
            "vmap fault never tore a block: the kill_runner injection "
            "hit a scalar trial (or raced every lane's FINAL) — the soak "
            "exercised nothing (tune vmap_plan's nth)")
        report["ok"] = False
    return report


def check_invariants(events: List[Dict[str, Any]],
                     requeue_bound_s: Optional[float] = None,
                     stall_flag_bound_s: Optional[float] = 15.0
                     ) -> Dict[str, Any]:
    """Pure invariant check over journal events. Returns a report with
    ``violations`` (empty = all invariants hold), per-fault recovery
    latencies, health-flag stats, and lifecycle counts.

    ``stall_flag_bound_s`` bounds invariant 5 (every ``stall_runner``
    injection must be followed by a health ``raised`` flag for the stalled
    partition). The invariant is enforced only when the journal carries
    the health engine's ``started`` liveness marker — a pre-health or
    ``health=False`` journal has nothing watching, which is a skipped
    check, not a violation. Passing None also skips it."""
    queued: Dict[str, float] = {}
    finalized: Dict[str, List[float]] = {}
    finalized_ok: Dict[str, List[float]] = {}
    running_at: Dict[str, List[float]] = {}
    requeued: Dict[str, List[float]] = {}
    requeued_evs: Dict[str, List[Dict[str, Any]]] = {}
    preempted_evs: Dict[str, List[Dict[str, Any]]] = {}
    resumed_evs: Dict[str, List[Dict[str, Any]]] = {}
    forked_evs: Dict[str, List[Dict[str, Any]]] = {}
    gang_assembled: Dict[str, List[Dict[str, Any]]] = {}
    gang_released: Dict[str, List[Dict[str, Any]]] = {}
    # Vectorized blocks (invariant 16): block leader id -> {lane trial id
    # -> its lane-tagged assigned event}. Only block assignments carry a
    # "block" field; scalar journals never enter this map.
    block_lanes: Dict[str, Dict[str, Dict[str, Any]]] = {}
    parent_of: Dict[str, Any] = {}
    chaos_events: List[Dict[str, Any]] = []
    health_raised: List[Dict[str, Any]] = []
    health_by_check: Dict[str, int] = {}
    health_engine_ran = False
    experiment_finalized = False
    obs_armed = False
    profile_captures: List[Dict[str, Any]] = []
    driver_epochs: List[Dict[str, Any]] = []
    recovered_markers: List[Dict[str, Any]] = []
    adopted = 0
    for ev in events:
        kind = ev.get("ev")
        t = ev.get("t")
        if kind == "chaos":
            chaos_events.append(dict(ev))
            continue
        if kind == "driver_epoch":
            driver_epochs.append(dict(ev))
            continue
        if kind == "runner":
            if ev.get("phase") == "adopted":
                adopted += 1
            continue
        if kind == "obs_started":
            obs_armed = True
            continue
        if kind == "profile_captured":
            profile_captures.append(dict(ev))
            continue
        if kind == "health":
            if ev.get("check") == "engine":
                health_engine_ran |= ev.get("status") == "started"
            elif ev.get("status") == "raised":
                health_raised.append(dict(ev))
                health_by_check[ev.get("check")] = \
                    health_by_check.get(ev.get("check"), 0) + 1
            continue
        if kind == "experiment":
            if ev.get("phase") in ("finalized", "end"):
                experiment_finalized = True
            elif ev.get("phase") == "recovered":
                recovered_markers.append(dict(ev))
            continue
        if kind != "trial" or t is None:
            continue
        trial, phase = ev.get("trial"), ev.get("phase")
        if trial is None:
            continue
        if phase == "queued":
            queued.setdefault(trial, t)
            if (ev.get("info") or {}).get("parent") is not None:
                parent_of.setdefault(trial, ev["info"]["parent"])
        elif phase == "requeued":
            requeued.setdefault(trial, []).append(t)
            requeued_evs.setdefault(trial, []).append(dict(ev))
        elif phase == "assigned":
            if ev.get("block") is not None:
                block_lanes.setdefault(ev["block"], {}).setdefault(
                    trial, dict(ev))
        elif phase == "gang_assembled":
            gang_assembled.setdefault(trial, []).append(dict(ev))
        elif phase == "gang_released":
            gang_released.setdefault(trial, []).append(dict(ev))
        elif phase == "preempted":
            preempted_evs.setdefault(trial, []).append(dict(ev))
        elif phase == "resumed":
            resumed_evs.setdefault(trial, []).append(dict(ev))
        elif phase == "forked_from":
            forked_evs.setdefault(trial, []).append(dict(ev))
        elif phase == "running":
            running_at.setdefault(trial, []).append(t)
        elif phase == "finalized":
            finalized.setdefault(trial, []).append(t)
            if not ev.get("error"):
                finalized_ok.setdefault(trial, []).append(t)

    violations: List[str] = []
    for trial in sorted(queued):
        n = len(finalized.get(trial, []))
        if n == 0:
            violations.append("lost trial: {} was queued but never "
                              "finalized".format(trial))
        elif n > 1:
            violations.append("duplicate FINAL: {} finalized {} "
                              "times".format(trial, n))
    for trial in sorted(set(finalized) - set(queued)):
        violations.append("phantom trial: {} finalized but never "
                          "queued".format(trial))
    if not experiment_finalized:
        violations.append("experiment never finalized (no experiment "
                          "finalized/end event in the journal)")

    # Fault -> requeue recovery, for every injected runner-death fault
    # that names the trial it disturbed. A kill MUST produce a requeue
    # (the dead runner can never deliver the FINAL); a fake preemption
    # may lose the race to a fast trial — the alive runner's FINAL lands
    # before the loss scan fires, nothing was endangered, and that
    # benign outcome is reported as completed_before_detection.
    recoveries: List[Dict[str, Any]] = []
    for ce in chaos_events:
        if ce.get("kind") not in _REQUEUE_KINDS:
            continue
        trial, t0 = ce.get("trial"), ce.get("t")
        if trial is None or t0 is None:
            continue
        later = [t for t in requeued.get(trial, []) if t >= t0]
        finished = [t for t in finalized.get(trial, []) if t >= t0]
        rec = {"kind": ce["kind"], "trial": trial,
               "partition": ce.get("partition")}
        if later:
            rec["outcome"] = "requeued"
            rec["requeues"] = len(later)
            latency = min(later) - t0
            rec["requeue_latency_s"] = round(latency, 3)
            if requeue_bound_s is not None and latency > requeue_bound_s:
                violations.append(
                    "slow requeue: {} fault on trial {} took {:.2f}s to "
                    "requeue (bound {:.2f}s)".format(
                        ce["kind"], trial, latency, requeue_bound_s))
        elif finished and ce["kind"] not in ("kill_runner", "kill_agent",
                                             "kill_fork"):
            # A killed runner/agent can never deliver the FINAL itself —
            # a post-kill FINAL without a requeue would mean a duplicate
            # delivery path, not a benign race.
            rec["outcome"] = "completed_before_detection"
            rec["requeue_latency_s"] = None
        else:
            rec["outcome"] = "unrecovered"
            rec["requeue_latency_s"] = None
            violations.append(
                "no requeue: {} fault hit trial {} (partition {}) but the "
                "journal has no subsequent requeued event".format(
                    ce["kind"], trial, ce.get("partition")))
        recoveries.append(rec)

    # Invariant 6: exactly-once requeue. N runner-death faults naming a
    # trial must produce exactly N requeues of it — a piggybacked
    # assignment dying with its runner before the first heartbeat must
    # not be double-requeued by racing recovery paths (LOST scan vs a
    # re-registration BLACK), nor silently over-requeued in general.
    death_faults: Dict[str, int] = {}
    for ce in chaos_events:
        if ce.get("kind") in _REQUEUE_KINDS and ce.get("trial") is not None:
            death_faults[ce["trial"]] = death_faults.get(ce["trial"], 0) + 1
    for trial, n_faults in sorted(death_faults.items()):
        n_req = len(requeued.get(trial, []))
        if n_req > n_faults:
            violations.append(
                "duplicate requeue: trial {} was requeued {} times for {} "
                "runner-death fault(s)".format(trial, n_req, n_faults))

    # Invariant 7: checkpoint-assisted preemption. Every preempt_trial
    # fault must be followed by the trial's graceful ``preempted`` ack
    # (unless the trial outran the STOP and finalized — benign); a trial
    # preempted WITH a checkpoint must later resume exactly from that
    # step (never restart at 0); invariants 1/2 (single FINAL, no lost
    # trial) already cover the rest of the chain above.
    preempt_recs: List[Dict[str, Any]] = []
    for ce in chaos_events:
        if ce.get("kind") != "preempt_trial":
            continue
        trial, t0 = ce.get("trial"), ce.get("t")
        if trial is None or t0 is None:
            continue
        acks = [p for p in preempted_evs.get(trial, [])
                if p.get("t") is not None and p["t"] >= t0]
        rec: Dict[str, Any] = {"trial": trial,
                               "partition": ce.get("partition")}
        if not acks:
            if [t for t in finalized.get(trial, []) if t >= t0]:
                rec["outcome"] = "completed_before_preempt"
            else:
                rec["outcome"] = "unacked"
                violations.append(
                    "unacked preemption: preempt_trial fault on trial {} "
                    "produced neither a preempted ack nor a FINAL".format(
                        trial))
            preempt_recs.append(rec)
            continue
        ack = acks[0]
        step = ack.get("step")
        rec.update(outcome="preempted", step=step,
                   checkpointed=bool(ack.get("checkpointed")))
        if ack.get("checkpointed"):
            resumes = [r for r in resumed_evs.get(trial, [])
                       if r.get("t") is not None and r["t"] >= ack["t"]]
            if not resumes:
                violations.append(
                    "unresumed preemption: trial {} was preempted at "
                    "checkpoint step {} but never carried a resumed "
                    "edge".format(trial, step))
            else:
                from_step = resumes[0].get("from_step")
                rec["from_step"] = from_step
                if from_step != step:
                    violations.append(
                        "resume step mismatch: trial {} was preempted at "
                        "checkpoint step {} but resumed from_step={}"
                        .format(trial, step, from_step))
                elif not from_step or from_step < 1:
                    violations.append(
                        "resume from scratch: trial {} checkpointed but "
                        "resumed from step {} (expected >= 1)".format(
                            trial, from_step))
        preempt_recs.append(rec)

    # Invariant 8: gang revocation is whole and exactly-once. A
    # kill_gang_member fault whose member-loss detection won the race
    # against the trial's FINAL must be followed by the WHOLE gang's
    # release, the trial's requeue with reason gang_member_lost exactly
    # once, and a later re-assembly (the trial can only ever run through
    # a gang, and invariant 1 demands it finalizes).
    gang_recs: List[Dict[str, Any]] = []
    for ce in chaos_events:
        if ce.get("kind") != "kill_gang_member":
            continue
        trial, t0 = ce.get("trial"), ce.get("t")
        if trial is None or t0 is None:
            continue
        rec: Dict[str, Any] = {"trial": trial,
                               "victim": ce.get("partition"),
                               "leader": ce.get("leader")}
        gml = [e for e in requeued_evs.get(trial, [])
               if e.get("t") is not None and e["t"] >= t0
               and e.get("reason") == "gang_member_lost"]
        if not gml:
            if [t for t in finalized.get(trial, []) if t >= t0]:
                rec["outcome"] = "completed_before_detection"
            else:
                rec["outcome"] = "unrevoked"
                violations.append(
                    "unrevoked gang: kill_gang_member fault on trial {} "
                    "(victim runner {}) produced neither a "
                    "gang_member_lost requeue nor a FINAL".format(
                        trial, ce.get("partition")))
            gang_recs.append(rec)
            continue
        rec["outcome"] = "revoked"
        rec["requeues"] = len(gml)
        rec["revoke_latency_s"] = round(min(e["t"] for e in gml) - t0, 3)
        if len(gml) > 1:
            violations.append(
                "gang over-requeue: trial {} carries {} gang_member_lost "
                "requeues for one kill_gang_member fault".format(
                    trial, len(gml)))
        if not [e for e in gang_released.get(trial, [])
                if e.get("t") is not None and e["t"] >= t0]:
            violations.append(
                "gang lease not released: trial {} was revoked but the "
                "journal carries no gang_released edge after the "
                "fault".format(trial))
        t_req = min(e["t"] for e in gml)
        if not [e for e in gang_assembled.get(trial, [])
                if e.get("t") is not None and e["t"] >= t_req]:
            violations.append(
                "gang never reassembled: trial {} was requeued for "
                "gang_member_lost but no later gang_assembled edge "
                "exists".format(trial))
        gang_recs.append(rec)

    # Invariant 14: checkpoint forks survive runner death. Every
    # kill_fork fault names the forked trial it disturbed: the requeue
    # contract (exactly once) is covered by the generic checks above;
    # on top, the re-dispatch must RESUME from the SAME fork point (a
    # resumed edge whose from_step equals the forked_from step — never
    # a silent from-scratch restart) and the genealogy edge must stay
    # exactly-once per span across the requeue.
    fork_recs: List[Dict[str, Any]] = []
    for ce in chaos_events:
        if ce.get("kind") != "kill_fork":
            continue
        trial, t0 = ce.get("trial"), ce.get("t")
        if trial is None or t0 is None:
            continue
        edges = forked_evs.get(trial, [])
        step = edges[0].get("step") if edges else None
        rec: Dict[str, Any] = {"trial": trial,
                               "partition": ce.get("partition"),
                               "step": step}
        if len(edges) != 1:
            violations.append(
                "fork lineage not exactly-once: trial {} carries {} "
                "forked_from edges".format(trial, len(edges)))
        resumes = [r for r in resumed_evs.get(trial, [])
                   if r.get("t") is not None and r["t"] >= t0]
        if not resumes:
            rec["outcome"] = "not_resumed"
            violations.append(
                "fork lost: kill_fork hit trial {} but no later resumed "
                "edge re-dispatched it from its fork point".format(trial))
        elif step is not None and resumes[0].get("from_step") != step:
            rec["outcome"] = "wrong_fork_point"
            violations.append(
                "fork point drifted: trial {} was forked at step {} but "
                "resumed from_step={}".format(
                    trial, step, resumes[0].get("from_step")))
        else:
            rec["outcome"] = "resumed_from_fork"
            rec["from_step"] = resumes[0].get("from_step")
        fork_recs.append(rec)

    # Invariant 16: a vectorized block dies as a unit and recovers as
    # individuals. A runner-death fault naming ANY lane of an in-flight
    # block (the chaos event may name a non-leader lane — its running
    # edge fired the trigger — while the reservation names the leader)
    # must be followed by the exactly-once requeue of EVERY lane that
    # had not already finalized; non-leader lanes carry reason
    # vmap_block_lost. Phantom FINALs and lost lanes are invariants 1/2
    # above; this block pins the seam-specific contract.
    lane_block: Dict[str, str] = {}
    for bid, lanes_map in block_lanes.items():
        for tr in lanes_map:
            lane_block.setdefault(tr, bid)
    block_kills: Dict[str, List[Dict[str, Any]]] = {}
    for ce in chaos_events:
        if ce.get("kind") not in ("kill_runner", "kill_fork"):
            continue
        bid = lane_block.get(ce.get("trial"))
        if bid is not None and ce.get("t") is not None:
            block_kills.setdefault(bid, []).append(ce)
    vmap_recs: List[Dict[str, Any]] = []
    for bid, kills in sorted(block_kills.items()):
        lanes_map = block_lanes[bid]
        t0 = min(ce["t"] for ce in kills)
        rec: Dict[str, Any] = {"block": bid,
                               "lanes": sorted(lanes_map),
                               "victim": kills[0].get("trial"),
                               "partition": kills[0].get("partition")}
        live = [tr for tr in sorted(lanes_map)
                if not [t for t in finalized.get(tr, []) if t <= t0]]
        if not live:
            rec["outcome"] = "completed_before_detection"
            vmap_recs.append(rec)
            continue
        rec["outcome"] = "requeued"
        rec["live_lanes"] = live
        for tr in live:
            later = [e for e in requeued_evs.get(tr, [])
                     if e.get("t") is not None and e["t"] >= t0]
            n_req = len(requeued.get(tr, []))
            if not later:
                rec["outcome"] = "torn"
                violations.append(
                    "lane lost to the block seam: a runner-death fault "
                    "tore block {} but live lane trial {} was never "
                    "requeued".format(bid, tr))
            elif n_req > len(kills):
                violations.append(
                    "lane over-requeue: trial {} (block {}) was requeued "
                    "{} times for {} runner-death fault(s) on its "
                    "block".format(tr, bid, n_req, len(kills)))
            elif tr != bid and later[0].get("reason") not in (
                    "vmap_block_lost", "preempted"):
                violations.append(
                    "lane requeue reason drift: non-leader lane {} of "
                    "block {} requeued with reason {!r} (expected "
                    "vmap_block_lost)".format(
                        tr, bid, later[0].get("reason")))
        vmap_recs.append(rec)

    # Invariant 5: stall -> health flag. A frozen runner shorter than the
    # loss bound is invisible to the heartbeat-loss scan; the health
    # engine's hang watchdog (or straggler scoring) must still see it,
    # within bounded time, attributed to the right partition.
    from maggy_tpu.telemetry.health import STALL_CHECKS

    stall_flags: List[Dict[str, Any]] = []
    enforce_stall = stall_flag_bound_s is not None and health_engine_ran
    for ce in chaos_events:
        if ce.get("kind") != "stall_runner" or not enforce_stall:
            continue
        pid, t0 = ce.get("partition"), ce.get("t")
        if pid is None or t0 is None:
            continue
        matching = [h for h in health_raised
                    if h.get("partition") == pid
                    and h.get("check") in STALL_CHECKS
                    and h.get("t") is not None
                    and t0 <= h["t"] <= t0 + stall_flag_bound_s]
        rec = {"partition": pid, "t": t0,
               "flagged": bool(matching),
               "flag_latency_s": round(min(h["t"] for h in matching) - t0, 3)
               if matching else None,
               "checks": sorted({h["check"] for h in matching})}
        stall_flags.append(rec)
        if not matching:
            violations.append(
                "unflagged stall: stall_runner fault on partition {} at "
                "t={:.3f} produced no health straggler/hang flag within "
                "{:.1f}s".format(pid, t0, stall_flag_bound_s))

    # Invariant 9, journal half: with the obs plane armed, the FIRST
    # straggler/hang flag per stalled partition yields exactly ONE
    # health-triggered profile artifact — zero means the capture hook
    # never fired, more than one means the per-partition dedup (or the
    # run-wide rate limit) is broken.
    from maggy_tpu.telemetry.profiling import AUTO_CAPTURE_LIMIT

    auto_captures = [p for p in profile_captures
                     if p.get("reason") == "auto"]
    if len(auto_captures) > AUTO_CAPTURE_LIMIT:
        violations.append(
            "profile rate limit broken: {} auto captures journaled "
            "(limit {})".format(len(auto_captures), AUTO_CAPTURE_LIMIT))
    if obs_armed and enforce_stall:
        stalled_pids = []
        for ce in chaos_events:
            if ce.get("kind") == "stall_runner" \
                    and ce.get("partition") is not None \
                    and ce["partition"] not in stalled_pids:
                stalled_pids.append(ce["partition"])
        flagged_pids = {f["partition"] for f in stall_flags
                        if f.get("flagged")}
        for pid in stalled_pids:
            captures = [p for p in auto_captures
                        if p.get("partition") == pid]
            if len(captures) > 1:
                violations.append(
                    "duplicate profile capture: stalled partition {} "
                    "journaled {} auto profile_captured events (expected "
                    "exactly 1)".format(pid, len(captures)))
            elif not captures and pid in flagged_pids \
                    and len(auto_captures) < AUTO_CAPTURE_LIMIT:
                violations.append(
                    "missing profile capture: stalled partition {} was "
                    "health-flagged but journaled no profile_captured "
                    "artifact".format(pid))

    # Invariant 13: driver failover is lossless. Completed trials never
    # re-run (a successful FINAL is durable past a crash — the FINAL-path
    # barrier — so recovery must never re-dispatch one): a ``running``
    # edge after the trial's LAST successful finalized is a double run.
    # Errored trials are exempt — a controller retrying a failed unit of
    # work (PBT segment retry) legitimately re-issues the identical id.
    for trial, times in sorted(finalized_ok.items()):
        t_done = max(times)
        if any(t > t_done for t in running_at.get(trial, [])):
            violations.append(
                "completed trial re-ran: {} has a running edge after its "
                "successful finalized at t={:.3f}".format(trial, t_done))
    # Every kill_driver fault must be followed by a restarted incarnation
    # AND a journal-replay reconstruction marker — a kill with neither
    # means the failover never happened; a restart without ``recovered``
    # means it came back blind (artifact-only resume, not crash-only
    # recovery).
    failover_recs: List[Dict[str, Any]] = []
    for ce in chaos_events:
        if ce.get("kind") != "kill_driver":
            continue
        t0 = ce.get("t")
        if t0 is None:
            continue
        restarts = [d for d in driver_epochs
                    if d.get("t") is not None and d["t"] >= t0]
        recovers = [r for r in recovered_markers
                    if r.get("t") is not None and r["t"] >= t0]
        rec: Dict[str, Any] = {"t": t0}
        if not restarts:
            rec["outcome"] = "no_restart"
            violations.append(
                "driver never restarted: kill_driver at t={:.3f} has no "
                "later driver_epoch event".format(t0))
        elif not recovers:
            rec["outcome"] = "no_recovery"
            violations.append(
                "driver restarted blind: kill_driver at t={:.3f} has a "
                "later driver_epoch but no journal-replay 'recovered' "
                "marker".format(t0))
        else:
            rec["outcome"] = "recovered"
            rec["epoch"] = restarts[0].get("epoch")
            rec["mttr_s"] = round(min(r["t"] for r in recovers) - t0, 3)
        failover_recs.append(rec)

    # Invariant 15: rework chip-time lands EXACTLY on the trials whose
    # attempts the journal shows torn — a runner-death requeue seam
    # (requeued with a non-preempt reason) or a from-scratch promotion
    # (parent recorded but no forked_from edge). The goodput accountant
    # folds the SAME events; a rework second booked on an un-seamed
    # trial means the ledger mis-attributes (and a fault-free soak must
    # book ~zero rework at all — run_goodput_soak gates that side).
    from maggy_tpu.telemetry.goodput import compute_goodput

    goodput = compute_goodput(events)
    per_trial_gp = goodput.get("per_trial") or {}
    seamed = {trial for trial, evs in requeued_evs.items()
              if any(e.get("reason") != "preempted" for e in evs)}
    seamed |= {trial for trial, parent in parent_of.items()
               if parent is not None and trial not in forked_evs}
    rework_trials: Dict[str, float] = {}
    for trial, buckets in sorted(per_trial_gp.items()):
        rw = buckets.get("rework") or 0.0
        if rw > 1e-6:
            rework_trials[trial] = round(rw, 3)
            if trial not in seamed:
                violations.append(
                    "rework misattributed: trial {} books {:.3f}s rework "
                    "chip-time but the journal shows no requeue seam or "
                    "from-scratch promotion for it".format(trial, rw))
    # The positive half: a runner-death fault that tore a STARTED
    # attempt (a running edge precedes the kill) and forced a requeue
    # must show up as rework on that trial — dead-attempt seconds can
    # never fall into unaccounted.
    for ce in chaos_events:
        if ce.get("kind") not in ("kill_runner", "kill_fork"):
            continue
        trial, t0 = ce.get("trial"), ce.get("t")
        if trial is None or t0 is None:
            continue
        if not [t for t in requeued.get(trial, []) if t >= t0]:
            continue  # benign race: covered by the recovery checks above
        if not [t for t in running_at.get(trial, []) if t <= t0]:
            continue  # fault beat the first running edge: nothing torn
        if (per_trial_gp.get(trial) or {}).get("rework", 0.0) <= 1e-6:
            violations.append(
                "rework not booked: {} fault requeued started trial {} "
                "but the goodput ledger books zero rework chip-time for "
                "it".format(ce["kind"], trial))

    by_kind: Dict[str, int] = {}
    for ce in chaos_events:
        by_kind[ce["kind"]] = by_kind.get(ce["kind"], 0) + 1
    return {
        "ok": not violations,
        "violations": violations,
        "trials": {"queued": len(queued),
                   "finalized": sum(1 for v in finalized.values() if v),
                   "requeued": sum(len(v) for v in requeued.values())},
        "faults": {"injected": len(chaos_events), "by_kind": by_kind},
        "recoveries": recoveries,
        "preemptions": preempt_recs,
        "gang_revocations": gang_recs,
        # Invariant 14 (checkpoint-forking search): per-kill_fork
        # outcome — the forked trial's requeue resumed from its exact
        # fork point with lineage intact.
        "forks": fork_recs,
        # Invariant 16 (vectorized micro-trials): per torn block —
        # every live lane requeued exactly once, non-leader lanes with
        # reason vmap_block_lost.
        "vmap_blocks": vmap_recs,
        "health": {"engine_ran": health_engine_ran,
                   "raised": len(health_raised),
                   "by_check": health_by_check,
                   "stall_flags": stall_flags},
        "profiles": {"obs_armed": obs_armed,
                     "captured": len(profile_captures),
                     "auto": len(auto_captures)},
        # Invariant 15 (chip-time goodput ledger): the full fold over
        # this journal plus the rework attribution the invariant
        # verified (seamed = trials allowed to book rework).
        "goodput": goodput,
        "rework": {"trials": rework_trials,
                   "seamed": sorted(seamed),
                   "booked_s": round(sum(rework_trials.values()), 3)},
        # Invariant 13 (crash-only driver failover): incarnation seams,
        # per-kill recovery outcome + MTTR, and how many pre-crash
        # runners re-bound to the restarted driver.
        "failover": {
            "driver_epochs": [d.get("epoch") for d in driver_epochs],
            "kills": sum(1 for ce in chaos_events
                         if ce.get("kind") == "kill_driver"),
            "recoveries": failover_recs,
            "recovered_markers": len(recovered_markers),
            "adopted": adopted,
        },
    }


def assert_invariants(report: Dict[str, Any]) -> None:
    if report["violations"]:
        raise AssertionError(
            "chaos invariants violated:\n  " +
            "\n  ".join(report["violations"]))
