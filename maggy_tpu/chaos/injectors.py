"""Chaos engine: runtime fault injection behind no-op-by-default hooks.

The hook points live in the existing control-plane seams:

- ``Server._dispatch`` (core/rpc.py) consults ``on_server_message`` for
  drop_msg / delay_msg / sever_conn before (or instead of) handling;
- ``Client._request`` (core/rpc.py) consults ``on_client_request`` — a
  condemned runner dies there (``ChaosKilled``), a cooperatively stalled
  one sleeps;
- ``Server._loop`` calls ``tick()`` between selects for elapsed-time
  triggers;
- ``Telemetry.trial_event`` forwards phase transitions to
  ``on_trial_phase`` for on-state-transition triggers;
- ``LocalEnv.dump`` / ``GCSEnv.dump`` / ``exclusive_create`` consult
  ``on_env_write`` for transient storage failures;
- runner pools expose ``kill_worker`` / ``stall_worker`` for the
  process-level faults.

Every hook first calls ``active_engine()`` — None (the default, and the
only state outside a chaos soak) short-circuits to a no-op, so the hot
path pays one global read. The engine is armed by the driver when
``config.chaos`` or ``MAGGY_TPU_CHAOS=<plan.json>`` is set, and every
injection it performs is journaled as a telemetry ``chaos`` event so the
soak harness (and offline replay) can line faults up against the trial
spans they disturbed.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from maggy_tpu.chaos.plan import RUNNER_KINDS, FaultPlan, FaultSpec


class ChaosKilled(ConnectionError):
    """Cooperative runner death (thread pools, where nothing can SIGKILL a
    runner). Subclasses ConnectionError ON PURPOSE: the heartbeat loop
    swallows ConnectionError, so a condemned runner's beats go silent —
    exactly the signature of a dead runner — while the executor's
    request-path calls (get_suggestion / finalize_metric) propagate it and
    kill the runner thread for real. ``Client._request`` re-raises it
    immediately instead of burning reconnect retries on a runner that is
    supposed to be dead."""


# ---------------------------------------------------------------- global arm

_ENGINE: Optional["ChaosEngine"] = None


def active_engine() -> Optional["ChaosEngine"]:
    """The armed engine, or None (the no-op default). Read on every hook —
    keep it a bare global load."""
    return _ENGINE


def arm(engine: "ChaosEngine") -> None:
    global _ENGINE
    _ENGINE = engine


def disarm(engine: Optional["ChaosEngine"] = None) -> None:
    """Disarm fault injection. With ``engine`` given, only if it is the
    one armed (a finished soak must not disarm a newer experiment's)."""
    global _ENGINE
    if engine is None or _ENGINE is engine:
        _ENGINE = None


# -------------------------------------------------------------------- engine


class _SpecState:
    """Mutable trigger bookkeeping for one spec."""

    __slots__ = ("spec", "index", "rng", "fired", "matches", "next_after")

    def __init__(self, spec: FaultSpec, index: int, rng):
        self.spec = spec
        self.index = index
        self.rng = rng
        self.fired = 0      # injections performed
        self.matches = 0    # matching occurrences seen (nth/every_nth basis)
        self.next_after = None  # next after_s deadline (periodic re-arm)

    def exhausted(self) -> bool:
        return self.spec.count > 0 and self.fired >= self.spec.count

    def should_fire_on_match(self) -> bool:
        """Advance the occurrence counter and decide. The decision order is
        a pure function of (plan seed, matching-occurrence ordinal), which
        is what makes two runs of the same plan comparable."""
        if self.exhausted():
            return False
        self.matches += 1
        trig = self.spec.trigger
        if "nth" in trig and "on_phase" not in trig:
            return self.matches == int(trig["nth"])
        if "every_nth" in trig:
            return self.matches % int(trig["every_nth"]) == 0
        if "probability" in trig:
            return self.rng.random() < float(trig["probability"])
        if "on_phase" in trig:
            return self.matches == int(trig.get("nth", 1))
        return False


class ChaosEngine:
    """Executes a FaultPlan against a live experiment. Thread-safe: hooks
    run on the RPC event loop, the driver worker, and runner threads."""

    def __init__(self, plan: FaultPlan, telemetry=None):
        self.plan = plan
        self.telemetry = telemetry
        self._lock = threading.RLock()
        self._t0 = time.monotonic()
        self._states = [_SpecState(s, i, plan.rng_for(i))
                        for i, s in enumerate(plan.specs)]
        self.pool = None
        self.reservations = None
        self.driver = None  # preempt_trial acts through the driver
        # Cooperative (thread-pool) fault state, consulted by the client
        # hook: condemned partitions die on their next request; stalled
        # ones sleep until the deadline.
        self._condemned: set = set()  # guarded-by: _lock
        self._stalled_until: Dict[int, float] = {}  # guarded-by: _lock
        # Partitions under an ACTIVE fake preemption (pid -> mute
        # deadline): the driver's loss-reap must not SIGKILL them — the
        # whole point of the fault is a HEALTHY runner declared lost
        # (the duplicate-FINAL race), and reaping would degrade it into
        # a plain kill on process pools.
        self._preempted: Dict[int, float] = {}  # guarded-by: _lock
        #: Injection log: [{"kind", "t", ...}] — the in-memory mirror of
        #: the journaled chaos events (tests assert on it without a
        #: journal round-trip).
        self.injected: List[Dict[str, Any]] = []

    def attach(self, pool=None, reservations=None, driver=None) -> None:
        """Late-bind the fault surfaces: the pool exists only once
        ``run_experiment`` builds it, the reservations once the server
        does; the driver carries the graceful-preemption entry point."""
        with self._lock:
            if pool is not None:
                self.pool = pool
            if reservations is not None:
                self.reservations = reservations
            if driver is not None:
                self.driver = driver

    # ------------------------------------------------------------- hook API

    def on_server_message(self, msg: Dict[str, Any]):
        """Message-level faults, evaluated where a total message order
        exists (the single server event loop — client-side evaluation
        would be per-process and unordered). Returns None, ("drop",),
        ("delay", seconds) or ("sever",)."""
        verb = msg.get("type")
        pid = msg.get("partition_id")
        fired = None
        action = None
        with self._lock:
            for st in self._states:
                spec = st.spec
                if spec.kind not in ("drop_msg", "delay_msg", "sever_conn"):
                    continue
                if not self._match_target(spec, partition=pid, verb=verb):
                    continue
                if st.should_fire_on_match():
                    st.fired += 1
                    # Decision under the lock; the journal write happens
                    # AFTER release — telemetry takes its own locks, and
                    # holding the engine lock across them is an
                    # acquisition edge the canonical order need not
                    # admit.
                    fired = (spec, st.matches)
                    if spec.kind == "drop_msg":
                        action = ("drop",)
                    elif spec.kind == "delay_msg":
                        action = ("delay", spec.delay_s)
                    else:
                        action = ("sever",)
                    break
        if fired is not None:
            spec, occurrence = fired
            self._journal(spec, partition=pid, verb=verb,
                          occurrence=occurrence)
        return action

    def on_client_request(self, msg: Dict[str, Any]) -> None:
        """Runner-side cooperation: a condemned partition dies here, a
        stalled one freezes (both its request thread and its heartbeat
        thread block on their next call — the SIGSTOP analogue threads
        allow). No fault *decisions* are made here."""
        pid = msg.get("partition_id")
        if pid is None:
            return
        with self._lock:
            condemned = pid in self._condemned
            stall_deadline = self._stalled_until.get(pid)
        if stall_deadline is not None:
            remaining = stall_deadline - time.monotonic()
            if remaining > 0:
                time.sleep(remaining)
            else:
                with self._lock:
                    self._stalled_until.pop(pid, None)
        if condemned:
            raise ChaosKilled(
                "chaos: runner {} killed by fault injection".format(pid))

    def on_env_write(self, path: str) -> None:
        """Raises OSError when an env_write_fail fault fires for ``path``.

        The telemetry journal itself is exempt unconditionally: failing
        its flushes would destroy the very artifact the soak invariants
        are checked against (and a match-anything spec would otherwise
        hit it on every flush)."""
        journal = getattr(self.telemetry, "journal", None)
        if journal is not None and path == getattr(journal, "path", None):
            return
        fired = None
        with self._lock:
            for st in self._states:
                spec = st.spec
                if spec.kind != "env_write_fail":
                    continue
                substr = spec.target.get("path")
                if substr and substr not in path:
                    continue
                if st.should_fire_on_match():
                    st.fired += 1
                    fired = (spec, st.matches)
                    break
        if fired is not None:
            # Journal outside the engine lock (telemetry takes its own
            # locks), then raise the injected failure.
            spec, occurrence = fired
            self._journal(spec, path=path, occurrence=occurrence)
            raise OSError(
                "chaos: injected transient write failure for "
                "{}".format(path))

    def on_trial_phase(self, trial_id: str, phase: str,
                       partition: Optional[int]) -> None:
        """On-state-transition triggers (Telemetry.trial_event forwards
        every journaled phase occurrence here)."""
        fire: List[tuple] = []
        with self._lock:
            for st in self._states:
                spec = st.spec
                if spec.kind not in RUNNER_KINDS:
                    continue
                if spec.trigger.get("on_phase") != phase:
                    continue
                if not self._match_target(spec, partition=partition):
                    continue
                # A runner fault needs a runner: phase events journaled
                # without a partition (queued, stop_flagged) cannot
                # target one — skip WITHOUT consuming the occurrence, so
                # "nth" counts only targetable transitions and the fault
                # never lands on an arbitrary wrong runner.
                tpid = spec.target.get("partition", partition)
                if tpid is None:
                    continue
                if st.should_fire_on_match():
                    st.fired += 1
                    fire.append((st, tpid))
        for st, tpid in fire:
            self._fire_runner_fault(st.spec, tpid, trial=trial_id, phase=phase)

    def tick(self) -> None:
        """Elapsed-time triggers; called between server event-loop selects.
        ``after_s`` is periodic under ``count`` > 1: each firing re-arms
        the deadline one interval later (count=3, after_s=10 means three
        fault episodes ~10 s apart, not a 3-shot burst on consecutive
        ticks)."""
        elapsed = time.monotonic() - self._t0
        fire: List[_SpecState] = []
        with self._lock:
            for st in self._states:
                spec = st.spec
                if spec.kind not in RUNNER_KINDS:
                    continue
                after = spec.trigger.get("after_s")
                if after is None or st.exhausted():
                    continue
                if st.next_after is None:
                    st.next_after = float(after)
                if elapsed >= st.next_after:
                    st.fired += 1
                    st.next_after += float(after)
                    fire.append(st)
        for st in fire:
            # target.partition is validated present for after_s runner
            # faults at plan build (a timed fault has no phase event to
            # name its victim).
            self._fire_runner_fault(st.spec, st.spec.target["partition"])

    # ------------------------------------------------------------- internals

    @staticmethod
    def _match_target(spec: FaultSpec, partition=None, verb=None) -> bool:
        want_pid = spec.target.get("partition")
        if want_pid is not None and (partition is None
                                     or int(partition) != int(want_pid)):
            return False
        want_verb = spec.target.get("verb")
        if want_verb is not None and verb != want_verb:
            return False
        return True

    def _fire_runner_fault(self, spec: FaultSpec, partition,
                           trial: Optional[str] = None,
                           phase: Optional[str] = None) -> None:
        pid = int(partition) if partition is not None else 0
        if trial is None and self.reservations is not None:
            # Timed (after_s) faults have no phase event naming a victim:
            # resolve the trial the partition holds NOW, so the journal
            # event carries it and the harness's fault->requeue invariant
            # covers timed kills too.
            try:
                trial = self.reservations.get_assigned_trial(pid)
            except Exception:  # noqa: BLE001 - journaling must never fail a fault
                trial = None
        detail: Dict[str, Any] = {}
        if spec.kind in ("kill_runner", "kill_fork"):
            # Real kill when the pool can (process pools); cooperative
            # connection-death otherwise. Condemn EITHER WAY: a SIGKILLed
            # process cannot race it, and on thread pools it is the kill.
            # kill_fork is the same mechanism with a FORKED victim: the
            # on_phase=forked_from trigger names the runner the forked
            # trial was just dispatched to, so invariant 14 can assert
            # the exactly-once requeue resumes from the same fork point.
            with self._lock:
                self._condemned.add(pid)
            killed = bool(self.pool is not None
                          and self.pool.kill_worker(pid))
            detail["mechanism"] = "sigkill" if killed else "cooperative"
        elif spec.kind == "stall_runner":
            stalled = bool(self.pool is not None and
                           getattr(self.pool, "stall_worker", None) is not None
                           and self.pool.stall_worker(pid, spec.duration_s))
            if not stalled:
                with self._lock:
                    self._stalled_until[pid] = (time.monotonic()
                                                + spec.duration_s)
            detail["mechanism"] = "sigstop" if stalled else "cooperative"
            detail["duration_s"] = spec.duration_s
        elif spec.kind == "preempt_trial":
            # GRACEFUL preemption through the driver: the trial's
            # early-stop machinery carries a preempt-flagged STOP, the
            # runner acks with its last checkpoint step, and the driver
            # requeues the trial to resume there (invariant 7 checks the
            # preempted -> resumed -> single-FINAL chain).
            drv = self.driver
            preempted = None
            if drv is not None \
                    and hasattr(drv, "preempt_partition"):
                try:
                    preempted = drv.preempt_partition(pid, evict=False)
                except Exception:  # noqa: BLE001 - injection must never crash the hook
                    preempted = None
            if preempted is not None:
                trial = preempted
            detail["mechanism"] = "graceful" if preempted is not None \
                else "noop"
        elif spec.kind == "kill_gang_member":
            # Kill one NON-leader member of the trial's assembled gang
            # (the on_phase=gang_assembled event's partition IS the
            # leader; killing the leader is the ordinary LOST path the
            # kill_runner fault already covers). Victim choice is
            # deterministic: the lowest member id that isn't the
            # triggering partition. Falls back to the triggering
            # partition when the gang table is gone (released in the
            # window between trigger and firing) so the injection is
            # journaled either way.
            members: List[int] = []
            if self.driver is not None and trial is not None:
                try:
                    members = [int(m)
                               for m in self.driver.gang_members(trial)
                               if int(m) != pid]
                except Exception:  # noqa: BLE001 - injection must never crash the hook
                    members = []
            victim = min(members) if members else pid
            with self._lock:
                self._condemned.add(victim)
            killed = bool(self.pool is not None
                          and self.pool.kill_worker(victim))
            detail["mechanism"] = "sigkill" if killed else "cooperative"
            detail["leader"] = pid
            pid = victim
        elif spec.kind == "fake_preemption":
            # The runner stays alive; only the driver's view of its
            # heartbeats is aged — the falsely-declared-lost race. The
            # mute window (duration_s, set >= hb_loss_timeout in plans)
            # keeps the runner's ongoing beats from refreshing last_beat
            # before the loss scan looks.
            if self.reservations is not None:
                self.reservations.age_beat(pid, 3600.0,
                                           mute_s=spec.duration_s)
            with self._lock:
                self._preempted[pid] = time.monotonic() + spec.duration_s
            detail["mechanism"] = "aged_heartbeat"
            detail["mute_s"] = spec.duration_s
        self._journal(spec, partition=pid, trial=trial, phase=phase, **detail)

    def _journal(self, spec: FaultSpec, **fields: Any) -> None:
        record = {"kind": spec.kind, "t": time.time(),
                  **{k: v for k, v in fields.items() if v is not None}}
        with self._lock:
            self.injected.append(record)
        telem = self.telemetry
        if telem is not None:
            telem.event("chaos", **{k: v for k, v in record.items()
                                    if k != "t"})

    def suppress_reap(self, partition) -> bool:
        """True while ``partition`` is under an active fake preemption:
        the driver's heartbeat-loss reap must leave the (healthy) runner
        alive so it can deliver the duplicate FINAL the fault exists to
        provoke."""
        if partition is None:
            return False
        with self._lock:
            deadline = self._preempted.get(int(partition))
            if deadline is None:
                return False
            if time.monotonic() > deadline:
                del self._preempted[int(partition)]
                return False
            return True

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            by_kind: Dict[str, int] = {}
            for rec in self.injected:
                by_kind[rec["kind"]] = by_kind.get(rec["kind"], 0) + 1
            return {"injected": len(self.injected), "by_kind": by_kind}
