"""Declarative, seeded fault plans.

A ``FaultPlan`` is a JSON-serializable list of ``FaultSpec``s plus one
seed. Every random decision the chaos engine makes (e.g. "drop this METRIC
with probability 0.05") draws from a per-spec ``random.Random`` stream
derived deterministically from ``(seed, spec index)`` — so the same plan +
seed always yields the same fault schedule over the same message/phase
stream, and two runs of a soak are comparable injection-for-injection.
``fingerprint()`` exposes that determinism as a pure value: equal plans
with equal seeds produce byte-identical fingerprints, which is what the
CLI prints and the determinism test asserts on.

A spec names WHAT to inject (kind), WHERE (target selector), and WHEN
(trigger):

kinds
    ``kill_runner``      kill the targeted runner (SIGKILL on process
                         pools; cooperative connection-death on thread
                         pools) — its trial must be requeued via
                         heartbeat loss.
    ``stall_runner``     freeze the runner for ``duration_s`` (SIGSTOP/
                         SIGCONT on process pools; RPC-hook sleep on
                         thread pools) — the classic straggler.
    ``fake_preemption``  age the runner's heartbeat record so the driver
                         declares it lost while it is actually alive —
                         the falsely-declared-lost race (duplicate-FINAL
                         path).
    ``kill_gang_member`` kill one non-leader member of an assembled
                         gang (fire it ``on_phase: gang_assembled``) —
                         the whole gang lease must be revoked, the
                         members returned to the pool, and the trial
                         requeued exactly once.
    ``kill_fork``        kill the runner a forked trial was dispatched
                         to (fire it ``on_phase: forked_from``) — the
                         trial must be requeued exactly once and resume
                         from the SAME fork point (invariant 14).
    ``drop_msg``         the server discards a matching request unseen
                         and resets the connection (message lost; the
                         client's retry path re-delivers).
    ``delay_msg``        the server stalls ``delay_s`` before handling a
                         matching request (control-plane hiccup).
    ``sever_conn``       the server handles a matching request but drops
                         the connection INSTEAD of replying — the client
                         retries and the handler runs twice
                         (at-least-once delivery).
    ``env_write_fail``   a matching ``env.dump``/``exclusive_create``
                         raises OSError (transient storage failure).

target (all keys optional; omitted = match anything)
    ``partition``   runner index the fault applies to.
    ``verb``        RPC message type (METRIC, FINAL, GET, REG, ...) for
                    message faults.
    ``path``        substring of the write path for env_write_fail.

trigger (exactly one of)
    ``after_s``      elapsed seconds since the engine was armed
                     (runner-level faults; evaluated on the server tick).
    ``nth``          the Nth matching occurrence (1-based): message for
                     message faults, write for env faults, phase
                     transition when combined with ``on_phase``.
    ``every_nth``    every Nth matching occurrence.
    ``probability``  per-occurrence Bernoulli draw from the spec's seeded
                     stream.
    ``on_phase``     a trial-span phase transition (spans.PHASES), e.g.
                     fire the kill when the Nth trial starts ``running``
                     (``nth`` defaults to 1).

``count`` caps total injections for the spec (default 1 for runner-level
faults, unbounded for message/env faults).
"""

from __future__ import annotations

import json
import random
from typing import Any, Dict, List, Optional

KINDS = (
    "kill_runner",
    "stall_runner",
    "fake_preemption",
    "preempt_trial",
    "kill_gang_member",
    "kill_fork",
    "drop_msg",
    "delay_msg",
    "sever_conn",
    "env_write_fail",
)

#: Kinds that act on a runner (fired from ticks / phase transitions), as
#: opposed to per-message / per-write faults evaluated at a hook site.
#: ``preempt_trial`` exercises the GRACEFUL checkpoint-assisted
#: preemption path (the fleet scheduler's mechanism): the driver flags
#: the partition's trial, the runner acks with its checkpoint step, and
#: the trial must resume from that step — invariant 7.
#: ``kill_gang_member`` kills one NON-LEADER member of an assembled
#: gang (trigger it ``on_phase: gang_assembled`` so the event names the
#: gang trial; the engine resolves the victim through the driver's gang
#: table) — the whole gang's lease must be revoked and the trial
#: requeued exactly once (invariant 8).
#: ``kill_fork`` kills the runner a FORKED trial was just dispatched to
#: (trigger it ``on_phase: forked_from`` — the genealogy edge names both
#: the trial and its runner): the trial must be requeued exactly once
#: and resume from the SAME fork point, lineage intact (invariant 14).
RUNNER_KINDS = ("kill_runner", "stall_runner", "fake_preemption",
                "preempt_trial", "kill_gang_member", "kill_fork")

_TRIGGER_KEYS = ("after_s", "nth", "every_nth", "probability", "on_phase")


class FaultSpec:
    """One declarative fault. Plain-dict in, plain-dict out."""

    __slots__ = ("kind", "target", "trigger", "delay_s", "duration_s", "count")

    def __init__(self, kind: str, target: Optional[Dict[str, Any]] = None,
                 trigger: Optional[Dict[str, Any]] = None,
                 delay_s: float = 0.05, duration_s: float = 1.0,
                 count: Optional[int] = None):
        if kind not in KINDS:
            raise ValueError("Unknown fault kind {!r}; choose from {}".format(
                kind, KINDS))
        self.kind = kind
        self.target = dict(target or {})
        self.trigger = dict(trigger or {})
        unknown = set(self.trigger) - set(_TRIGGER_KEYS)
        if unknown:
            raise ValueError("Unknown trigger key(s) {} in {!r} spec; valid: "
                             "{}".format(sorted(unknown), kind, _TRIGGER_KEYS))
        present = sorted(k for k in _TRIGGER_KEYS if k in self.trigger)
        if not present:
            raise ValueError(
                "{!r} spec needs a trigger (one of {})".format(
                    kind, _TRIGGER_KEYS))
        # Exactly one trigger, with the single documented combination
        # on_phase+nth ("the Nth such transition"). Anything else would
        # be resolved by silent precedence — the opposite of the
        # fail-loudly contract a chaos plan needs.
        if len(present) > 1 and present != ["nth", "on_phase"]:
            raise ValueError(
                "{!r} spec has ambiguous triggers {}: use exactly one "
                "(or on_phase combined with nth)".format(kind, present))
        # Reject combinations no hook site ever evaluates — a plan built
        # from one would be a silent no-op and the soak would pass with
        # zero injections, which is worse than failing loudly here.
        if kind in RUNNER_KINDS:
            if not ("after_s" in self.trigger or "on_phase" in self.trigger):
                raise ValueError(
                    "{!r} is a runner fault: it fires from the server tick "
                    "(after_s) or a span phase transition (on_phase), not "
                    "from per-message triggers — got {}".format(
                        kind, sorted(self.trigger)))
            if "after_s" in self.trigger and \
                    self.target.get("partition") is None:
                raise ValueError(
                    "{!r} with an after_s trigger needs target.partition: "
                    "a timed runner fault has no phase event to name its "
                    "victim (on_phase faults target the transitioning "
                    "runner)".format(kind))
        else:
            if "after_s" in self.trigger or "on_phase" in self.trigger:
                raise ValueError(
                    "{!r} is a per-occurrence fault: trigger it with nth / "
                    "every_nth / probability, not after_s/on_phase — got "
                    "{}".format(kind, sorted(self.trigger)))
        phase = self.trigger.get("on_phase")
        if phase is not None:
            from maggy_tpu.telemetry.spans import PHASES

            if phase not in PHASES:
                raise ValueError(
                    "on_phase {!r} is not a span phase; valid: {}".format(
                        phase, PHASES))
        self.delay_s = float(delay_s)
        self.duration_s = float(duration_s)
        # Runner faults default to one-shot; message/env faults recur.
        if count is None:
            count = 1 if kind in RUNNER_KINDS else 0  # 0 = unbounded
        self.count = int(count)

    def to_dict(self) -> Dict[str, Any]:
        return {"kind": self.kind, "target": dict(self.target),
                "trigger": dict(self.trigger), "delay_s": self.delay_s,
                "duration_s": self.duration_s, "count": self.count}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "FaultSpec":
        return cls(kind=d["kind"], target=d.get("target"),
                   trigger=d.get("trigger"), delay_s=d.get("delay_s", 0.05),
                   duration_s=d.get("duration_s", 1.0), count=d.get("count"))

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return "FaultSpec({})".format(self.to_dict())


class FaultPlan:
    """A seed plus an ordered list of fault specs."""

    def __init__(self, specs: List[FaultSpec], seed: int = 0):
        self.specs = list(specs)
        self.seed = int(seed)

    # ------------------------------------------------------------- serialize

    def to_json(self) -> str:
        return json.dumps({"seed": self.seed,
                           "faults": [s.to_dict() for s in self.specs]},
                          indent=2)

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        data = json.loads(text)
        return cls([FaultSpec.from_dict(d) for d in data.get("faults", [])],
                   seed=data.get("seed", 0))

    @classmethod
    def load(cls, path: str, env=None) -> "FaultPlan":
        """Read a plan file through ``env`` when given, else the local fs."""
        if env is not None:
            return cls.from_json(env.load(path))
        with open(path) as f:
            return cls.from_json(f.read())

    # ----------------------------------------------------------- determinism

    def rng_for(self, spec_index: int) -> random.Random:
        """The spec's private decision stream. Seeded from a STRING so the
        derivation is platform-stable (str seeding hashes via sha512,
        unaffected by PYTHONHASHSEED)."""
        return random.Random("maggy_chaos:{}:{}".format(self.seed, spec_index))

    def fingerprint(self, draws: int = 64) -> List[Dict[str, Any]]:
        """Pure expansion of the plan's decision schedule: per spec, the
        trigger parameters plus (for probability triggers) the first
        ``draws`` Bernoulli outcomes of its seeded stream. Equal plan +
        equal seed => equal fingerprint; this is the artifact the
        same-seed-same-schedule acceptance check compares."""
        out = []
        for i, spec in enumerate(self.specs):
            entry: Dict[str, Any] = {"kind": spec.kind,
                                     "target": dict(spec.target),
                                     "trigger": dict(spec.trigger)}
            p = spec.trigger.get("probability")
            if p is not None:
                rng = self.rng_for(i)
                entry["decisions"] = [rng.random() < float(p)
                                      for _ in range(draws)]
            out.append(entry)
        return out
