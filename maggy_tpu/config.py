"""Typed experiment configs; the config type selects the experiment kind.

Parity: reference `maggy/experiment_config.py:18-81` (LagomConfig base,
OptimizationConfig, AblationConfig, DistributedConfig). Redesigned for TPU:
``DistributedConfig`` describes a JAX mesh + sharding strategy instead of a
torch module, and every config carries ``num_workers`` explicitly (the
reference infers it from Spark dynamic-allocation settings,
`hopsworks.py:236-244`, which has no TPU analogue).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Sequence, Union

from maggy_tpu import constants
from maggy_tpu.searchspace import Searchspace


@dataclass
class LagomConfig:
    """Base config (reference `experiment_config.py:18-23`)."""

    name: str = "maggyTpuExperiment"
    description: str = ""
    hb_interval: float = constants.DEFAULT_HEARTBEAT_INTERVAL_S
    #: Print a live progress line while the experiment runs (the reference
    #: streams a progress bar to Jupyter, `util.py:71-86`).
    verbose: bool = False
    #: Unified telemetry (maggy_tpu.telemetry): trial-span tracing, metric
    #: registry, and the <exp_dir>/telemetry.jsonl journal the TELEM RPC
    #: verb / `monitor --telem` / bench.py read. Record paths are
    #: buffer-only (journal writes happen on a background flusher), so the
    #: default-on cost on the message hot path is a few dict ops.
    telemetry: bool = True
    #: Heartbeat-loss detection shape (used when ``hb_loss_timeout`` is
    #: None): a runner is declared lost after
    #: max(hb_loss_min_s, hb_interval * hb_loss_factor) seconds of
    #: silence. Overridable per experiment so soak/chaos tests can tighten
    #: failure detection without monkeypatching module globals.
    hb_loss_factor: float = constants.HEARTBEAT_LOSS_FACTOR
    hb_loss_min_s: float = constants.HEARTBEAT_LOSS_MIN_S
    #: Fault injection (maggy_tpu.chaos): a FaultPlan instance or a path
    #: to a plan JSON. None (default) = every chaos hook is a no-op. Also
    #: armable without touching code via MAGGY_TPU_CHAOS=<plan.json>.
    chaos: Any = None
    #: Live health engine (maggy_tpu.telemetry.health): periodic
    #: straggler/hang/RTT-degradation analysis over spans + runner stats,
    #: journaled as ``health`` events and surfaced via TELEM /
    #: ``monitor --health``. Requires telemetry; off when telemetry is.
    health: bool = True
    #: Seconds between health checks; None -> max(0.25, hb_interval).
    health_interval_s: Optional[float] = None
    #: Hang watchdog: a partition holding a trial with no journal progress
    #: for ``health_hang_factor * hb_interval`` seconds is flagged (with a
    #: faulthandler thread dump journaled). Deliberately below the
    #: heartbeat-loss shape so sub-loss stalls — which the loss scan can
    #: never see — still surface.
    health_hang_factor: float = 25.0

    #: Live observability plane (maggy_tpu.telemetry.obs): an HTTP server
    #: exposing GET /metrics (Prometheus text format), /status (TELEM
    #: snapshot + live trial-store/reservation/gang/fleet state),
    #: /healthz (200/503 from the health engine's raised findings) and
    #: /profilez (on-demand jax.profiler capture). None (the default) =
    #: OFF: no socket is opened and behavior is bit-for-bit unchanged.
    #: 0 = bind an ephemeral port (journaled as an ``obs_started`` event
    #: so tools can discover it). Also armable without touching code via
    #: MAGGY_TPU_OBS_PORT. One obs server per process — a second
    #: experiment in the same process joins the first one's listener.
    obs_port: Optional[int] = None
    #: Obs bind host. Loopback by default: the endpoints are
    #: unauthenticated (Prometheus-style), so exposing them beyond the
    #: host is an explicit operator decision.
    obs_host: str = "127.0.0.1"

    #: Shared-fleet attachment (maggy_tpu.fleet): a FleetBinding placed
    #: here by ``experiment.lagom_submit`` / ``Fleet.submit`` makes the
    #: driver LEASE runners from the fleet scheduler (weighted fair share,
    #: priority classes, quotas, checkpoint-assisted preemption) and
    #: publish its RPC server on the fleet's shared listener. None (the
    #: default, and always the case for plain ``lagom()``) preserves the
    #: classic single-tenant behavior bit-for-bit — ``lagom()`` is simply
    #: a fleet of one that owns its pool.
    fleet: Any = None
    #: Fleet journal-sink routing (maggy_tpu.telemetry.sink): True makes
    #: a FLEET-ATTACHED experiment ship its telemetry journal to the
    #: fleet's journal sink over the shared socket (one process-wide
    #: shipper thread, no per-tenant flusher — what re-enables telemetry
    #: for 500-tenant churn) instead of writing <exp_dir>/telemetry.jsonl
    #: directly; that local path becomes the degradation fallback the
    #: shipper falls back to (and re-ships from) when the sink is down.
    #: Ignored (plain local journal) without a fleet or with the fleet's
    #: sink disabled. Default False: bit-for-bit the classic layout.
    sink: bool = False

    def resolved_obs_port(self) -> Optional[int]:
        """The observability server port to bind, or None for off: the
        explicit ``obs_port`` field when set, else MAGGY_TPU_OBS_PORT
        (empty/unparsable = off). The ONE home of this resolution — the
        drivers and the fleet both consult it."""
        if self.obs_port is not None:
            return int(self.obs_port)
        return resolved_env_obs_port()

    def resolved_hb_loss_timeout(self) -> float:
        """Seconds of heartbeat silence before a runner/worker is
        declared lost: the explicit ``hb_loss_timeout`` field when set
        (OptimizationConfig/DistributedConfig), else the configured shape
        max(hb_loss_min_s, hb_interval * hb_loss_factor). The ONE home of
        this resolution — both driver families consult it."""
        explicit = getattr(self, "hb_loss_timeout", None)
        if explicit is not None:
            return float(explicit)
        return max(self.hb_loss_min_s,
                   self.hb_interval * self.hb_loss_factor)


def resolved_env_obs_port() -> Optional[int]:
    """MAGGY_TPU_OBS_PORT as an int, or None when unset/empty/garbage."""
    raw = os.environ.get("MAGGY_TPU_OBS_PORT", "").strip()
    if not raw:
        return None
    try:
        return int(raw)
    except ValueError:
        return None


@dataclass
class OptimizationConfig(LagomConfig):
    """Hyperparameter-optimization experiment (reference `experiment_config.py:25-50`).

    ``optimizer`` is a registry name ("randomsearch", "gridsearch", "asha",
    "tpe", "gp", "none") or an AbstractOptimizer instance. ``num_workers`` is
    the number of concurrent trial runners (local processes or TPU sub-slice
    agents); it is clamped to ``num_trials`` by the driver.
    """

    num_trials: int = 1
    optimizer: Union[str, Any] = "randomsearch"
    searchspace: Optional[Searchspace] = None
    optimization_key: str = "metric"
    direction: str = "max"
    es_interval: int = constants.DEFAULT_ES_INTERVAL
    es_min: int = constants.DEFAULT_ES_MIN
    es_policy: Union[str, Any] = constants.DEFAULT_ES_POLICY
    # Concurrent trial runners, or "auto" to size from the runtime device
    # inventory (one runner per local chip subset for pool="tpu", one per
    # local device otherwise) — the reference reads its executor count
    # from cluster conf at runtime (`hopsworks.py:236-244`).
    num_workers: Union[int, str] = 1
    seed: Optional[int] = None
    # Runner substrate: "thread" (in-process), "process" (one JAX runtime
    # per trial), "tpu" (processes pinned to disjoint chip sub-slices),
    # "remote" (external `python -m maggy_tpu.runner` agents join over DCN).
    pool: str = "thread"
    # Control-plane bind host. Defaults to loopback for local pools; set to
    # "0.0.0.0" (the default when pool="remote") to accept remote agents.
    bind_host: Optional[str] = None
    # Per-trial device assignment: how many TPU chips each trial gets
    # (used by pool="tpu").
    chips_per_trial: int = 1
    # Multi-chip trial sizing: budget -> chip need. Two mechanisms share
    # the declaration, selected by the pool:
    # - pool="elastic" (int values): budget-sized chip sub-slices —
    #   runners exit and respawn re-pinned when their capacity doesn't
    #   match the next trial's requirement (SURVEY §7.3's
    #   slice-repartitioning problem). Budgets missing from the map use
    #   chips_per_trial.
    # - pool="thread" / fleet mode (int or maggy_tpu.gang.GangSpec
    #   values): GANG SCHEDULING — the driver assembles N fleet runners
    #   (runner ≈ chip) into one contiguous mesh slice, dispatches the
    #   trial to a designated leader (ctx.gang carries the mesh axes +
    #   strategy), and holds the members until the trial releases. A
    #   bare int N is shorthand for GangSpec(N) (dp mesh). Packing is
    #   topology-aware (best-fit aligned contiguous blocks, journaled
    #   pack events — see docs/user.md "Multi-chip sweeps").
    # A Searchspace GANG entry declares the same thing per trial instead
    # of per budget (and lets the sweep SEARCH over sharding shapes).
    chips_per_budget: Optional[Dict[Any, Any]] = None
    # Total chips the elastic pool may lease (None -> probe the host).
    total_chips: Optional[int] = None
    # Pipelined trial hand-off: the driver pre-materializes controller
    # suggestions on a dedicated suggester thread (up to one per live
    # runner) and the FINAL reply carries the next TRIAL (or GSTOP)
    # inline, so the common hand-off costs zero extra round trips and
    # never waits on a model fit. GET polling remains the fallback
    # (registration, idle wake-ups, requeues). False restores the
    # synchronous pre-pipelining behavior exactly; controllers that
    # override get_suggestion wholesale (no report/suggest split) fall
    # back automatically. See docs/telemetry.md "Hand-off path".
    prefetch: bool = True
    # Compile-once hot path (train/warm.py): runners keep the compiled
    # train step, computed shardings, and donated state buffers resident
    # across trials whose program identity matches (model config, mesh
    # topology, strategy, input shapes, swept-optimizer family), so a
    # repeat-shape trial's time-to-first-metric drops from a fresh XLA
    # trace+compile (20-40 s on TPU) to near dispatch cost. State VALUES
    # are always recomputed per trial — only memory and executables are
    # reused — and resumed/promoted trials never consume retired buffers.
    # False restores the build-per-trial behavior bit-for-bit.
    warm_start: bool = True
    # Checkpoint-forking search (docs/user.md "Forking search"): an ASHA
    # promotion / PBT exploit-or-continue segment / BO near-duplicate is
    # dispatched with ``forked_from`` + ``resume_step`` stamped into its
    # assignment, the executor stages the parent's checkpoint into the
    # child's trial dir (train/checkpoint.fork_checkpoint), and a ctx-
    # aware train fn RESUMES from that step instead of re-training the
    # parent's prefix — at the top ASHA rungs this recovers the
    # rung-ratio multiple of compute. Requires the train fn to
    # checkpoint via ctx (fns that never checkpoint simply run from
    # scratch — the stamp resolves to no checkpoint and is skipped).
    # False restores from-scratch promotions bit-for-bit.
    fork: bool = True
    # Vectorized micro-trials (docs/user.md "Vectorized sweeps"): the
    # driver packs up to this many COMPATIBLE suggestions (same
    # non-float params, same budget, no gang spec — the driver-side
    # proxy for the warm-cache program key) into one block and delivers
    # the whole block to one runner in a single TRIAL; the executor runs
    # all lanes in lockstep as ONE vmapped program (train/vmap.py), so a
    # small-model sweep fills the chip across the hyperparameter axis
    # instead of one trial at a time. Early stop masks a lane without
    # recompiling; each lane keeps its own span/METRIC/FINAL. 1 (the
    # default) disables block assembly and restores the scalar dispatch
    # path bit-for-bit.
    vmap_lanes: int = 1
    # Capture a jax.profiler trace per trial into its TensorBoard dir.
    profile: bool = False
    # Tee the user train_fn's print() calls into the reporter log channel,
    # streaming them to the driver/monitor on heartbeats (the reference
    # ships prints to Jupyter by patching builtins.print,
    # `trial_executor.py:71-81`). Off by default: reporter.log() is the
    # explicit channel; this flag restores the reference behavior.
    ship_prints: bool = False
    # Declare a runner lost after this many seconds of heartbeat silence
    # while holding a trial (its trial is requeued to another runner).
    # None -> max(HEARTBEAT_LOSS_MIN_S, hb_interval * HEARTBEAT_LOSS_FACTOR).
    hb_loss_timeout: Optional[float] = None
    # Experiment artifact root; defaults to the environment's base dir.
    experiment_dir: Optional[str] = None
    # Resume the most recent interrupted run of this app: finalized trials
    # are reloaded from their trial.json artifacts and skipped; unfinished
    # ones re-run. Pruner (Hyperband/ASHA bracket) state restores from its
    # checkpoint; sampling optimizers must carry a fixed seed.
    resume: bool = False

    def __post_init__(self):
        if self.direction not in ("max", "min"):
            raise ValueError("direction must be 'max' or 'min', got {!r}".format(self.direction))
        if self.pool not in ("thread", "process", "tpu", "elastic", "remote"):
            raise ValueError(
                "pool must be 'thread', 'process', 'tpu', 'elastic', or "
                "'remote'")
        if not isinstance(self.vmap_lanes, int) \
                or isinstance(self.vmap_lanes, bool) or self.vmap_lanes < 1:
            raise ValueError(
                "vmap_lanes must be an int >= 1 (1 = scalar dispatch), "
                "got {!r}".format(self.vmap_lanes))
        if self.vmap_lanes > 1 and self.chips_per_budget is not None:
            raise ValueError(
                "vmap_lanes packs K trials onto ONE chip; gang-scheduled "
                "sweeps (chips_per_budget) size trials the other way — "
                "pick one")
        if self.chips_per_budget is not None and \
                self.pool not in ("elastic", "thread"):
            raise ValueError(
                "chips_per_budget needs pool='elastic' (budget-sized "
                "respawnable pinned workers) or pool='thread' "
                "(gang-scheduled runner groups); got pool={!r}".format(
                    self.pool))
        if self.chips_per_budget is not None and self.pool == "elastic":
            from maggy_tpu.gang import GangSpec

            if any(isinstance(v, (GangSpec, dict))
                   for v in self.chips_per_budget.values()):
                raise ValueError(
                    "GangSpec chips_per_budget values gang-schedule fleet "
                    "runners and need pool='thread' (or fleet mode); the "
                    "elastic pool respawns single pinned runners from int "
                    "chip counts")
        if self.searchspace is not None:
            gang_names = [n for n in self.searchspace.names()
                          if self.searchspace.get_type(n) == "GANG"]
            if gang_names and self.pool not in ("thread",):
                raise ValueError(
                    "a Searchspace GANG entry gang-schedules fleet runners "
                    "and needs pool='thread' (or fleet mode); got "
                    "pool={!r}".format(self.pool))
            if len(gang_names) > 1:
                raise ValueError(
                    "at most one Searchspace GANG entry per sweep (a trial "
                    "runs on one gang); got {}".format(gang_names))
        if isinstance(self.num_workers, str) and self.num_workers != "auto":
            raise ValueError(
                "num_workers must be an int or 'auto', got {!r}".format(
                    self.num_workers))
        if self.bind_host is None and self.pool == "remote":
            self.bind_host = "0.0.0.0"


@dataclass
class AblationConfig(OptimizationConfig):
    """Ablation-study experiment (reference `experiment_config.py:52-66`).

    Subclasses OptimizationConfig for the shared driver-plumbing fields
    (num_workers/pool/direction/...); `optimizer` and the early-stop knobs
    are ignored — ablation schedules are fixed and never early-stop
    (reference `ablation_driver.py:33`).
    """

    ablation_study: Any = None
    ablator: Union[str, Any] = "loco"
    es_policy: str = "none"


@dataclass
class DistributedConfig(LagomConfig):
    """Distributed data/model-parallel training of ONE model (reference
    `experiment_config.py:68-81`, where it carried a torch module + datasets).

    TPU-native version: the user's ``train_fn`` receives a `ShardingEnv`
    (mesh + named shardings + process info) instead of a DDP-wrapped model;
    gradients flow over ICI via XLA collectives inserted by GSPMD.
    """

    #: Flax module / model spec forwarded to the train function.
    model: Any = None
    train_set: Any = None
    test_set: Any = None
    #: Number of participating processes (multi-host world size).
    num_workers: int = 1
    #: Logical mesh axes, e.g. {"data": 8} or {"data": 4, "model": 2}.
    mesh_shape: Dict[str, int] = field(default_factory=dict)
    #: Parallelism strategy name: "dp", "fsdp", "tp", "dp_tp", "sp".
    strategy: str = "dp"
    #: Worker substrate: None/"process" (local processes), "thread" (tests),
    #: "remote" (external `python -m maggy_tpu.runner` agents over DCN).
    backend: Optional[str] = None
    #: Control-plane bind host; defaults to 0.0.0.0 when backend="remote".
    bind_host: Optional[str] = None
    #: Declare a worker dead after this many seconds of heartbeat silence
    #: (the experiment fails — a dead SPMD rank wedges the world).
    #: None -> max(HEARTBEAT_LOSS_MIN_S, hb_interval * HEARTBEAT_LOSS_FACTOR).
    hb_loss_timeout: Optional[float] = None
    #: Capture a jax.profiler trace per worker into the experiment dir.
    profile: bool = False
    experiment_dir: Optional[str] = None

    def __post_init__(self):
        if self.bind_host is None and self.backend == "remote":
            self.bind_host = "0.0.0.0"
