"""Framework-wide constants.

Parity: reference `maggy/constants.py:23-28` (allowed user-function return
types and numeric types). Extended with TPU-framework defaults.
"""

from __future__ import annotations

import os

import numpy as np


class USER_FCT:
    """Allowed return types of a user training function."""

    RETURN_TYPES = (float, int, np.number, dict)
    NUMERIC_TYPES = (float, int, np.number)


# Control-plane defaults (see BASELINE.md "scheduling constants").
DEFAULT_HEARTBEAT_INTERVAL_S = 1.0
DRIVER_IDLE_REQUEUE_TICK_S = 0.1
# First GET retry after a miss; doubles up to DRIVER_IDLE_REQUEUE_TICK_S.
CLIENT_GET_POLL_MIN_S = 0.005
# DIST_CONFIG rendezvous poll cap: same fast-start doubling as GET (from
# CLIENT_GET_POLL_MIN_S), backing off to this once the wait is clearly a
# still-registering world rather than a race.
CLIENT_DIST_CONFIG_POLL_MAX_S = 0.5
CLIENT_POLL_INTERVAL_S = 1.0
# Pipelined hand-off (config.prefetch): how long the FINAL fast path may
# wait for the driver's schedule lock before falling back to the worker
# queue (reply OK, runner GET-polls). The lock is only ever contended
# while the suggester thread is mid-model-fit, so this bounds the RPC
# event loop's worst-case stall per FINAL.
PREFETCH_FINAL_LOCK_TIMEOUT_S = 0.05
REGISTRATION_TIMEOUT_S = 600.0
# Checkpoint-forking search (config.fork): how long a forked trial may be
# held for the runner that ran its parent (parent affinity — warm slot +
# locally staged checkpoint) before ANY idle runner takes it. A few idle
# ticks: affinity is a preference, never a scheduling stall.
FORK_AFFINITY_HOLD_S = float(os.environ.get(
    "MAGGY_TPU_FORK_AFFINITY_HOLD_S", "0.5"))
# Bound between an elastic RESIZE request and the respawned runner's
# REGISTER. A respawn that wedges before registering (e.g. a stale device
# claim at backend init) never heartbeats, so heartbeat-loss detection
# cannot see it — this is its liveness bound.
RESIZE_RESPAWN_TIMEOUT_S = 120.0
RENDEZVOUS_TIMEOUT_S = 60.0
# Request retry budget. Env-overridable (MAGGY_TPU_CLIENT_MAX_RETRIES)
# because the right value depends on how long a DEAD CONTROL PLANE may
# stay dead: the default ~0.5 s horizon suits transient blips, while
# crash-only driver failover (the runner must outlive the driver's
# restart — process spawn + jax import + journal replay, seconds to tens
# of seconds) needs runners that keep retrying across the window; the
# driver soak raises it for its runner-agent processes.
CLIENT_MAX_RETRIES = int(os.environ.get("MAGGY_TPU_CLIENT_MAX_RETRIES",
                                        "3"))
# Client retry backoff: exponential from BASE doubling to CAP, with full
# jitter (a fixed cadence synchronizes every client's retry storm onto a
# recovering server).
CLIENT_RETRY_BACKOFF_BASE_S = 0.05
CLIENT_RETRY_BACKOFF_CAP_S = 2.0
RPC_RECV_BUFSIZE = 1 << 16
# Heartbeat batching: beats whose ship failed are kept client-side
# (coalesced per trial, rstats stripped — the rstats delta requeues into
# the runner-stats buffer separately) and shipped together as ONE BATCH
# frame on the next beat. The bounds cap memory on a long driver outage
# — beat COUNT and coalesced LOG LINES per banked beat; beyond them the
# oldest entries are dropped, which matches the pre-batching behavior
# (a failed beat's payload was simply lost).
CLIENT_MAX_PENDING_BEATS = 16
CLIENT_MAX_PENDING_LOG_LINES = 500
# Shared-fleet control plane (rpc.SharedServer): bounded per-tenant
# dispatch queue depth. A tenant whose handlers fall behind fills its own
# queue; further frames for THAT tenant are dropped with the connection
# (the client's retry/backoff path re-delivers), which is the per-tenant
# backpressure signal — other tenants' queues are unaffected.
TENANT_DISPATCH_QUEUE_DEPTH = 512

# Failure detection: a runner whose assigned trial has gone this many
# heartbeat intervals without any message is declared lost and its trial is
# requeued to another runner (floor guards against sub-second hb_interval
# settings declaring a compiling trial dead). Defaults for the
# ``hb_loss_factor`` / ``hb_loss_min_s`` config fields — override THOSE
# (e.g. chaos soaks tightening failure detection), not these globals.
HEARTBEAT_LOSS_FACTOR = 30.0
HEARTBEAT_LOSS_MIN_S = 10.0

# Multi-fidelity bracket-state checkpoint (resume=True with Hyperband).
PRUNER_STATE_FILE = ".pruner_state.json"

# Early-stop defaults (reference `maggy/experiment_config.py:33-35`).
DEFAULT_ES_INTERVAL = 1
DEFAULT_ES_MIN = 10
DEFAULT_ES_POLICY = "median"
