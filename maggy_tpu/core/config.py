"""Runtime-mode detection.

Parity: reference `maggy/core/config.py:17-37` detects HOPSWORKS vs
SPARK_ONLY from env vars at import. TPU-native equivalent: LOCAL vs TPU_VM
vs TPU_POD, from the TPU runtime's env markers — used for runner-pool and
environment defaults. Detection is lazy (a function, not import-time state)
so tests can monkeypatch the environment.
"""

from __future__ import annotations

import os
from typing import Literal

Mode = Literal["LOCAL", "TPU_VM", "TPU_POD"]


def detect_mode() -> Mode:
    """LOCAL (no TPU), TPU_VM (single host with chips), or TPU_POD
    (multi-host slice)."""
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if hostnames and len(hostnames.split(",")) > 1:
        return "TPU_POD"
    if _has_tpu():
        return "TPU_VM"
    return "LOCAL"


def _has_tpu() -> bool:
    if os.environ.get("TPU_SKIP_MDS_QUERY") or os.environ.get("TPU_WORKER_ID"):
        return True
    try:
        import jax

        return jax.default_backend() in ("tpu", "axon")
    except Exception:  # noqa: BLE001
        return False


def default_pool_type() -> str:
    """Sensible runner-pool default for the detected mode."""
    return "thread" if detect_mode() == "LOCAL" else "tpu"


def num_local_chips() -> int:
    try:
        import jax

        return len([d for d in jax.local_devices()
                    if d.platform in ("tpu", "axon")])
    except Exception:  # noqa: BLE001
        return 0
