from maggy_tpu.core.driver.driver import Driver
from maggy_tpu.core.driver.optimization_driver import OptimizationDriver

__all__ = ["Driver", "OptimizationDriver"]
