"""Ablation-study driver.

Parity: reference `maggy/core/experiment_driver/ablation_driver.py` —
subclasses the HPO driver (:26), forces no early stopping (:33), controller =
LOCO over the study with num_trials from the ablator (:46-49), executor runs
in ablation mode (:95-106) resolving declarative specs to
dataset/model generators.
"""

from __future__ import annotations

from maggy_tpu.ablation.ablator import LOCO, AbstractAblator
from maggy_tpu.config import AblationConfig
from maggy_tpu.core.driver.optimization_driver import OptimizationDriver
from maggy_tpu.core.executors.trial_executor import trial_executor_fn
from maggy_tpu.earlystop import NoStoppingRule

ABLATOR_REGISTRY = {"loco": LOCO}


class AblationDriver(OptimizationDriver):
    def __init__(self, config: AblationConfig, app_id: str, run_id: int):
        if getattr(config, "pool", "thread") == "remote":
            raise ValueError(
                "pool='remote' is not supported for ablation studies: the "
                "study's model/dataset generators are local callables and "
                "cannot be shipped to remote agents. Use a local pool."
            )
        super().__init__(config, app_id, run_id)
        # Early stopping is meaningless for a fixed ablation schedule
        # (reference `ablation_driver.py:33`).
        self.earlystop_check = NoStoppingRule

    @staticmethod
    def _init_controller(config):
        ablator = config.ablator
        if isinstance(ablator, str):
            key = ablator.lower()
            if key not in ABLATOR_REGISTRY:
                raise ValueError(
                    "Unknown ablator '{}'; choose from {} or pass an "
                    "AbstractAblator instance.".format(ablator, sorted(ABLATOR_REGISTRY))
                )
            return ABLATOR_REGISTRY[key](config.ablation_study)
        if not isinstance(ablator, AbstractAblator):
            raise TypeError("ablator must be a name or AbstractAblator instance")
        return ablator

    def _resolve_num_trials(self, config) -> int:
        return self.controller.get_number_of_trials()

    def _executor_fn(self, train_fn):
        return trial_executor_fn(
            server_addr=self.server_addr,
            secret=self.secret_for_clients(),
            hb_interval=self.hb_interval,
            exp_dir=self.exp_dir,
            optimization_key=self.optimization_key,
            train_fn=train_fn,
            trial_type="ablation",
            ablation_resolver=self.controller.make_resolver(),
            profile=getattr(self.config, "profile", False),
            ship_prints=getattr(self.config, "ship_prints", False),
            warm_start=getattr(self.config, "warm_start", True),
        )

    def _exp_startup_callback(self) -> None:
        import time

        self.job_start = time.time()
        self.env.update_experiment(
            self.exp_dir, {"ablation_study": self.config.ablation_study.to_dict()}
        )
