"""Distributed-training driver.

Parity: reference `maggy/core/experiment_driver/distributed_driver.py:23-73`
— DistributedServer, per-worker FINAL metrics collected into `results`,
experiment result = their average; only METRIC(logs) and FINAL callbacks.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List

from maggy_tpu.config import DistributedConfig
from maggy_tpu.core.driver.driver import Driver
from maggy_tpu.core.executors.dist_executor import dist_executor_fn
from maggy_tpu.core.rpc import DistributedServer
from maggy_tpu.core.runner_pool import ProcessRunnerPool, ThreadRunnerPool


class DistributedDriver(Driver):
    def __init__(self, config: DistributedConfig, app_id: str, run_id: int):
        self.num_workers = config.num_workers
        super().__init__(config, app_id, run_id)
        self.results: List[float] = []
        self._results_lock = threading.Lock()
        self.job_start = None

    def _make_server(self):
        return DistributedServer(self.num_workers, secret=self.secret)

    def _make_runner_pool(self):
        # Real multi-process SPMD needs one JAX runtime per worker; a single
        # worker (or tests) can run in-thread.
        if self.num_workers == 1:
            return ThreadRunnerPool(1)
        backend = getattr(self.config, "backend", None)
        if backend == "thread":
            return ThreadRunnerPool(self.num_workers)
        return ProcessRunnerPool(self.num_workers)

    def _executor_fn(self, train_fn):
        return dist_executor_fn(
            server_addr=self.server_addr,
            secret=self.server.secret_hex,
            hb_interval=self.hb_interval,
            exp_dir=self.exp_dir,
            train_fn=train_fn,
            config=self.config,
            num_workers=self.num_workers,
        )

    def _register_msg_callbacks(self) -> None:
        self.message_callbacks.update(
            METRIC=self._log_msg_callback,
            FINAL=self._final_msg_callback,
        )

    def _log_msg_callback(self, msg) -> None:
        self.add_executor_logs(msg.get("logs"))

    def _final_msg_callback(self, msg) -> None:
        self.add_executor_logs(msg.get("logs"))
        if msg.get("value") is not None:
            with self._results_lock:
                self.results.append(float(msg["value"]))

    def _exp_startup_callback(self) -> None:
        self.job_start = time.time()

    def _exp_final_callback(self, job_end: float, exp_json: Dict[str, Any]):
        with self._results_lock:
            avg = sum(self.results) / len(self.results) if self.results else None
        result = {"average_metric": avg, "per_worker": list(self.results),
                  "num_workers": self.num_workers,
                  "duration_s": job_end - (self.job_start or job_end)}
        self.env.dump(json.dumps(result, indent=2), self.exp_dir + "/result.json")
        self.env.finalize_experiment(self.exp_dir, "FINISHED", {"result": result})
        return result

    def _exp_exception_callback(self, exc) -> None:
        self.env.finalize_experiment(self.exp_dir, "FAILED", {"error": repr(exc)})
        raise exc

    def progress_snapshot(self) -> Dict[str, Any]:
        with self._results_lock:
            return {"workers_done": len(self.results), "num_workers": self.num_workers}
