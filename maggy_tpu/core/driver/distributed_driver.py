"""Distributed-training driver.

Parity: reference `maggy/core/experiment_driver/distributed_driver.py:23-73`
— DistributedServer, per-worker FINAL metrics collected into `results`,
experiment result = their average; only METRIC(logs) and FINAL callbacks.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List

from maggy_tpu.config import DistributedConfig
from maggy_tpu.core.driver.driver import Driver
from maggy_tpu.core.executors.dist_executor import dist_executor_fn
from maggy_tpu.core.rpc import DistributedServer
from maggy_tpu.core.runner_pool import ProcessRunnerPool, ThreadRunnerPool


class DistributedDriver(Driver):
    def __init__(self, config: DistributedConfig, app_id: str, run_id: int):
        self.num_workers = config.num_workers
        self.num_executors = config.num_workers  # RemoteRunnerPool contract
        super().__init__(config, app_id, run_id)
        self.results: List[float] = []  # guarded-by: _results_lock
        self._finals = 0  # guarded-by: _results_lock
        self._worker_errors = 0  # guarded-by: _results_lock
        self._results_lock = threading.Lock()
        self.job_start = None
        # A silent SPMD worker deadlocks the whole world's collectives —
        # heartbeat loss surfaces it as a failed experiment rather than a
        # hang (see DistributedServer._tick).
        self.server.hb_loss_timeout = config.resolved_hb_loss_timeout()

    def _make_server(self):
        return DistributedServer(self.num_workers, secret=self.secret)

    def _make_runner_pool(self):
        backend = getattr(self.config, "backend", None)
        if backend == "remote":
            # Multi-host SPMD: each TPU VM runs `python -m maggy_tpu.runner
            # --train mod:fn` and JOINs; worker 0's advertised endpoint
            # becomes the jax.distributed coordinator.
            from maggy_tpu.core.runner_pool import RemoteRunnerPool

            self.server.join_info = {
                "hb_interval": self.hb_interval,
                "exp_dir": self.exp_dir,
                "optimization_key": "metric",
                "trial_type": "distributed",
                "num_workers": self.num_workers,
                "mesh_shape": dict(self.config.mesh_shape or {}),
                "strategy": self.config.strategy,
            }
            return RemoteRunnerPool(self)
        # Real multi-process SPMD needs one JAX runtime per worker; a single
        # worker (or tests) can run in-thread.
        if self.num_workers == 1:
            return ThreadRunnerPool(1)
        if backend == "thread":
            return ThreadRunnerPool(self.num_workers)
        return ProcessRunnerPool(self.num_workers)

    def _executor_fn(self, train_fn):
        return dist_executor_fn(
            server_addr=self.server_addr,
            secret=self.server.secret_hex,
            hb_interval=self.hb_interval,
            exp_dir=self.exp_dir,
            train_fn=train_fn,
            config=self.config,
            num_workers=self.num_workers,
        )

    def _register_msg_callbacks(self) -> None:
        self.message_callbacks.update(
            METRIC=self._log_msg_callback,
            FINAL=self._final_msg_callback,
            DEAD_WORKER=self._dead_worker_msg_callback,
        )

    def _dead_worker_msg_callback(self, msg) -> None:
        self.exception = RuntimeError(
            "Distributed worker {} stopped heartbeating; a dead rank wedges "
            "the SPMD world, aborting the experiment.".format(msg["partition_id"]))
        self.experiment_done = True
        self._terminate_active_pool()

    def _terminate_active_pool(self) -> None:
        """Tear down local worker processes: survivors of a failed/dead rank
        may be wedged in a collective and would block run_experiment."""
        pool = getattr(self, "_active_pool", None)
        if pool is not None:
            pool.terminate()

    def _log_msg_callback(self, msg) -> None:
        self.add_executor_logs(msg.get("logs"))

    def _final_msg_callback(self, msg) -> None:
        self.add_executor_logs(msg.get("logs"))
        self.telemetry.metrics.counter(
            "dist.finals.error" if msg.get("error") else "dist.finals.ok").inc()
        with self._results_lock:
            self._finals += 1
            # Fail fast on the FIRST errored rank: a failed worker dooms the
            # SPMD world, so waiting for the rest (who may be wedged in a
            # collective) only delays the inevitable FAILED verdict.
            done = self._finals >= self.num_workers or bool(msg.get("error"))
            if msg.get("error"):
                self._worker_errors += 1
            elif msg.get("value") is not None:
                self.results.append(float(msg["value"]))
        if done:
            # Lets the remote pool stop waiting (local pools end when their
            # worker processes return).
            self.experiment_done = True
        if msg.get("error"):
            # Fail fast (remote agents notice via their own collective
            # timeouts).
            self._terminate_active_pool()

    def _exp_startup_callback(self) -> None:
        self.job_start = time.time()

    def _exp_final_callback(self, job_end: float, exp_json: Dict[str, Any]):
        with self._results_lock:
            if self._worker_errors:
                # A failed rank means the "average" covers a partial world —
                # that is a failed experiment, not a FINISHED one.
                raise RuntimeError(
                    "{} of {} distributed workers failed (see worker logs in "
                    "{}).".format(self._worker_errors, self.num_workers,
                                  self.exp_dir))
        with self._results_lock:
            avg = sum(self.results) / len(self.results) if self.results else None
            per_worker = list(self.results)
        result = {"average_metric": avg, "per_worker": per_worker,
                  "num_workers": self.num_workers,
                  "duration_s": job_end - (self.job_start or job_end)}
        self.env.dump(json.dumps(result, indent=2), self.exp_dir + "/result.json")
        self.env.finalize_experiment(self.exp_dir, "FINISHED", {"result": result})
        return result

    def _exp_exception_callback(self, exc) -> None:
        self.env.finalize_experiment(self.exp_dir, "FAILED", {"error": repr(exc)})
        raise exc

    def progress_snapshot(self) -> Dict[str, Any]:
        with self._results_lock:
            return {"workers_done": self._finals, "num_workers": self.num_workers}
