"""Abstract experiment driver: the control-plane kernel.

Parity: reference `maggy/core/experiment_driver/driver.py` — owns the RPC
server + per-experiment secret (:54-57,74-79), a message queue consumed by a
daemon worker thread dispatching to registered callbacks (:59-61,140-158),
and the experiment lifecycle `run_experiment`: startup callback -> register
experiment -> start server+worker -> fan out executors -> final callback ->
stop (:81-117).

Redesign: the Spark `sc.parallelize(...).foreachPartition` fan-out
(`driver.py:96-106`) is replaced by a pluggable `RunnerPool` that launches N
trial-runner workers (threads in-process, local processes, or TPU-VM agent
processes pinned to chip sub-slices).
"""

from __future__ import annotations

import json
import os
import queue
import secrets as pysecrets
import threading
import time
import traceback
from abc import ABC, abstractmethod
from typing import Any, Callable, Dict, Optional

from maggy_tpu.core.environment import EnvSing

#: Per-run control-plane identity, persisted into the experiment dir at
#: init: the shared secret and the bound (host, port). Crash-only
#: recovery reads it so the restarted driver comes back ON THE SAME
#: SECRET AND ADDRESS — surviving runners' reconnect/retry loops then
#: re-bind without any new discovery step. Same trust domain as the
#: runner ticket (which already carries the secret for remote pools).
DRIVER_STATE_FILE = "driver_state.json"


class Driver(ABC):
    def __init__(self, config, app_id: str, run_id: int):
        from maggy_tpu import util

        self.config = config
        self.app_id = app_id
        self.run_id = run_id
        self.name = config.name
        self.description = getattr(config, "description", "")
        self.hb_interval = getattr(config, "hb_interval", 1.0)
        self.env = EnvSing.get_instance()
        # Incarnation claim BEFORE anything touches the run dir's
        # artifacts: exactly one driver may (re-)enter a run dir at a
        # time — the loser of a two-restarting-drivers adoption race
        # exits with RunAdoptionError here, before register_experiment
        # could clobber the interrupted run's metadata. Fresh runs claim
        # epoch 1 (their dir was staked exclusively by claim_run_id);
        # resume claims the next epoch.
        base = getattr(config, "experiment_dir", None) \
            or self.env.experiment_base_dir()
        run_dir = "{}/{}_{}".format(base.rstrip("/"), app_id, run_id)
        self.driver_epoch = util.claim_driver_epoch(run_dir, env=self.env)
        # Pre-crash control-plane identity (crash-only recovery): reuse
        # the interrupted incarnation's secret so still-live runners'
        # HMAC-authenticated frames keep verifying against this server.
        self.driver_state: Optional[Dict[str, Any]] = None
        if getattr(config, "resume", False):
            state_path = run_dir + "/" + DRIVER_STATE_FILE
            if self.env.exists(state_path):
                try:
                    self.driver_state = json.loads(self.env.load(state_path))
                except ValueError:
                    self.driver_state = None  # torn write: fresh identity
        self.secret = (self.driver_state or {}).get("secret") \
            or pysecrets.token_hex(16)

        self.server = self._make_server()
        self.server.attach_driver(self)
        self.server_addr: Optional[tuple] = None

        self._message_q: "queue.Queue[Dict[str, Any]]" = queue.Queue()
        self.message_callbacks: Dict[str, Callable[[Dict[str, Any]], None]] = {}
        self.worker_done = False
        self.experiment_done = False  # unguarded-ok: monotonic completion latch, polled lock-free by design
        self._worker_thread: Optional[threading.Thread] = None
        self.executor_logs: list = []  # guarded-by: _log_lock
        self._log_lock = threading.Lock()
        self.exception: Optional[BaseException] = None

        self.exp_dir = self.env.register_experiment(
            app_id, run_id,
            {"name": self.name, "description": self.description,
             "type": type(self).__name__},
            base_dir=getattr(config, "experiment_dir", None),
        )
        self.log_file = None
        # Unified telemetry: metrics registry + trial spans + JSONL journal
        # under the experiment dir. The server exposes it via the TELEM
        # verb and times its verbs through it; record paths are buffer-only
        # (the journal flushes on its own daemon thread), so attaching it
        # costs the message hot path no I/O.
        from maggy_tpu.telemetry import JOURNAL_NAME, Telemetry

        # Fleet-attached experiments may route the journal through the
        # fleet's journal SINK (config.sink, telemetry/sink.py): events
        # ship over the shared socket to <fleet_home>/journal/<name>.jsonl
        # and the local path below becomes the degradation fallback.
        sink_binding = None
        sink_source = None
        fleet_binding = getattr(config, "fleet", None)
        if fleet_binding is not None and getattr(config, "sink", False):
            sink_binding = fleet_binding.fleet.sink_binding()
            sink_source = fleet_binding.entry.name
        self.telemetry = Telemetry(
            env=self.env, journal_path=self.exp_dir + "/" + JOURNAL_NAME,
            enabled=getattr(config, "telemetry", True),
            sink=sink_binding, sink_source=sink_source)
        self.server.telemetry = self.telemetry
        if getattr(config, "resume", False):
            # One continuous journal across interruptions: replaying it
            # must cover the whole logical experiment, not just this
            # process's lifetime.
            restored = 0
            if self.telemetry.journal is not None:
                restored = self.telemetry.journal.load_existing()
            # Span state rides the journal too: restored trials keep
            # their pre-crash span ids and first-occurrence timestamps,
            # so post-recovery phase events continue the same spans.
            self.telemetry.restore_spans()
            self.telemetry.event("experiment", phase="resumed",
                                 restored_events=restored)
        # Incarnation boundary marker: the seam recovery and invariant 13
        # split a multi-incarnation journal on.
        self.telemetry.event("driver_epoch", epoch=self.driver_epoch)
        self.telemetry.event("experiment", phase="start", name=self.name,
                             driver=type(self).__name__, app_id=app_id,
                             run_id=run_id)
        # Fault injection (maggy_tpu.chaos): armed ONLY when a plan is
        # named — via config.chaos (FaultPlan or plan-JSON path) or
        # MAGGY_TPU_CHAOS=<plan.json>. Unarmed, every chaos hook in the
        # RPC/pool/env seams is a no-op global read.
        self.chaos = None
        plan_src = getattr(config, "chaos", None) \
            or os.environ.get("MAGGY_TPU_CHAOS")
        if plan_src:
            from maggy_tpu.chaos import ChaosEngine, FaultPlan, arm

            if not self.telemetry.enabled:
                # Without telemetry there are no phase events to trigger
                # on and no journal to record injections in — the plan
                # would be a silent no-op and the soak would "pass".
                raise ValueError(
                    "chaos fault injection requires telemetry=True: "
                    "on_phase triggers ride trial-span events and every "
                    "injection must be journaled for the recovery "
                    "invariants to be checkable")
            plan = plan_src if isinstance(plan_src, FaultPlan) \
                else FaultPlan.load(plan_src, env=self.env)
            self.chaos = ChaosEngine(plan, telemetry=self.telemetry)
            self.chaos.attach(reservations=self.server.reservations,
                              driver=self)
            # Phase transitions feed on-state-transition triggers.
            self.telemetry.chaos_hook = self.chaos.on_trial_phase
            arm(self.chaos)
            self.telemetry.event("chaos_armed", seed=plan.seed,
                                 specs=len(plan.specs))
        # Live health engine: periodic straggler/hang/RTT analysis over
        # spans + runner stats, on its own daemon thread (buffer-only
        # record paths, like the journal flusher). Feeds on telemetry, so
        # it follows telemetry's enablement.
        self.health = None
        if self.telemetry.enabled and getattr(config, "health", True):
            from maggy_tpu.telemetry.health import (DEFAULT_HANG_FACTOR,
                                                    HealthEngine)

            self.health = HealthEngine(
                self.telemetry, hb_interval=self.hb_interval,
                interval_s=getattr(config, "health_interval_s", None),
                hang_factor=getattr(config, "health_hang_factor",
                                    DEFAULT_HANG_FACTOR))
            self.health.attach(reservations=self.server.reservations)
            self.telemetry.health = self.health
            self.health.start()
        # Live observability plane (maggy_tpu.telemetry.obs): /metrics,
        # /status, /healthz, /profilez over the process-wide HTTP server.
        # OFF unless config.obs_port / MAGGY_TPU_OBS_PORT names a port —
        # with it unset, no socket is opened and nothing below runs. When
        # on, the health engine additionally gains the auto-capture hook:
        # the first straggler/hang raise per partition yields a device
        # profile + thread dump under exp_dir/profiles/, journaled as a
        # ``profile_captured`` event.
        self.obs_registration = None
        self.profiler = None
        obs_port = None
        resolver = getattr(config, "resolved_obs_port", None)
        if resolver is not None:
            obs_port = resolver()
        if obs_port is not None and self.telemetry.enabled:
            from maggy_tpu.telemetry import obs as obs_mod
            from maggy_tpu.telemetry.profiling import ProfileCapturer

            self.profiler = ProfileCapturer(
                self.telemetry,
                profile_dir=self.exp_dir + "/profiles")
            if self.health is not None:
                self.health.attach(profiler=self.profiler)
            self.obs_registration = obs_mod.ObsRegistration(
                key="{}/{}".format(app_id, run_id),
                labels={"experiment": self.name,
                        "run": "{}/{}".format(app_id, run_id)},
                telemetry=self.telemetry,
                status_fn=self.obs_status,
                health=self.health,
                profiler=self.profiler)
            server = obs_mod.register(
                self.obs_registration, port=obs_port,
                host=getattr(config, "obs_host", "127.0.0.1"))
            # Discovery record: port 0 binds an ephemeral port, and the
            # journal is where tools (monitor --live, the soak scraper)
            # learn the real address.
            self.telemetry.event(
                "obs_started", host=server.address[0],
                port=server.address[1], experiment=self.name,
                app_id=app_id, run_id=run_id)
        self._register_msg_callbacks()

    # ------------------------------------------------------------- template

    @abstractmethod
    def _make_server(self):
        ...

    @abstractmethod
    def _make_runner_pool(self):
        ...

    @abstractmethod
    def _executor_fn(self, train_fn) -> Callable:
        """Build the worker closure each runner executes (the reference's
        `_patching_fn`, `driver.py:160-162`)."""

    def _exp_startup_callback(self) -> None:
        pass

    def _exp_final_callback(self, job_end: float, exp_json: dict) -> Any:
        return None

    def _exp_exception_callback(self, exc: BaseException) -> None:
        raise exc

    @abstractmethod
    def _register_msg_callbacks(self) -> None:
        ...

    # ------------------------------------------------------------ lifecycle

    def run_experiment(self, train_fn: Callable) -> Any:
        job_start = time.time()
        result = None
        try:
            self._exp_startup_callback()
            self.init()
            # Fleet mode (config.fleet): the driver LEASES runners from
            # the shared fleet scheduler instead of owning a pool — the
            # leased pool registers this experiment's executor and blocks
            # until completion, exactly like a pool.run would.
            binding = getattr(self.config, "fleet", None)
            pool = binding.lease_pool(self) if binding is not None \
                else self._make_runner_pool()
            self._active_pool = pool
            if self.chaos is not None:
                # Late-bind the pool: kill/stall faults act through it.
                self.chaos.attach(pool=pool)
            # Fan out the executor wrapper to all runners; BLOCKS until all
            # workers return (the reference's foreachPartition semantics).
            failures = pool.run(self._executor_fn(train_fn)) or []
            job_end = time.time()
            # A worker-callback failure must surface BEFORE finalization, or
            # the experiment would transiently be marked FINISHED with a
            # bogus result.json.
            if self.exception is not None:
                raise self.exception
            # Dead runners are survivable IF the surviving ones completed the
            # schedule (their trials were requeued via heartbeat-loss
            # detection); otherwise the failure is fatal.
            if failures:
                if self.experiment_done:
                    self._log("{} runner(s) died but the experiment completed: "
                              "{}".format(len(failures), failures))
                else:
                    raise RuntimeError(
                        "{} runner(s) failed and the experiment did not "
                        "complete: {}".format(len(failures), failures)
                    ) from failures[0]
            result = self._exp_final_callback(job_end, {})
            return result
        except BaseException as exc:  # noqa: BLE001 - driver must always clean up
            self._exp_exception_callback(exc)
        finally:
            self.stop()

    def init(self) -> None:
        binding = getattr(self.config, "fleet", None)
        if binding is not None:
            # Fleet mode: this experiment's traffic shares the fleet's ONE
            # listening socket, routed by which experiment secret
            # authenticates each frame (rpc.SharedServer).
            self.server_addr = binding.attach_server(self.server)
        else:
            host = getattr(self.config, "bind_host", None)
            prev_port = int((self.driver_state or {}).get("port") or 0)
            try:
                # Crash-only recovery: rebind the pre-crash port so
                # surviving runners' reconnect loops (they hold the old
                # (host, port)) land on the restarted server. The dead
                # process's socket is gone, so the rebind succeeds
                # unless another process squatted the port meanwhile.
                self.server_addr = self.env.connect_host(
                    self.server, host=host, port=prev_port)
            except OSError as e:
                if prev_port == 0:
                    raise
                # A still-bound pre-crash port is the strongest available
                # evidence the PRIOR incarnation is alive (wedged, not
                # dead): the epoch marker arbitrates RACING adopters, but
                # it cannot see a predecessor that claimed earlier and
                # never exited — binding fresh here would run two live
                # control planes against one run dir. Refuse; the
                # operator clears driver_state.json if the port is in
                # fact squatted by an unrelated process.
                from maggy_tpu.exceptions import RunAdoptionError

                raise RunAdoptionError(
                    "cannot adopt run {}: the pre-crash control-plane "
                    "port {} is still bound ({}) — the prior driver "
                    "incarnation appears to be alive. If the port is "
                    "held by an unrelated process, delete {}/{} and "
                    "resume on a fresh port (pre-crash runners will "
                    "requeue via the liveness scan).".format(
                        self.exp_dir, prev_port, e, self.exp_dir,
                        DRIVER_STATE_FILE)) from e
        # Persist the control-plane identity: what a future incarnation
        # needs to come back on the same secret and address.
        try:
            self.env.dump(json.dumps({
                "secret": self.secret,
                "host": self.server_addr[0],
                "port": int(self.server_addr[1]),
                "driver_epoch": self.driver_epoch,
                "os_pid": os.getpid(),
            }), self.exp_dir + "/" + DRIVER_STATE_FILE)
        except Exception:  # noqa: BLE001 - identity mirror must not kill a run
            pass
        self._start_worker()
        if getattr(self.config, "verbose", False):
            self._start_progress_printer()

    def _start_progress_printer(self) -> None:
        """Live progress line on stdout (the reference's Jupyter progress
        bar, `util.py:71-86`); remote observers use `maggy_tpu.monitor`."""
        from maggy_tpu import monitor

        def printer():
            last = None
            while not self.worker_done:
                line = monitor.render(self.progress_snapshot())
                if line != last:
                    print("[{}] {}".format(self.name, line), flush=True)
                    last = line
                time.sleep(1.0)

        threading.Thread(target=printer, daemon=True,
                         name="progress-printer").start()

    def _start_worker(self) -> None:
        def worker():
            while not self.worker_done:
                try:
                    msg = self._message_q.get(timeout=0.1)
                except queue.Empty:
                    continue
                callback = self.message_callbacks.get(msg.get("type"))
                if callback is None:
                    continue
                try:
                    callback(msg)
                except Exception as exc:  # noqa: BLE001 - keep worker alive, surface later
                    # Flags before the slow traceback log (see
                    # _suggester_loop: an exception observer must already
                    # see the experiment done).
                    self.exception = exc
                    self.experiment_done = True
                    self._log("worker callback error: {}".format(traceback.format_exc()))

        self._worker_thread = threading.Thread(target=worker, daemon=True, name="driver-worker")
        self._worker_thread.start()

    def stop(self) -> None:
        self.worker_done = True
        # unguarded-ok: cross-thread completion latch — monotonic bool,
        # readers poll it lock-free by design
        self.experiment_done = True
        if self._worker_thread is not None:
            self._worker_thread.join(timeout=5)
        if self.obs_registration is not None:
            # Deregister BEFORE the telemetry teardown: a scrape landing
            # mid-stop must not read a closing journal. The process obs
            # listener itself closes only when the last experiment
            # leaves.
            from maggy_tpu.telemetry import obs as obs_mod

            obs_mod.deregister(self.obs_registration)
            self.obs_registration = None
        if self.health is not None:
            self.health.close()
        self.server.stop()
        if self.chaos is not None:
            # Journal the injection tally, then disarm (only if WE are the
            # armed engine — a newer experiment's must survive).
            from maggy_tpu.chaos import disarm

            self.telemetry.event("chaos_summary", **self.chaos.summary())
            disarm(self.chaos)
        self.telemetry.event("experiment", phase="end")
        self.telemetry.close()

    # ------------------------------------------------------------- services

    def enqueue(self, msg: Dict[str, Any]) -> None:
        self._message_q.put(msg)

    def secret_for_clients(self) -> str:
        return self.server.secret_hex

    def get_trial(self, trial_id: str):
        return None

    def progress_snapshot(self) -> Dict[str, Any]:
        return {}

    def obs_status(self) -> Dict[str, Any]:
        """Live control-plane state for the obs /status route: progress
        plus the reservation table (who holds what). Subclasses extend
        with their own stores (trial backlog, gangs, fleet shares).
        Read-only and lock-brief — runs on an obs handler thread, never
        holding more than one structure's lock at a time."""
        progress = {k: v for k, v in self.progress_snapshot().items()
                    if k not in ("log_tail", "log_total")}
        reservations = {}
        for pid, rec in self.server.reservations.all().items():
            reservations[pid] = {
                "trial": rec.get("trial_id"),
                "released": bool(rec.get("released")),
                "evict": bool(rec.get("evict")),
                "gang": rec.get("gang"),
                "capacity": rec.get("capacity"),
            }
        return {"experiment": self.name, "app_id": self.app_id,
                "run_id": self.run_id, "driver": type(self).__name__,
                "done": self.experiment_done, "progress": progress,
                "reservations": reservations}

    def _log(self, msg: str) -> None:
        line = "{} ({}/{}): {}".format(
            time.strftime("%Y-%m-%d %H:%M:%S"), self.app_id, self.run_id, msg
        )
        with self._log_lock:
            try:
                with self.env.open_file(self.exp_dir + "/maggy.log", "a") as f:
                    f.write(line + "\n")
            except OSError:
                pass

    def add_executor_logs(self, logs) -> None:
        if logs:
            with self._log_lock:
                self.executor_logs.extend(logs)
