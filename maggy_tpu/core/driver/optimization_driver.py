"""HPO experiment driver.

Parity: reference `maggy/core/experiment_driver/optimization_driver.py` —
optimizer registry (:35-43), executor clamping (:57-59), pruner/gridsearch
num_trials overrides (:63-69), controller wiring to trial/final stores
(:87-93), message callbacks METRIC/BLACK/FINAL/IDLE/REG (:331-457), result
aggregation best/worst/avg (:247-307), finalize writing result.json +
experiment summary (:158-194).
"""

from __future__ import annotations

import json
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

from maggy_tpu import constants, util
from maggy_tpu import gang as gang_mod
from maggy_tpu.config import OptimizationConfig
from maggy_tpu.core.driver.driver import Driver
from maggy_tpu.core.executors.trial_executor import trial_executor_fn
from maggy_tpu.core.rpc import OptimizationServer
from maggy_tpu.core.runner_pool import ThreadRunnerPool, resolve_num_workers
from maggy_tpu.earlystop import MedianStoppingRule, NoStoppingRule
from maggy_tpu.optimizers import PBT, Asha, GridSearch, RandomSearch, SingleRun
from maggy_tpu.optimizers.abstractoptimizer import AbstractOptimizer
from maggy_tpu.trial import Trial


def _lazy_gp(**kwargs):
    from maggy_tpu.optimizers.bayes import GP

    return GP(**kwargs)


def _lazy_tpe(**kwargs):
    from maggy_tpu.optimizers.bayes import TPE

    return TPE(**kwargs)


# "gp"/"tpe" resolve lazily: the BO stack pulls sklearn/scipy (~2.5 s of
# import), which must not tax experiments that never use it.
CONTROLLER_REGISTRY = {
    "randomsearch": RandomSearch,
    "gridsearch": GridSearch,
    "asha": Asha,
    "pbt": PBT,
    "tpe": _lazy_tpe,
    "gp": _lazy_gp,
    "none": SingleRun,
}

ES_REGISTRY = {"median": MedianStoppingRule, "none": NoStoppingRule}

#: Fork-step cache sentinel: "never looked" is distinct from "looked and
#: the parent has no checkpoint" (a legitimately cached None).
_UNRESOLVED = object()


class OptimizationDriver(Driver):
    controller_dict = CONTROLLER_REGISTRY

    def __init__(self, config: OptimizationConfig, app_id: str, run_id: int):
        self.controller = self._init_controller(config)
        # Pruner must exist BEFORE sizing the schedule: it owns num_trials
        # when multi-fidelity (reference `optimization_driver.py:63-65`).
        self.controller.init_pruner()
        if getattr(config, "resume", False):
            # Validate BEFORE super().__init__ re-registers the experiment
            # dir — a late failure would have already clobbered the
            # interrupted run's experiment.json.
            self._validate_resume()
        self.num_trials = self._resolve_num_trials(config)
        # Controllers whose schedule bounds concurrency below the trial
        # count (PBT: members are sequential chains, so at most
        # `population` trials can ever be in flight) cap the worker pool —
        # excess runners would hold hardware and idle-tick all experiment.
        max_conc = getattr(self.controller, "max_concurrency", None)
        ceiling = min(self.num_trials,
                      max_conc() if max_conc is not None else self.num_trials)
        # Gang-scheduled trials need N runners for ONE trial, so the
        # trial-count clamp must not shrink the pool below the largest
        # declared gang.
        max_gang = gang_mod.config_max_gang_chips(config)
        if max_gang > 1 and getattr(config, "pool", "thread") != "elastic":
            ceiling = max(ceiling, max_gang)
        self.num_executors = min(resolve_num_workers(config), ceiling)
        if max_gang > 1 and getattr(config, "pool", "thread") != "elastic" \
                and self.num_executors < max_gang:
            raise ValueError(
                "a declared gang needs {} chips but only {} runner(s) are "
                "configured (num_workers); a gang can never "
                "assemble".format(max_gang, self.num_executors))
        super().__init__(config, app_id, run_id)

        # Trial bookkeeping shared with the server thread.
        self._trial_store: Dict[str, Trial] = {}  # guarded-by: _store_lock
        self._final_store: List[Trial] = []  # guarded-by: _store_lock
        self._store_lock = threading.RLock()
        # Trials orphaned by a lost runner, waiting for reassignment. Served
        # by _assign_next ahead of fresh controller suggestions. Guarded by
        # the STORE lock, not _sched_lock: the LOST/BLACK callbacks and the
        # server event loop (periodic_check) touch the backlog without ever
        # taking the schedule lock.
        self._requeue: List[str] = []  # guarded-by: _store_lock
        # Trials parked for a runner of the RIGHT chip capacity (elastic
        # pools): the schedule already committed to them, but the runner
        # that triggered the suggestion is pinned to a different size.
        self._parked: List[str] = []  # guarded-by: _store_lock
        # Elastic respawn sizing reads chips_per_budget ONLY on the
        # elastic pool; on thread/fleet pools the same declaration means
        # gang scheduling (see below) and the elastic machinery stays off.
        pool_kind = getattr(config, "pool", "thread")
        self._chips_map = getattr(config, "chips_per_budget", None) \
            if pool_kind == "elastic" else None

        # ---- gang scheduling (multi-chip trials; maggy_tpu.gang) ----
        # A trial declaring N>1 chips (GangSpec per budget, or a
        # Searchspace GANG entry) is not assigned to one runner: the
        # driver reserves a contiguous chip block through the placer,
        # conscripts runners whose chips fall inside it as they free up
        # (gang holds in the reservation table), and dispatches the
        # trial to the lowest-chip member as LEADER once the block is
        # fully held. The members keep heartbeating/idle-polling —
        # their chips belong to the leader's mesh until the gang
        # releases (FINAL/error/preemption/member loss).
        self._gang_map = getattr(config, "chips_per_budget", None) \
            if pool_kind != "elastic" else None
        self._gang_mode = gang_mod.config_declares_gangs(config) \
            and pool_kind != "elastic"
        # The GANG-typed searchspace entry, found by TYPE — a user may
        # name it anything ("topology", "sharding", ...); a by-name
        # lookup would silently run every trial unsharded on one chip.
        sp = getattr(config, "searchspace", None)
        self._gang_param = next(
            (n for n in sp.names() if sp.get_type(n) == "GANG"),
            None) if sp is not None else None
        binding = getattr(config, "fleet", None)
        # Fleet mode: the placer spans thread runners PLUS agent slots —
        # a remote gang assembles across agent-held fleet runners too.
        placer_chips = (binding.fleet.num_runners
                        + getattr(binding.fleet, "max_agents", 0)) \
            if binding is not None else self.num_executors
        if self._gang_mode and max_gang > placer_chips:
            # The num_executors guard above covers thread pools; in
            # fleet mode the placer spans the FLEET's runners — an
            # oversized gang would wait in _gang_demand forever.
            raise ValueError(
                "a declared gang needs {} chips but the {} spans only "
                "{} runner(s); the gang can never assemble".format(
                    max_gang,
                    "fleet" if binding is not None else "runner pool",
                    placer_chips))
        self._placer = gang_mod.GangPlacer(
            placer_chips, telemetry=self.telemetry) \
            if self._gang_mode else None
        # Trials waiting for a gang (FIFO; requeued gang trials wait in
        # _requeue instead and take priority).
        self._gang_wait: List[str] = []  # guarded-by: _store_lock
        # ---- checkpoint-forking search (config.fork) ----
        # A suggestion whose info carries a parent (ASHA promotion, PBT
        # exploit/continue segment, BO near-duplicate) is stamped with
        # forked_from + resume_step at commit time, so the promoted
        # trial RESUMES the parent's checkpoint instead of re-training
        # its prefix (ROADMAP item 3 — the rung-ratio compute win).
        self._fork_enabled = bool(getattr(config, "fork", True))
        # Fork affinity: (deadline, preferred partition, trial_id) holds
        # for forked trials parked briefly for the runner that holds the
        # parent's warm slot + local checkpoint (extends the PR-14
        # prewarm lease hints from family-affinity to parent-affinity).
        self._fork_hold: List[tuple] = []  # guarded-by: _store_lock
        # Trials that already had their one affinity hold (a second hold
        # after expiry would starve the trial forever).
        self._fork_held: set = set()  # guarded-by: _store_lock
        # Parents whose checkpoint dir was garbage-collected (journaled
        # ckpt_gc): retirement is once-only and never repeats on disk.
        self._ckpt_gced: set = set()  # guarded-by: _store_lock
        # Resolved fork points: parent trial id -> latest ack'd
        # checkpoint step (or None). A finalized parent's checkpoints
        # never move, so the env round trip (isdir+ls — two object-store
        # hops on GCS) is paid once per parent, not once per child, and
        # repeat exploits of a popular PBT donor stamp lock-free.
        self._fork_step_cache: Dict[str, Optional[int]] = {}  # guarded-by: _store_lock
        # Assembled gangs: trial_id -> {chips, members, leader, mesh,
        # strategy, revoking}.
        self._gangs: Dict[str, Dict[str, Any]] = {}  # guarded-by: _store_lock
        # Fleet-level contiguous-block reservation held while gangs are
        # waiting or running (see FleetScheduler.request_gang).
        self._fleet_gang_active = False  # guarded-by: _store_lock
        # ---- vectorized micro-trials (config.vmap_lanes; train/vmap.py) ----
        # K>1: the dispatch path assembles up to K program-compatible
        # suggestions into ONE block delivered to a single runner, which
        # trains them in lockstep as one vmapped executable. K=1 keeps
        # every code path below bit-for-bit scalar.
        self._vmap_lanes = int(getattr(config, "vmap_lanes", 1) or 1)
        # Assembled blocks in flight: leader trial id ->
        # {"lanes": [trial_id, ...] (lane order), "partition": pid}.
        self._vmap_blocks: Dict[str, Dict[str, Any]] = {}  # guarded-by: _store_lock
        # Reverse map: lane trial id -> leader trial id.
        self._lane_leader: Dict[str, str] = {}  # guarded-by: _store_lock
        # Outstanding resize requests by target size: bounds the idle-runner
        # migration so a herd of idle runners doesn't all chase one parked
        # trial's size (decremented when a runner REGisters at that size).
        self._resize_inflight: Dict[int, int] = {}  # guarded-by: _store_lock
        # partition_id -> (monotonic request time, target chips): liveness
        # watch on resize respawns (see periodic_check).
        self._resize_watch: Dict[int, tuple] = {}  # guarded-by: _store_lock
        # Arm heartbeat-loss detection (SURVEY.md §5.3): a silent runner's
        # trial is requeued to whichever runner asks for work next. The
        # loss shape (floor + interval multiple) is per-experiment config
        # so soak/chaos tests can tighten detection without monkeypatching
        # the module-global defaults.
        self.server.hb_loss_timeout = config.resolved_hb_loss_timeout()
        self.earlystop_check = self._init_earlystop(config)
        self.es_interval = config.es_interval
        self.es_min = config.es_min
        self.direction = config.direction
        self.optimization_key = config.optimization_key

        # Wire the controller (reference `optimization_driver.py:87-93`).
        self.controller.searchspace = config.searchspace
        self.controller.num_trials = self.num_trials
        self.controller.trial_store = self._trial_store
        self.controller.final_store = self._final_store
        self.controller.direction = config.direction
        # Lanes-aware optimizers (ASHA's K-at-a-time rung drain, BO's
        # fork-lane discount) read this; everyone else ignores it.
        self.controller.vmap_lanes = self._vmap_lanes
        self.controller._initialize(exp_dir=self.exp_dir)

        self.result = {"best_id": None, "best_val": None, "best_hp": None,
                       "worst_id": None, "worst_val": None, "worst_hp": None,
                       "avg": None, "num_trials": 0, "early_stopped": 0}
        self.job_start: Optional[float] = None
        self.maggy_log = ""

        # ---- pipelined trial hand-off (config.prefetch) ----
        # The schedule lock serializes everything the single driver-worker
        # thread used to serialize implicitly, now that three threads can
        # touch the schedule: the worker (REG/IDLE/BLACK/LOST callbacks +
        # FINAL fallbacks), the RPC dispatch thread (the FINAL fast path),
        # and the suggester thread (prefetch refills). Ordering: sched ->
        # store lock, never the reverse.
        self._sched_lock = threading.RLock()
        self._prefetch_enabled = bool(getattr(config, "prefetch", True)) \
            and getattr(self.controller, "supports_prefetch",
                        lambda: False)()
        # The FINAL fast path persists trial.json before the hand-off, on
        # the RPC event loop — only tolerable when the env's writes are
        # local fs ops. Remote envs (GCS) keep FINAL processing on the
        # worker thread; the prefetch queue still feeds it, so only the
        # piggybacked reply (one GET round trip) is given up.
        self._inline_final_enabled = self._prefetch_enabled and \
            getattr(self.env, "FAST_LOCAL_WRITES", False)
        # Pre-materialized suggestions (oldest first), each stamped with
        # the controller's schedule_version at suggest time; a FINAL that
        # bumps the version invalidates the stale entries before dispatch.
        # Both guarded by _sched_lock.
        self._prefetched: List[Trial] = []  # guarded-by: _sched_lock
        self._prefetch_versions: Dict[str, int] = {}  # guarded-by: _sched_lock
        self._suggest_wake = threading.Event()
        # >0 while the FINAL fast path is executing on the RPC dispatch
        # thread (mutated under _sched_lock): an expensive suggest() must
        # fall back to the suggester instead of fitting on the event loop.
        self._inline_depth = 0  # guarded-by: _sched_lock
        self._suggester_thread: Optional[threading.Thread] = None

        if getattr(config, "resume", False):
            self._restore_previous_run()
        if self._prefetch_enabled:
            # Started AFTER resume restore: the suggester must never
            # sample from a controller whose state is still rebuilding.
            self._suggester_thread = threading.Thread(
                target=self._suggester_loop, daemon=True, name="suggester")
            self._suggester_thread.start()

    # --------------------------------------------------------------- set up

    @staticmethod
    def _init_controller(config) -> AbstractOptimizer:
        opt = config.optimizer
        if isinstance(opt, str):
            key = opt.lower()
            if key not in CONTROLLER_REGISTRY:
                raise ValueError(
                    "Unknown optimizer '{}'; choose from {} or pass an "
                    "AbstractOptimizer instance.".format(opt, sorted(CONTROLLER_REGISTRY))
                )
            return CONTROLLER_REGISTRY[key](seed=config.seed) if key != "none" \
                else SingleRun(seed=config.seed)
        if opt is None:
            return SingleRun(seed=config.seed)
        if not isinstance(opt, AbstractOptimizer):
            raise TypeError(
                "optimizer must be a registry name or AbstractOptimizer, got {}".format(type(opt))
            )
        return opt

    def _resolve_num_trials(self, config) -> int:
        # Pruner owns the schedule; gridsearch computes from the space
        # (reference `optimization_driver.py:63-69`); controllers with a
        # fixed combinatorial schedule (PBT: population x generations)
        # expose it via schedule_size().
        if self.controller.pruner is not None:
            return self.controller.pruner.num_trials()
        if isinstance(self.controller, GridSearch):
            return GridSearch.get_num_trials(config.searchspace)
        size = getattr(self.controller, "schedule_size", None)
        if size is not None:
            return size()
        return config.num_trials

    @staticmethod
    def _init_earlystop(config):
        pol = config.es_policy
        if isinstance(pol, str):
            if pol.lower() not in ES_REGISTRY:
                raise ValueError("Unknown es_policy '{}'".format(pol))
            return ES_REGISTRY[pol.lower()]
        return pol

    def _make_server(self):
        # Barrier sized to the CLAMPED worker count, and keyed by the
        # driver's per-experiment secret.
        return OptimizationServer(self.num_executors, secret=self.secret)

    def _make_runner_pool(self):
        pool = getattr(self.config, "pool", "thread")
        if pool == "thread":
            return ThreadRunnerPool(self.num_executors)
        from maggy_tpu.core.runner_pool import ProcessRunnerPool, TPURunnerPool

        if pool == "process":
            return ProcessRunnerPool(self.num_executors)
        if pool == "tpu":
            return TPURunnerPool(self.num_executors,
                                 chips_per_trial=self.config.chips_per_trial)
        if pool == "elastic":
            from maggy_tpu.core.runner_pool import (ElasticTPURunnerPool,
                                                    _probe_local_devices)

            total = getattr(self.config, "total_chips", None)
            if total is None:
                total = _probe_local_devices()[0]
            if self._chips_map:
                worst = max(self._chips_map.values())
                if worst > total:
                    raise ValueError(
                        "chips_per_budget asks for {} chips but only {} "
                        "are available to lease".format(worst, total))
            return ElasticTPURunnerPool(
                self.num_executors, total_chips=total,
                chips_per_trial=self.config.chips_per_trial,
                should_stop=lambda: self.experiment_done)
        if pool == "remote":
            from maggy_tpu.core.runner_pool import RemoteRunnerPool

            # Open JOIN admission: agents that dial in get a partition id
            # and this executor config.
            self.server.join_info = {
                "hb_interval": self.hb_interval,
                "exp_dir": self.exp_dir,
                "optimization_key": self.optimization_key,
                "trial_type": "optimization",
                "warm_start": getattr(self.config, "warm_start", True),
            }
            return RemoteRunnerPool(self)
        raise ValueError("Unknown pool type {!r}".format(pool))

    def _executor_fn(self, train_fn):
        return trial_executor_fn(
            server_addr=self.server_addr,
            secret=self.secret_for_clients(),
            hb_interval=self.hb_interval,
            exp_dir=self.exp_dir,
            optimization_key=self.optimization_key,
            train_fn=train_fn,
            trial_type="optimization",
            profile=getattr(self.config, "profile", False),
            ship_prints=getattr(self.config, "ship_prints", False),
            warm_start=getattr(self.config, "warm_start", True),
        )

    def _validate_resume(self) -> None:
        from maggy_tpu.optimizers.bayes.base import BaseAsyncBO

        if isinstance(self.controller, (RandomSearch, BaseAsyncBO)) \
                and self.controller.seed is None:
            raise ValueError(
                "resume=True with {} requires a fixed seed: an unseeded "
                "rerun presamples a different schedule and would re-run "
                "everything on top of the restored trials.".format(
                    type(self.controller).__name__))

    def _restore_previous_run(self) -> None:
        """Experiment resume (beyond the reference, SURVEY.md §5.4): reload
        every finalized trial.json from the experiment dir, rebuild result
        aggregates, and let the controller drop already-executed configs.
        The interrupted run's unfinished trials simply re-run."""
        swept = self.env.sweep_tmp_files(self.exp_dir)
        if swept:
            self._log("resume: swept {} orphaned tmp file(s)".format(swept))
        restored: List[Trial] = []
        for name in sorted(self.env.ls(self.exp_dir)):
            path = "{}/{}/trial.json".format(self.exp_dir, name)
            if not self.env.exists(path):
                continue
            try:
                trial = Trial.from_json(self.env.load(path))
            except (ValueError, KeyError):
                # Torn artifact from a hard kill mid-write (pre-atomic-dump
                # experiments): the trial was in flight, so treating it as
                # unfinished and re-running it is exactly resume semantics.
                self._log("resume: skipping unreadable {} (trial will "
                          "re-run)".format(path))
                continue
            if trial.status == Trial.FINALIZED and trial.final_metric is not None:
                restored.append(trial)
        with self._store_lock:
            self._final_store.extend(restored)
        for trial in restored:
            self._update_result(trial)
        # Carry the interrupted run's early-stop count so the resumed
        # result.json covers all the trials it claims to.
        self.result["early_stopped"] += sum(1 for t in restored if t.early_stop)
        # Crash-only recovery (core/driver/recovery.py): rebuild the
        # IN-FLIGHT half from the journal — committed-but-unfinalized
        # trials re-enter the store with their pre-crash run epochs and
        # holding partitions, the reservation table is re-seeded so
        # still-live runners re-bind (adopted) and dead ones requeue via
        # the ordinary slot-reclaim liveness. Runs BEFORE the controller
        # restore so buffer-backed samplers can drop the in-flight
        # configs too (they are already minted — re-suggesting them
        # would collide in the store).
        from maggy_tpu.core.driver import recovery as recovery_mod

        recovered_stats = recovery_mod.recover_optimization_driver(self)
        with self._store_lock:
            inflight = list(self._trial_store.values())
        self.controller.restore_from_finals(restored, inflight=inflight)
        if self.controller.pruner is not None:
            path = self.exp_dir + "/" + constants.PRUNER_STATE_FILE
            if not self.env.exists(path):
                if restored:
                    raise ValueError(
                        "resume=True with a pruner needs the bracket-state "
                        "checkpoint {}; this experiment predates pruner "
                        "checkpointing.".format(path))
            else:
                self.controller.pruner.load_state_dict(
                    json.loads(self.env.load(path)))
                self.controller.pruner.restore(
                    {t.trial_id for t in restored})
        if recovered_stats is not None:
            self.telemetry.event("experiment", phase="recovered",
                                 finalized=len(restored),
                                 **recovered_stats)
        self._log("resume: restored {} finalized trials from {}{}".format(
            len(restored), self.exp_dir,
            "; recovered {} in-flight trial(s) across {} partition(s) "
            "from the journal".format(
                recovered_stats["inflight"],
                recovered_stats["recovered_partitions"])
            if recovered_stats is not None else ""))

    # ------------------------------------------------------------ callbacks

    def _register_msg_callbacks(self) -> None:
        self.message_callbacks.update(
            METRIC=self._metric_msg_callback,
            BLACK=self._blacklist_msg_callback,
            FINAL=self._final_msg_callback,
            IDLE=self._idle_msg_callback,
            REG=self._register_msg_callback,
            LOST=self._lost_msg_callback,
            GANG_LOST=self._gang_lost_msg_callback,
        )

    def get_trial(self, trial_id):
        with self._store_lock:
            return self._trial_store.get(trial_id)

    def _metric_msg_callback(self, msg) -> None:
        """Append heartbeat metric; early-stop check every es_interval steps
        once es_min trials finalized (reference :331-361). A vectorized
        block's beat carries ``lanes`` — K lane-tagged (trial_id, value,
        step) entries, each applied as its own trial's metric so the
        early-stop rule sees K independent streams."""
        self.add_executor_logs(msg.get("logs"))
        lanes = msg.get("lanes")
        if lanes:
            for beat in lanes:
                self._apply_metric_beat(beat.get("trial_id"),
                                        beat.get("value"), beat.get("step"),
                                        msg.get("partition_id"),
                                        lane=beat.get("lane"))
            return
        self._apply_metric_beat(msg.get("trial_id"), msg.get("value"),
                                msg.get("step"), msg.get("partition_id"))

    def _apply_metric_beat(self, trial_id, value, step, partition_id,
                           lane=None) -> None:
        trial = self.get_trial(trial_id)
        if trial is None or value is None:
            return
        appended = trial.append_metric(value, step)
        if not appended:
            return
        with trial.lock:
            n_steps = len(trial.step_history)
        if n_steps == 1:
            # Scheduling pipeline milestone: time-to-first-signal. The
            # span's running->first_metric delta is the trial's
            # startup/compile cost as the control plane sees it. The lane
            # tag rides only on vectorized beats — scalar journals stay
            # bit-identical to the K=1 path.
            extra = {"lane": lane} if lane is not None else {}
            self.telemetry.trial_event(trial.trial_id, "first_metric",
                                       partition=partition_id, **extra)
        with self._store_lock:
            n_final = len(self._final_store)
        if n_final >= self.es_min and n_steps % self.es_interval == 0:
            with self._store_lock:
                final_snapshot = list(self._final_store)
            stopped = self.earlystop_check.earlystop_check(
                {trial.trial_id: trial}, final_snapshot, self.direction
            )
            for t in stopped:
                # The rule can re-return an already-flagged trial (its
                # heartbeats keep appending metrics until the STOP reply
                # lands) — counting it again inflated early_stopped vs the
                # distinct-trial truth the telemetry journal exposes.
                if t.get_early_stop():
                    continue
                t.set_early_stop()
                self.result["early_stopped"] += 1
                # Opening edge of the early-stop reaction latency: the
                # closing edge is this trial's "finalized".
                self.telemetry.trial_event(t.trial_id, "stop_flagged")

    def _blacklist_msg_callback(self, msg) -> None:
        """Executor died and re-registered: requeue its trial (reference
        :363-367 + `rpc.py:308-326`)."""
        # The pid now names a REPLACEMENT process: the dead one's gauges
        # and merged stats are stale (the new runner re-ships its own).
        self.telemetry.prune_partition(msg.get("partition_id"))
        trial = self.get_trial(msg["trial_id"])
        if trial is not None and self.gang_members(trial.trial_id):
            # A re-registered gang leader cannot simply take its trial
            # back — its mesh slice is gone. Revoke the gang and let the
            # backlog reassemble one.
            self._release_gang(trial.trial_id, why="leader_blacklisted",
                               partition=msg.get("partition_id"))
            trial.reset_run_state()
            with self._store_lock:
                if trial.trial_id not in self._requeue:
                    self._requeue.append(trial.trial_id)
            self.telemetry.trial_event(trial.trial_id, "requeued",
                                       partition=msg["partition_id"],
                                       reason="blacklist")
            return
        if trial is not None:
            # A blacklisted block leader: the non-leader lanes requeue as
            # individual trials; the leader (vmap stamps stripped by the
            # helper) is reassigned below as a plain scalar trial.
            self._requeue_vmap_block(trial.trial_id, msg["partition_id"],
                                     "vmap_block_lost")
            trial.reset_run_state()
            # Explicit requeue edge BEFORE the reassignment: recovery
            # latency (fault -> requeued -> assigned) must be derivable
            # from the journal (the chaos harness asserts on it).
            self.telemetry.trial_event(trial.trial_id, "requeued",
                                       partition=msg["partition_id"],
                                       reason="blacklist")
            # A re-registered slot re-running a FORKED (or preempted)
            # trial resumes like the backlog path would: verify the fork
            # source survived, journal the resume edge with its step.
            self._verify_fork_source(trial, msg["partition_id"])
            self.server.reservations.assign_trial(msg["partition_id"], trial.trial_id)
            self.telemetry.trial_event(trial.trial_id, "assigned",
                                       partition=msg["partition_id"],
                                       requeue="blacklist")
            self._journal_fork_edge(trial, msg["partition_id"])
            with trial.lock:
                resume_step = trial.info_dict.get("resume_step")
            if resume_step is not None:
                self.telemetry.trial_event(trial.trial_id, "resumed",
                                           partition=msg["partition_id"],
                                           from_step=int(resume_step))
            self._log("executor {} restarted; trial {} requeued".format(
                msg["partition_id"], msg["trial_id"]))

    def _requeue_vmap_block(self, leader_id: str, partition_id,
                            reason: str) -> bool:
        """Tear down a dead vectorized block: every live NON-leader lane
        requeues exactly once as an individual scalar trial (the leader
        rides the caller's existing single-trial requeue path, so the
        whole block — leader included — requeues exactly once: chaos
        invariant 16). Lanes that already finalized stay finalized (no
        phantom re-runs); vmap stamps are stripped so the re-dispatch is
        plain scalar. Returns False when ``leader_id`` leads no block."""
        with self._store_lock:
            block = self._vmap_blocks.pop(leader_id, None)
            if block is None:
                return False
            for tid in block["lanes"]:
                self._lane_leader.pop(tid, None)
        for tid in block["lanes"]:
            trial = self.get_trial(tid)
            if trial is None:
                continue
            with trial.lock:
                trial.info_dict.pop("vmap", None)
                trial.info_dict.pop("vmap_block", None)
                done = trial.final_metric is not None or \
                    trial.status == Trial.ERROR
            if done or tid == leader_id:
                continue
            trial.reset_run_state()
            with self._store_lock:
                if tid not in self._requeue:
                    self._requeue.append(tid)
            # Literal reasons so the journalvocab emit scan sees them.
            if reason == "preempted":
                self.telemetry.trial_event(tid, "requeued",
                                           partition=partition_id,
                                           reason="preempted")
            else:
                self.telemetry.trial_event(tid, "requeued",
                                           partition=partition_id,
                                           reason="vmap_block_lost")
        return True

    def vmap_block_lanes(self, leader_id: str) -> List[str]:
        """Lane trial ids of an in-flight block (empty when ``leader_id``
        leads none) — chaos/bench introspection."""
        with self._store_lock:
            block = self._vmap_blocks.get(leader_id)
            return list(block["lanes"]) if block else []

    def _lost_msg_callback(self, msg) -> None:
        """A runner's heartbeats went silent while holding a trial: the
        runner is presumed dead and the trial goes back into the schedule
        for whichever runner asks for work next (elastic recovery beyond
        the reference's same-executor blacklist, SURVEY.md §5.3)."""
        trial = self.get_trial(msg["trial_id"])
        if trial is None:
            return
        # A lost block leader takes all K lanes with it — the non-leader
        # lanes requeue here; the leader requeues below like any scalar.
        self._requeue_vmap_block(trial.trial_id, msg.get("partition_id"),
                                 "vmap_block_lost")
        trial.reset_run_state()
        with self._store_lock:
            if trial.trial_id not in self._requeue:
                self._requeue.append(trial.trial_id)
        # A lost gang LEADER takes its whole gang down: the members'
        # chips go back to the pool and the requeued trial re-assembles
        # a fresh gang (the placer avoids the dead chip).
        self._release_gang(trial.trial_id, why="leader_lost",
                           partition=msg.get("partition_id"))
        self.telemetry.trial_event(trial.trial_id, "lost",
                                   partition=msg.get("partition_id"))
        # The explicit re-queue edge: without it the journal only shows a
        # later "assigned" whose span timestamp is NOT overwritten (spans
        # keep first occurrences), leaving recovery latency underivable.
        self.telemetry.trial_event(trial.trial_id, "requeued",
                                   partition=msg.get("partition_id"),
                                   reason="heartbeat_loss")
        self.result["lost_runners"] = self.result.get("lost_runners", 0) + 1
        self._log("runner {} heartbeat lost; trial {} requeued for reassignment".format(
            msg["partition_id"], msg["trial_id"]))
        # Reap the hung worker so it cannot block the pool's final join: a
        # runner wedged inside a native call (compile stall, stuck device
        # op) never returns on its own. Process pools kill just that one
        # worker; the experiment completes on the survivors and the killed
        # runner surfaces as a survivable pool failure. Exception: a
        # chaos-faked preemption — the runner is HEALTHY by construction
        # and must stay alive to deliver the duplicate FINAL the fault
        # exists to provoke.
        if self.chaos is not None and \
                self.chaos.suppress_reap(msg.get("partition_id")):
            self._log("runner {} loss was a chaos-faked preemption; "
                      "reap suppressed".format(msg["partition_id"]))
            return
        pool = getattr(self, "_active_pool", None)
        if pool is not None and pool.kill_worker(msg["partition_id"]):
            self._log("runner {} killed after heartbeat loss (presumed "
                      "wedged)".format(msg["partition_id"]))
        # The dead runner's live gauges/stats must not outlive it: a
        # reaped partition's last RSS/cadence would sit in the registry
        # (and the /metrics exposition, and the health-engine medians)
        # forever. A respawned runner repopulates on its first beat.
        self.telemetry.prune_partition(msg.get("partition_id"))

    def _chips_for(self, trial: Trial) -> Optional[int]:
        """Chip requirement of a trial under chips_per_budget (None when
        elastic sizing is off)."""
        if self._chips_map is None:
            return None
        budget = trial.params.get("budget", trial.info_dict.get("budget"))
        return int(self._chips_map.get(
            budget, getattr(self.config, "chips_per_trial", 1)))

    def _maybe_migrate(self, partition_id: int, cap: int) -> bool:
        """Resize or retire an idle elastic runner when waiting work needs
        sizes its capacity cannot serve. Returns True if the runner was
        told to leave (caller must not re-arm its idle chain)."""
        with self._store_lock:
            waiting = [self._chips_for(self._trial_store[tid])
                       for tid in self._parked + self._requeue
                       if tid in self._trial_store]
            demand: Dict[int, int] = {}
            for n in waiting:
                if n is not None:
                    demand[n] = demand.get(n, 0) + 1
        if not demand or cap in demand:
            # Nothing waiting, or this runner's size IS in demand (a
            # matching trial will reach it via _pop_parked/_pop_requeue).
            return False
        live = self.server.reservations.capacities()
        with self._store_lock:
            for size in sorted(demand, reverse=True):
                supply = live.get(size, 0) + self._resize_inflight.get(size, 0)
                if demand[size] > supply:
                    self._resize_inflight[size] = \
                        self._resize_inflight.get(size, 0) + 1
                    self._resize_watch[partition_id] = (
                        time.monotonic(), size, self._pool_spawn_stamp(
                            partition_id))
                    self.server.reservations.request_resize(partition_id, size)
                    self._log("idle runner {} (capacity {}) resized toward "
                              "waiting work ({} chips)".format(
                                  partition_id, cap, size))
                    return True
        # Demand covered: this runner's size serves nothing that remains —
        # retire it so its chips free up for the pending spawns. Never
        # retire the LAST live runner UNLESS a resize respawn is already in
        # flight: that respawn re-registers and polls, so the pool is not
        # left pollerless — and NOT retiring would deadlock it (the pending
        # bigger spawn waits on exactly the chips this idle runner holds;
        # observed as TestElasticChipLeasing hanging at the 2+2 -> 4
        # consolidation when the resizing runner was already released).
        with self._store_lock:
            inflight = sum(self._resize_inflight.values())
        if sum(live.values()) <= 1 and inflight == 0:
            return False
        self.server.reservations.request_resize(partition_id, 0)
        self._log("idle runner {} (capacity {}) retired; chips released "
                  "for pending resizes".format(partition_id, cap))
        return True

    def _pool_spawn_stamp(self, partition_id: int):
        pool = getattr(self, "_active_pool", None)
        stamp_of = getattr(pool, "spawn_stamp", None)
        return stamp_of(partition_id) if stamp_of is not None else None

    def periodic_check(self) -> None:
        """Server event-loop hook: bound resize-respawn registration.

        A respawn that wedges BEFORE registering (stale device claim at
        backend init) never heartbeats, so heartbeat-loss detection cannot
        see it — and with the last-runner retire rule the pool may have
        nobody else polling. Expired respawns are killed via the pool,
        which turns a silent infinite wait into a loud runner failure the
        driver surfaces. An expired entry whose process was still QUEUED
        for chips (kill_worker finds nothing) merely loses its in-flight
        credit — worst case another idle runner re-chases the demand."""
        pool = getattr(self, "_active_pool", None)
        stamp_of = getattr(pool, "spawn_stamp", None)
        now = time.monotonic()
        expired = []
        with self._store_lock:
            for pid, (t0, size, s0) in list(self._resize_watch.items()):
                if now - t0 <= constants.RESIZE_RESPAWN_TIMEOUT_S:
                    continue
                if stamp_of is None:
                    # No pool visibility: fall back to the request clock.
                    del self._resize_watch[pid]
                    if self._resize_inflight.get(size, 0) > 0:
                        self._resize_inflight[size] -= 1
                    expired.append((pid, size, "timed out (no pool "
                                               "visibility); killing it"))
                    continue
                stamp = stamp_of(pid)
                # Three healthy states re-arm the watch (expiring any of
                # them would drop an in-flight credit a later REGISTER
                # then double-decrements):
                # - stamp is None AND the pool still holds a pending
                #   respawn: QUEUED for chips — e.g. waiting behind
                #   another runner's minutes-long trial. stamp None
                #   WITHOUT a pending respawn means the process died (or
                #   crashed at spawn) before registering — nothing will
                #   ever register, so re-arming would leak the in-flight
                #   credit forever (and the stale credit would keep
                #   satisfying the last-runner-retire exemption);
                # - stamp == s0: the PRE-resize process is still winding
                #   down (it must not be killed for being old — its age
                #   predates the request by construction);
                # - a NEW process (stamp != s0) younger than the bound.
                # Only a post-request process older than the bound is a
                # wedged respawn.
                if stamp is None:
                    pending_of = getattr(pool, "pending_respawn", None)
                    if pending_of is None or pending_of(pid):
                        self._resize_watch[pid] = (now, size, s0)
                        continue
                    del self._resize_watch[pid]
                    if self._resize_inflight.get(size, 0) > 0:
                        self._resize_inflight[size] -= 1
                    expired.append((pid, size, "died before registering"))
                    continue
                if stamp == s0 or \
                        now - stamp <= constants.RESIZE_RESPAWN_TIMEOUT_S:
                    self._resize_watch[pid] = (now, size, s0)
                    continue
                del self._resize_watch[pid]
                if self._resize_inflight.get(size, 0) > 0:
                    self._resize_inflight[size] -= 1
                expired.append((pid, size, "spawned but did not re-register "
                                           "within {:.0f}s; killing it".format(
                                               constants.RESIZE_RESPAWN_TIMEOUT_S)))
        for pid, size, why in expired:
            self._log("resize respawn for runner {} ({} chips) {}".format(
                pid, size, why))
            if pool is not None:
                pool.kill_worker(pid)
        self._check_gang_members()

    def _pop_parked(self, capacity: Optional[int]) -> Optional[Trial]:
        """First parked trial this runner's capacity can serve (None
        capacity = non-elastic runner, matches anything)."""
        with self._store_lock:
            for i, tid in enumerate(self._parked):
                trial = self._trial_store.get(tid)
                if trial is None:
                    continue
                need = self._chips_for(trial)
                if capacity is None or need is None or need == capacity:
                    del self._parked[i]
                    return trial
        return None

    def _pop_requeue(self, capacity: Optional[int] = None) -> Optional[Trial]:
        """Next orphaned trial this runner can serve. Elastic pools match
        chip requirements here too — a budget-9 trial orphaned by a dead
        2-chip runner must NOT land on a 1-chip runner. Gang trials
        (N>1 chips) are skipped-but-RETAINED: a single undersized runner
        must never be served a trial whose mesh needs N chips — the
        backlog entry waits for gang assembly (_service_gangs) and is
        served intact to the whole gang, never split."""
        with self._store_lock:
            for i, tid in enumerate(list(self._requeue)):
                trial = self._trial_store.get(tid)
                if trial is None:
                    self._requeue.remove(tid)
                    continue
                spec = self._gang_spec_for(trial)
                if spec is not None and spec.chips > 1:
                    continue
                need = self._chips_for(trial)
                if capacity is None or need is None or need == capacity:
                    self._requeue.remove(tid)
                    return trial
        return None

    # ------------------------------------------------- gang scheduling

    def _gang_spec_for(self, trial: Trial) -> Optional["gang_mod.GangSpec"]:
        """The trial's declared gang shape (None = plain 1-runner
        trial): a sampled Searchspace GANG param wins, else the
        chips_per_budget entry for its budget."""
        if not self._gang_mode:
            return None
        g = trial.params.get(self._gang_param) \
            if self._gang_param is not None else None
        if g:
            return gang_mod.GangSpec.from_value(g)
        if self._gang_map:
            budget = trial.params.get("budget",
                                      trial.info_dict.get("budget"))
            v = self._gang_map.get(budget)
            if v is not None:
                return gang_mod.GangSpec.from_value(v)
        return None

    def _chip_of(self, partition_id: int) -> int:
        """The runner's chip/topology index. Thread pools: runner ≈
        chip, identity. Fleet mode: the fleet runner index this
        partition is currently leased to (FleetLeasedPool.chip_of), so
        contiguity means contiguous FLEET runners."""
        pool = getattr(self, "_active_pool", None)
        chip_of = getattr(pool, "chip_of", None)
        if chip_of is not None:
            chip = chip_of(partition_id)
            if chip is not None:
                return int(chip)
        return int(partition_id)

    # locked-by: _store_lock
    def _gang_demand_locked(self) -> List[str]:
        """Gang trials awaiting assembly, requeued (revoked/lost) ones
        first — store lock held."""
        demand = []
        for tid in self._requeue + self._gang_wait:
            if tid in demand or tid not in self._trial_store:
                continue
            trial = self._trial_store[tid]
            spec = self._gang_spec_for(trial)
            if spec is not None and spec.chips > 1 \
                    and tid not in self._gangs:
                demand.append(tid)
        return demand

    def _service_gangs_locked(self, partition_id: int) -> bool:
        """Reserve blocks for waiting gang trials, conscript this (and
        every other currently-free) runner whose chip falls inside one,
        and assemble any gang whose block became fully held. Returns
        True when the asking runner was conscripted — the caller must
        hand it no other work. Sched lock held."""
        if not self._gang_mode:
            return False
        res = self.server.reservations
        with self._store_lock:
            demand = self._gang_demand_locked()
            running = bool(self._gangs)
        self._sync_fleet_gang(bool(demand) or running)
        if not demand:
            return False
        bound = self.server.hb_loss_timeout
        free = [p for p in res.free_pids()
                if bound is None or not res.is_silent(p, bound)]
        chip_by_pid = {p: self._chip_of(p) for p in free}
        free_chips = set(chip_by_pid.values())
        # Chips whose runners can never come back (silent past the loss
        # bound, or released): a reserved block containing one would
        # park its gang forever.
        dead_chips = set()
        for pid, rec in res.all().items():
            if rec.get("released") or (
                    bound is not None and res.is_silent(pid, bound)):
                dead_chips.add(self._chip_of(pid))
        conscripted = False
        for tid in demand:
            trial = self.get_trial(tid)
            if trial is None:
                continue
            spec = self._gang_spec_for(trial)
            # Sticky reservations must not outlive their own viability: a
            # block containing a chip that DIED while busy (so it was
            # never gang-held and _check_gang_members never saw it) can
            # never fully free — release and re-plan around the dead
            # chip, or the gang parks forever.
            existing = self._placer.block_of(tid)
            if existing is not None and dead_chips & set(existing):
                self._release_gang(tid, why="block_chip_dead")
            block = self._placer.reserve(tid, spec.chips, free_chips,
                                         avoid=dead_chips - free_chips)
            if block is None:
                continue
            for p, c in list(chip_by_pid.items()):
                if c in block:
                    res.hold_for_gang(p, tid)
                    if p == partition_id:
                        conscripted = True
                    del chip_by_pid[p]
                    free_chips.discard(c)
            members = res.gang_members(tid)
            if len(members) >= spec.chips:
                self._assemble_gang_locked(tid, trial, spec, block,
                                           members)
        return conscripted

    def _assemble_gang_locked(self, tid: str, trial: Trial,
                              spec: "gang_mod.GangSpec", block: List[int],
                              members: List[int]) -> None:
        """All member chips held: designate the lowest-chip member as
        LEADER, stamp the gang geometry into the trial's info (it ships
        with the TRIAL reply -> ctx.gang), and assign the trial to the
        leader. Sched lock held."""
        leader = min(members, key=self._chip_of)
        info = {"chips": sorted(int(c) for c in block),
                "members": sorted(int(m) for m in members),
                "leader": int(leader), "mesh": dict(spec.mesh),
                "strategy": spec.strategy}
        # REMOTE gang: members registered from other processes (their
        # REG carried an advertised host_port — fleet agents do, thread
        # runners never) need a driver-coordinated jax.distributed
        # rendezvous instead of the runner≈chip-in-one-process
        # assumption. Stamped only when EVERY member is remote: each
        # agent is one OS process, so num_processes = len(members) and
        # every process runs the SPMD program. A MIXED thread+agent gang
        # must not be stamped — the co-process thread members would be
        # counted as distinct processes that can never all initialize
        # (one latch per process), hanging the world forever; it runs
        # the in-process path instead. Process ids in chip order, leader
        # = process 0, the leader's advertised address is the
        # coordinator.
        res = self.server.reservations
        coord_by_member = {
            m: (res.get(m) or {}).get("host_port") for m in members}
        if all(coord_by_member.get(m) for m in members):
            ordered = sorted(members, key=self._chip_of)
            info["rendezvous"] = {
                "coordinator": coord_by_member[ordered[0]],
                "num_processes": len(ordered),
                "process_ids": {str(int(m)): i
                                for i, m in enumerate(ordered)},
            }
        with trial.lock:
            trial.info_dict["gang"] = info
        with self._store_lock:
            self._gangs[tid] = dict(info)
            if tid in self._gang_wait:
                self._gang_wait.remove(tid)
            if tid in self._requeue:
                self._requeue.remove(tid)
        trial.set_status(Trial.SCHEDULED)
        self.server.reservations.assign_trial(leader, tid)
        self.telemetry.trial_event(tid, "gang_assembled", partition=leader,
                                   members=info["members"],
                                   chips=info["chips"],
                                   strategy=spec.strategy)
        self.telemetry.trial_event(tid, "assigned", partition=leader)
        self._log("gang assembled for trial {}: chips {} (leader runner "
                  "{}, strategy {})".format(tid, info["chips"], leader,
                                            spec.strategy))

    def _release_gang(self, tid: str, why: str,
                      partition: Optional[int] = None) -> None:
        """Return a gang's chips to the pool: drop the member holds,
        free the placer block, and journal the span edge. Idempotent —
        callable from every terminal path (FINAL, error, preemption,
        revocation, blacklist)."""
        with self._store_lock:
            info = self._gangs.pop(tid, None)
        freed = self.server.reservations.release_gang(tid)
        if self._placer is not None:
            self._placer.release(tid, reason=why)
        if info is None and not freed:
            return
        self.telemetry.trial_event(
            tid, "gang_released", partition=partition,
            members=(info or {}).get("members", freed), why=why)

    def _sync_fleet_gang(self, active: bool) -> None:
        """Keep the fleet-level contiguous-block reservation in step
        with gang demand: while gang trials wait or run, the fleet
        scheduler must route a contiguous runner block to THIS
        experiment (and protect it from preemption); when the last gang
        ends, the block goes back to fair share."""
        binding = getattr(self.config, "fleet", None)
        if binding is None or not hasattr(binding, "request_gang"):
            return
        with self._store_lock:
            was = self._fleet_gang_active
            self._fleet_gang_active = active
        if active and not was:
            got = binding.request_gang(
                gang_mod.config_max_gang_chips(self.config))
            if got is None:
                # No disjoint window right now (other experiments hold
                # blocks): stay un-latched so every subsequent demand
                # tick retries instead of running gangs without their
                # preemption-shielded block forever.
                with self._store_lock:
                    self._fleet_gang_active = False
        elif was and not active:
            binding.release_gang()

    def gang_members(self, trial_id: str) -> List[int]:
        """Members of an assembled gang (chaos's kill_gang_member picks
        its victim here); empty when the trial has no assembled gang."""
        with self._store_lock:
            info = self._gangs.get(trial_id)
            return list(info["members"]) if info else []

    def gang_info(self, trial_id: str) -> Optional[Dict[str, Any]]:
        """Snapshot of an assembled gang's geometry (None if not
        assembled) — the server's member-serve path reads the
        ``rendezvous`` block through this."""
        with self._store_lock:
            info = self._gangs.get(trial_id)
            return dict(info) if info else None

    def _check_gang_members(self) -> None:
        """Server event-loop scan: a silent member of an assembled gang
        means the gang's mesh is broken — revoke the WHOLE gang exactly
        once (the ``revoking`` flag dedupes rescans) via the worker
        thread. A silent member of a still-assembling gang just loses
        its hold so assembly re-plans around the dead chip."""
        bound = self.server.hb_loss_timeout
        if not self._gang_mode or bound is None:
            return
        res = self.server.reservations
        with self._store_lock:
            assembled = {tid: dict(info)
                         for tid, info in self._gangs.items()
                         if not info.get("revoking")}
        for tid, info in assembled.items():
            silent = [m for m in info["members"]
                      if res.is_silent(m, bound)]
            if not silent:
                continue
            with self._store_lock:
                live = self._gangs.get(tid)
                if live is None or live.get("revoking"):
                    continue
                live["revoking"] = True
            self.enqueue({"type": "GANG_LOST", "trial_id": tid,
                          "partition_id": silent[0]})
        # Pre-assembly holds on dead runners: release them so the
        # placer re-plans; the re-reserve path avoids dead chips.
        with self._store_lock:
            waiting = [tid for tid in self._gang_demand_locked()]
        for tid in waiting:
            for m in res.gang_members(tid):
                if res.is_silent(m, bound):
                    self._release_gang(tid, why="member_dead_assembling")
                    break

    def _gang_lost_msg_callback(self, msg) -> None:
        """Worker-thread half of gang revocation: requeue the trial
        EXACTLY once (reason ``gang_member_lost``), return the healthy
        members to the pool, and abort the (possibly still computing)
        leader through a reservation-level preempt STOP whose ack the
        idempotent preemption path drops."""
        tid = msg["trial_id"]
        pid = msg.get("partition_id")
        with self._sched_lock:
            with self._store_lock:
                info = self._gangs.get(tid)
            trial = self.get_trial(tid)
            if info is None or trial is None:
                return
            leader = info.get("leader")
            self._release_gang(tid, why="member_lost", partition=pid)
            self.server.reservations.clear_trial_if(leader, tid)
            trial.reset_run_state()
            with self._store_lock:
                if tid not in self._requeue:
                    self._requeue.append(tid)
            self.result["gang_revocations"] = \
                self.result.get("gang_revocations", 0) + 1
            self.telemetry.trial_event(tid, "requeued", partition=pid,
                                       reason="gang_member_lost")
            self._log("gang member (runner {}) lost for trial {}; gang "
                      "lease revoked, trial requeued".format(pid, tid))
            if leader is not None and leader != pid:
                # The leader is healthy but its mesh is gone: its next
                # heartbeat draws STOP(preempt); the ack finds the trial
                # already waiting and is dropped.
                self.server.reservations.request_stop(leader, tid)
        # The dead MEMBER's gauges must not linger (the healthy members
        # keep reporting their own).
        self.telemetry.prune_partition(pid)

    # ------------------------------------------- pipelined hand-off (prefetch)

    def _suggester_loop(self) -> None:
        """Dedicated suggester thread: keeps up to one pre-materialized
        suggestion per live runner, so an expensive suggest() (Bayes GP
        fit + acquisition) overlaps with device work instead of stalling
        whichever runner frees up next. Woken by REG/FINAL/dispatch; the
        idle tick bounds the wake-up latency when a signal is missed.
        A controller exception here is the same contract violation it
        would be on the worker thread: surface it and end the experiment
        rather than silently losing the pipeline."""
        while not self.worker_done and not self.experiment_done:
            try:
                refilled = self._refill_prefetch()
            except Exception as exc:  # noqa: BLE001 - mirror the worker contract
                # Both flags before the (slow, I/O-bound) traceback log:
                # anyone who observes the exception must already see the
                # experiment marked done.
                self.exception = exc
                # unguarded-ok: monotonic completion latch, polled lock-free by design
                self.experiment_done = True
                self._log("suggester error: {}".format(
                    traceback.format_exc()))
                return
            if not refilled:
                self._suggest_wake.wait(constants.DRIVER_IDLE_REQUEUE_TICK_S)
                self._suggest_wake.clear()

    def _prefetch_capacity(self) -> int:
        """Queue bound: one suggestion per live (registered, unreleased)
        runner, never more than the executor clamp (which already honors
        the controller's max_concurrency). Under vectorized trials the
        bound scales by K — a runner consumes up to K suggestions per
        hand-off, and a one-deep queue would starve block assembly down
        to scalar dispatches."""
        return min(self.num_executors,
                   self.server.reservations.live_count()) * self._vmap_lanes

    def _refill_prefetch(self) -> bool:
        """One refill attempt; True when a suggestion was materialized
        (the caller loops immediately to top the queue up)."""
        with self._sched_lock:
            if self.experiment_done or \
                    len(self._prefetched) >= self._prefetch_capacity():
                return False
            suggestion = self._timed_suggest(source="prefetch")
            if suggestion in (None, "IDLE"):
                return False
            self._admit_prefetched(suggestion)
            return True

    def _timed_suggest(self, source: str):
        """controller.suggest() with latency telemetry (sched lock held).
        Journals an ``ev: "suggest"`` event + the ``suggested`` span edge
        for every materialized trial; IDLE/None polls only feed the
        histogram."""
        t0 = time.monotonic()
        suggestion = self.controller.suggest()
        ms = (time.monotonic() - t0) * 1e3
        self.telemetry.observe_ms("controller.suggest_ms", ms)
        if suggestion in (None, "IDLE"):
            return suggestion
        self.telemetry.event("suggest", ms=round(ms, 3), source=source,
                             trial=suggestion.trial_id)
        self.telemetry.trial_event(suggestion.trial_id, "suggested")
        return suggestion

    # locked-by: _sched_lock
    def _admit_prefetched(self, trial: Trial) -> None:
        """Commit a prefetched suggestion (sched lock held): it enters the
        trial store NOW, so controller capacity checks — BO busy-location
        imputation, ASHA's in-flight rung-0 count — see it as in flight
        and cannot overshoot the schedule. The span's ``queued`` edge
        waits for dispatch, so chaos invariant 1 (every queued trial
        finalizes) is untouched by a later invalidation."""
        with self._store_lock:
            clash = self._trial_store.get(trial.trial_id)
            self._trial_store[trial.trial_id] = trial
        if clash is not None and clash is not trial:
            self._log("WARNING: controller re-issued trial id {} while it "
                      "was still in flight; the schedule may lose an "
                      "entry".format(trial.trial_id))
        self._prefetched.append(trial)
        self._prefetch_versions[trial.trial_id] = getattr(
            self.controller, "schedule_version", 0)

    # locked-by: _sched_lock
    def _invalidate_stale_prefetch(self) -> None:
        """Drop prefetched suggestions minted before the controller's
        current schedule_version (sched lock held): a FINAL that changed
        the schedule — ASHA promotion available, pruner stop, experiment
        done — must not be beaten to the runner by a pre-decision sample.
        Dropped trials leave the store and go back through
        controller.recycle(), so buffer-backed schedules lose nothing."""
        version = getattr(self.controller, "schedule_version", 0)
        stale = [t for t in self._prefetched
                 if self._prefetch_versions.get(t.trial_id) != version]
        if not stale:
            return
        for trial in stale:
            self._prefetched.remove(trial)
            self._prefetch_versions.pop(trial.trial_id, None)
            with self._store_lock:
                self._trial_store.pop(trial.trial_id, None)
            self.controller.recycle(trial)
        self.telemetry.event("prefetch_invalidated", n=len(stale),
                             version=version,
                             trials=[t.trial_id for t in stale])
        self.telemetry.metrics.counter("prefetch.invalidated").inc(len(stale))
        self._suggest_wake.set()

    # locked-by: _sched_lock
    def _ingest_final_report(self, last_trial: Trial) -> None:
        """The FINAL-path half of the split controller contract (sched
        lock held): rung/pruner/member bookkeeping, then stale-prefetch
        invalidation against the post-report schedule version."""
        self.controller.report(last_trial)
        self._invalidate_stale_prefetch()

    # locked-by: _sched_lock
    def _next_suggestion(self):
        """Controller-sourced candidate for a hand-off (sched lock held):
        the oldest still-valid prefetched suggestion when available, else
        a live suggest() — unless this is the RPC fast path and the
        controller is expensive (a GP fit must never run on the event
        loop; the reply falls back to OK and the suggester refills while
        the freed runner GET-polls)."""
        if self._prefetched:
            trial = self._prefetched.pop(0)
            self._prefetch_versions.pop(trial.trial_id, None)
            self._suggest_wake.set()  # a queue slot opened
            return trial
        if self._inline_depth > 0 and \
                getattr(self.controller, "SUGGEST_COST", "cheap") == "expensive":
            self._suggest_wake.set()
            return "IDLE"
        return self._timed_suggest(source="inline")

    def process_final_inline(self, msg) -> bool:
        """RPC-thread FINAL fast path (config.prefetch): finalize the
        trial, report it to the controller, invalidate stale prefetches,
        and decide the partition's next assignment — all before the FINAL
        reply is written, so the reply can carry the hand-off (the server
        serves the resulting assignment inline; see
        OptimizationServer._final). Returns True when fully processed
        (the caller must NOT also enqueue the message); False falls back
        to the worker path. The bounded lock wait is the event-loop
        protection: the lock is only contended while the suggester is
        mid-model-fit, and stalling every runner's heartbeats behind a GP
        fit is the exact pathology this pipeline removes. Remote envs
        (slow dump()) are excluded wholesale — persisting trial.json on
        the event loop would stall every heartbeat per FINAL."""
        if not self._inline_final_enabled or self.worker_done:
            return False
        if not self._sched_lock.acquire(
                timeout=constants.PREFETCH_FINAL_LOCK_TIMEOUT_S):
            self.telemetry.metrics.counter("prefetch.lock_fallbacks").inc()
            # This hand-off really fell back to GET polling: it must count
            # as a miss, or a Bayes sweep's hit rate would exclude exactly
            # the fit-contended FINALs misses are most common on.
            self.telemetry.trial_event(msg.get("trial_id"), "prefetch_miss",
                                       once=True,
                                       partition=int(msg["partition_id"]))
            return False
        try:
            self._inline_depth += 1
            try:
                self._final_msg_callback(msg)
            finally:
                self._inline_depth -= 1
            return True
        except Exception as exc:  # noqa: BLE001 - mirror the worker contract
            self.exception = exc
            self._log("FINAL fast-path error: {}".format(
                traceback.format_exc()))
            self.experiment_done = True
            return True
        finally:
            self._sched_lock.release()

    def _final_msg_callback(self, msg) -> None:
        """Finalize trial, persist artifacts, hand the executor new work
        (reference :369-417). Runs under the schedule lock in full: the
        trial-store pop below must never interleave with a suggester-held
        suggest() iterating the same dict (BO busy locations, ASHA
        in-flight counts) — on the worker fallback path that overlap is
        the COMMON case, since the fallback fires exactly because the
        suggester is mid-fit. Reentrant from process_final_inline."""
        with self._sched_lock:
            self._final_msg_locked(msg)

    def _final_msg_locked(self, msg) -> None:
        self.add_executor_logs(msg.get("logs"))
        # Any FINAL from this partition for this trial means the
        # computation a gang-revocation STOP (Reservations.request_stop)
        # was armed to abort has ended — consume it, or a stop orphaned
        # by a raced FINAL (dropped as stale below) would persist and
        # abort a healthy later re-run of the same trial on this runner.
        self.server.reservations.pop_stop(msg["partition_id"],
                                          msg.get("trial_id"))
        trial = self.get_trial(msg.get("trial_id"))
        if msg.get("preempted"):
            # A preemption ack is NOT a finalize: the trial goes back into
            # the schedule (resuming from its checkpoint step when it has
            # one), and the controller never sees a report for it.
            self._preempted_final(msg, trial)
            return
        if trial is None:
            # Duplicate FINAL (e.g. a falsely-declared-lost runner finishing a
            # trial another runner re-ran, or a retried FINAL whose first
            # delivery's reply was lost). The result is already recorded,
            # but the reporting runner still needs its next assignment or it
            # would poll GET empty-handed forever — UNLESS it already holds
            # an undelivered one (the retry raced the hand-off): assigning
            # again would orphan that trial in the store and hang the
            # experiment's in-flight wait.
            if self.server.reservations.get_assigned_trial(
                    msg["partition_id"]) is None:
                self._assign_next(msg["partition_id"], None)
            return
        msg_epoch = msg.get("epoch")
        with trial.lock:
            stale_epoch = msg_epoch is not None and \
                int(msg_epoch) != trial.run_epoch
        with self._store_lock:
            waiting = trial.trial_id in self._requeue
        if stale_epoch or (waiting and self.server.reservations
                           .get_assigned_trial(msg["partition_id"])
                           != trial.trial_id):
            # The trial was revoked/requeued out from under this runner
            # (gang member loss; a false loss detection) while its FINAL
            # was in flight: the requeue is authoritative — drop the
            # report and let the trial re-run. (A broken gang mesh could
            # not have produced a healthy FINAL on real hardware; the
            # CPU proxy would happily finalize it and the journal would
            # then show a requeue with no re-assembly.) The epoch check
            # catches what requeue-membership cannot: the dead run's
            # FINAL arriving AFTER the trial was re-dispatched — even
            # onto this same partition (a revoked gang reassembling onto
            # its old leader).
            self._log("dropping stale FINAL for requeued trial {} from "
                      "runner {}".format(trial.trial_id,
                                         msg["partition_id"]))
            if self.server.reservations.get_assigned_trial(
                    msg["partition_id"]) is None:
                self._assign_next(msg["partition_id"], None)
            return
        with trial.lock:
            if msg.get("error"):
                trial.status = Trial.ERROR
                trial.final_metric = None
            else:
                trial.status = Trial.FINALIZED
                trial.final_metric = float(msg["value"])
            trial.duration = time.time() - trial.start if trial.start else None
            was_error = trial.status == Trial.ERROR
            was_early_stop = trial.early_stop
        # "finalized": the hand-off gap's opening edge and the early-stop
        # reaction's closing edge — journaled BEFORE _assign_next so the
        # journal's event order matches the control flow it measures. Lane
        # FINALs tag their lane/block so per-lane spans close attributably
        # (and the goodput ledger can split block chip-time by lane).
        extra = {}
        if msg.get("block") is not None:
            extra = {"lane": msg.get("lane"), "block": msg.get("block")}
        self.telemetry.trial_event(trial.trial_id, "finalized",
                                   partition=msg.get("partition_id"),
                                   early_stop=was_early_stop,
                                   error=was_error, **extra)
        with self._store_lock:
            self._trial_store.pop(trial.trial_id, None)
            self._final_store.append(trial)
        # A finalized gang trial frees its whole mesh slice: members
        # return to the pool before the artifact dump below, so their
        # idle ticks can pick up work while the leader persists.
        self._release_gang(trial.trial_id,
                           why="error" if was_error else "finalized",
                           partition=msg.get("partition_id"))
        if trial.status == Trial.ERROR and self.controller.pruner is not None:
            report = getattr(self.controller.pruner, "report_failure", None)
            if report:
                report(trial.trial_id)
                self._checkpoint_pruner()
        self._update_result(trial)
        # Persist BEFORE the hand-off: assignment of the last trial flips
        # experiment_done and releases pool.run(), so a dump placed after it
        # could still be in flight (or fail unobserved) when lagom returns.
        self.env.dump(trial.to_json(),
                      "{}/{}/trial.json".format(self.exp_dir, trial.trial_id))
        if msg.get("block") is not None:
            leader_id = msg["block"]
            if not msg.get("last"):
                # Mid-block lane FINAL (early-stopped/masked lane, or any
                # lane before the closing one): the partition still holds
                # the block — report to the controller NOW (the optimizer
                # reacts at masking time, and stale prefetches drop) but
                # hand off nothing.
                with self._store_lock:
                    self._lane_leader.pop(trial.trial_id, None)
                if self._prefetch_enabled:
                    self._ingest_final_report(trial)
                else:
                    # Blocks only assemble from the prefetch queue, but a
                    # lane FINAL racing a config flip must not crash here.
                    report = getattr(self.controller, "report", None)
                    if report is not None:
                        report(trial)
                self._sweep_fork_gc()
                return
            # Closing lane: the block is done — drop its bookkeeping and
            # run the normal hand-off (report + piggybacked next block).
            with self._store_lock:
                block = self._vmap_blocks.pop(leader_id, None)
                for tid in (block or {}).get("lanes", ()):
                    self._lane_leader.pop(tid, None)
        self._assign_next(msg["partition_id"], trial)
        # AFTER the hand-off (the freed runner never waits on disk ops):
        # retire parent checkpoints this FINAL made unforkable.
        self._sweep_fork_gc()

    def _preempted_final(self, msg, trial: Optional[Trial]) -> None:
        """Requeue a preempted trial (sched lock held). Idempotent under
        at-least-once delivery: only a trial whose preempt flag is still
        armed is processed — a retried ack (severed reply) arrives after
        reset_run_state cleared it and is ignored. ``step`` is the
        runner's last checkpoint step: stored on the trial so the TRIAL
        reply that re-dispatches it ships ``resume_step`` to the next
        runner (ctx.resume_step); None = it never checkpointed and simply
        re-runs from scratch."""
        pid = msg.get("partition_id")
        if trial is None:
            return
        if not trial.get_preempt():
            # No armed preempt flag: either a RETRIED ack whose first
            # delivery already requeued the trial, or the evict race —
            # the worker assigned this trial AFTER request_evict but
            # before any flagging, so the GET path's synthetic preempted
            # FINAL is the trial's ONLY way back into the schedule.
            # Discriminate by where the trial is now: waiting or
            # re-dispatched or terminal => retry, drop it; otherwise it
            # is orphaned and must requeue (from scratch — it never ran
            # on the evicted runner).
            with self._store_lock:
                waiting = trial.trial_id in self._requeue \
                    or trial.trial_id in self._parked
            if waiting:
                return
            if any(rec.get("trial_id") == trial.trial_id
                   for rec in self.server.reservations.all().values()):
                return
            with trial.lock:
                if trial.final_metric is not None \
                        or trial.status == Trial.ERROR:
                    return
            msg = {**msg, "step": None}
        step = msg.get("step")
        # A preempted block leader takes its lanes with it: non-leader
        # lanes requeue here as scalar trials; the leader follows the
        # normal preemption path below.
        self._requeue_vmap_block(trial.trial_id, pid, "preempted")
        trial.reset_run_state()
        # A preempted gang trial releases its slice like any other
        # terminal path; reassembly happens from the requeue backlog.
        self._release_gang(trial.trial_id, why="preempted", partition=pid)
        with trial.lock:
            if step is not None:
                trial.info_dict["resume_step"] = int(step)
            else:
                fork = trial.info_dict.get("forked_from")
                if fork and fork.get("step") is not None:
                    # A FORKED trial preempted before it ever
                    # checkpointed (or even staged) still has its fork
                    # point: the re-dispatch resumes there, not from
                    # scratch.
                    trial.info_dict["resume_step"] = int(fork["step"])
                else:
                    trial.info_dict.pop("resume_step", None)
        with self._store_lock:
            if trial.trial_id not in self._requeue:
                self._requeue.append(trial.trial_id)
        self.result["preemptions"] = self.result.get("preemptions", 0) + 1
        self.telemetry.trial_event(trial.trial_id, "preempted",
                                   partition=pid, step=step,
                                   checkpointed=step is not None)
        # The explicit re-queue edge, like LOST/BLACK paths journal: the
        # chaos harness derives fault->requeue recovery from it.
        self.telemetry.trial_event(trial.trial_id, "requeued",
                                   partition=pid, reason="preempted")
        self._log("trial {} preempted on runner {} ({}); requeued".format(
            trial.trial_id, pid,
            "checkpoint step {}".format(step) if step is not None
            else "no checkpoint"))
        if not self.server.reservations.evict_requested(pid):
            # The runner stays with this experiment (chaos preemption, or
            # rebalancing without eviction): hand it work now — possibly
            # the preempted trial itself, which IS the resume path.
            self._assign_next_locked(pid, None)

    def preempt_partition(self, partition_id: int,
                          evict: bool = False) -> Optional[str]:
        """Gracefully preempt whatever ``partition_id`` is running:
        arm the trial's preempt + early-stop flags so the next heartbeat
        draws STOP(preempt) and the runner acks with a preempted FINAL
        carrying its checkpoint step. ``evict=True`` (fleet) additionally
        releases the runner from this experiment once the ack (or, when
        idle, its next GET) lands. Returns the preempted trial id, or
        None when the partition held nothing (eviction alone applies).
        Callable from any thread — touches only trial/reservation locks."""
        res = self.server.reservations
        if evict:
            res.request_evict(partition_id)
        trial_id = res.get_assigned_trial(partition_id)
        trial = self.get_trial(trial_id) if trial_id else None
        if trial is None:
            return None
        trial.set_preempt()
        trial.set_early_stop()
        self.telemetry.trial_event(trial.trial_id, "preempt_requested",
                                   partition=partition_id, evict=evict)
        return trial.trial_id

    def _register_msg_callback(self, msg) -> None:
        # A respawned elastic runner arriving at its new size satisfies one
        # outstanding resize request toward that capacity.
        cap = msg.get("capacity")
        if cap is not None:
            with self._store_lock:
                if self._resize_inflight.get(cap, 0) > 0:
                    self._resize_inflight[cap] -= 1
                self._resize_watch.pop(msg["partition_id"], None)
        self._assign_next(msg["partition_id"], None)

    def _idle_msg_callback(self, msg) -> None:
        """Re-poll the controller after a short tick (reference :419-439)."""
        self._assign_next(msg["partition_id"], msg.get("last_trial"))

    def _checkpoint_pruner(self) -> None:
        """Persist multi-fidelity bracket state (a few KB of JSON) so an
        interrupted Hyperband schedule resumes without re-running finalized
        rungs. Runs on the driver worker thread only."""
        pruner = self.controller.pruner
        if pruner is None or not hasattr(pruner, "state_dict"):
            return
        try:
            self.env.dump(json.dumps(pruner.state_dict()),
                          self.exp_dir + "/" + constants.PRUNER_STATE_FILE)
        except Exception:  # noqa: BLE001 - checkpointing must not kill a run
            pass

    def _rearm_idle(self, partition_id: int) -> None:
        msg = {"type": "IDLE", "partition_id": partition_id, "last_trial": None}
        timer = threading.Timer(constants.DRIVER_IDLE_REQUEUE_TICK_S,
                                self.enqueue, args=(msg,))
        timer.daemon = True
        timer.start()

    def _partition_state(self, partition_id: int) -> str:
        """'live', 'silent' (heartbeats stopped past the loss bound), or
        'released' (saw GSTOP — will never ask for work again). A
        dead-while-idle runner otherwise keeps winning work through its
        self-perpetuating IDLE timer chain — a requeued trial handed to it
        costs a full extra LOST cycle."""
        rec = self.server.reservations.get(partition_id)
        if rec is None:
            return "live"  # REG still in flight — not evidence of death
        if rec.get("released") or rec.get("evict"):
            # Evicted (fleet preemption): the runner is leaving this
            # experiment — fresh work must be rerouted, not assigned to it.
            return "released"
        bound = self.server.hb_loss_timeout
        if bound is not None and \
                self.server.reservations.is_silent(partition_id, bound):
            return "silent"
        return "live"

    def _assign_next(self, partition_id: int, last_trial: Optional[Trial]) -> None:
        # The controller, not a trial count, decides when the experiment is
        # over: multi-fidelity schedules (ASHA promotions, Hyperband brackets)
        # legitimately run more trials than `num_trials` rung-0 samples.
        if self.experiment_done:
            return
        with self._sched_lock:
            self._assign_next_locked(partition_id, last_trial)
        if self._prefetch_enabled:
            # Whatever happened (dispatch, finalize, registration), the
            # prefetch picture may have changed — let the suggester look.
            self._suggest_wake.set()

    def _assign_next_locked(self, partition_id: int,
                            last_trial: Optional[Trial]) -> None:
        # Iterative on purpose: a gang suggestion parks for assembly and
        # pulls the NEXT suggestion — an all-gang backlog must drain in
        # a loop, not one recursion frame per parked trial (a ~1k-trial
        # GANG-only sweep would blow the recursion limit).
        while self._assign_next_once_locked(partition_id, last_trial):
            last_trial = None

    # locked-by: _sched_lock
    def _assign_next_once_locked(self, partition_id: int,
                                 last_trial: Optional[Trial]
                                 ) -> Optional[bool]:
        """One assignment attempt; True = pull again (the suggestion was
        parked for gang assembly and this runner is still free)."""
        # A gang-held member is not free: its chip belongs to an
        # (assembling or running) gang's mesh slice. Keep its idle chain
        # ticking so it resumes work the moment the gang releases. A
        # FINAL-delivering runner is never held here — terminal paths
        # release the gang before assigning next work.
        if self._gang_mode and last_trial is None and \
                self.server.reservations.gang_of(partition_id) is not None:
            self._rearm_idle(partition_id)
            return
        # Orphaned trials (lost runners) take priority over fresh
        # suggestions — but never swallow a FINAL report: when last_trial is
        # set the controller must see it (ASHA rung bookkeeping, pruner
        # reports) before any reassignment happens.
        if last_trial is None:
            suggestion = "IDLE"
        elif self._prefetch_enabled:
            # Split contract: report on the FINAL path (dropping
            # schedule-stale prefetches), then source the hand-off from
            # the prefetch queue — suggest() only runs inline when the
            # queue is dry and the controller is cheap.
            self._ingest_final_report(last_trial)
            suggestion = self._next_suggestion()
        else:
            suggestion = self.controller.get_suggestion(last_trial)
        state = self._partition_state(partition_id)
        if state != "live":
            # The controller has seen the FINAL; route any fresh suggestion
            # to the requeue for a live runner instead of this one.
            if suggestion not in (None, "IDLE"):
                self._mint_span(suggestion)
                with self._store_lock:
                    self._trial_store[suggestion.trial_id] = suggestion
                    self._requeue.append(suggestion.trial_id)
                self.telemetry.trial_event(suggestion.trial_id, "requeued",
                                           partition=partition_id,
                                           reason="dead_partition")
            # 'released' partitions saw GSTOP and never come back — drop
            # their IDLE chain. A 'silent' one may be a transient stall
            # (network hiccup): keep ticking so it resumes getting work if
            # its heartbeats return, but without handing it trials now.
            if state == "silent":
                self._rearm_idle(partition_id)
            return
        if suggestion in (None, "IDLE"):
            # Gang service first: a free runner whose chip sits inside a
            # reserved block is conscripted here — skipped-but-retained
            # for the gang instead of grabbing 1-chip work the block
            # would then have to wait out. The idle chain stays armed:
            # it is how the member resumes work after the gang releases.
            if self._service_gangs_locked(partition_id):
                self._rearm_idle(partition_id)
                return
            cap = self.server.reservations.capacity(partition_id)
            held = self._pop_fork_hold(partition_id)
            if held is not None:
                # A forked trial held for this runner's warm parent
                # state (or an expired hold any runner may take).
                held.set_status(Trial.SCHEDULED)
                self.server.reservations.assign_trial(partition_id,
                                                      held.trial_id)
                self.telemetry.trial_event(held.trial_id, "assigned",
                                           partition=partition_id,
                                           fork_affinity=True)
                self._journal_fork_edge(held, partition_id)
                return
            parked = self._pop_parked(cap)
            if parked is not None:
                parked.set_status(Trial.SCHEDULED)
                self.server.reservations.assign_trial(partition_id, parked.trial_id)
                self.telemetry.trial_event(parked.trial_id, "assigned",
                                           partition=partition_id,
                                           requeue="parked")
                return
            requeued = self._pop_requeue(cap)
            if requeued is not None:
                # A requeued FORK must still have its resume point (the
                # staged child copy or the parent's original); a vanished
                # source downgrades it to from-scratch loudly.
                self._verify_fork_source(requeued, partition_id)
                self.server.reservations.assign_trial(partition_id, requeued.trial_id)
                # Neutral label: the backlog holds genuinely lost trials
                # AND fresh suggestions rerouted off dead partitions — a
                # lost trial is identifiable by its own "lost" phase
                # event, so don't stamp phantom losses here.
                self.telemetry.trial_event(requeued.trial_id, "assigned",
                                           partition=partition_id,
                                           requeue="backlog")
                self._journal_fork_edge(requeued, partition_id)
                with requeued.lock:
                    resume_step = requeued.info_dict.get("resume_step")
                if resume_step is not None:
                    # Checkpoint-assisted resume: the closing edge of a
                    # preemption (chaos invariant 7 asserts from_step
                    # matches the preempted checkpoint step).
                    self.telemetry.trial_event(requeued.trial_id, "resumed",
                                               partition=partition_id,
                                               from_step=int(resume_step))
                return
            if last_trial is None:
                suggestion = self._next_suggestion() if self._prefetch_enabled \
                    else self.controller.get_suggestion(None)
            # Only when the controller ALSO has nothing fresh: an idle
            # elastic runner whose size fits no waiting trial migrates
            # toward the waiting work — otherwise its chips stay leased to
            # a size the schedule no longer needs and the pool deadlocks.
            # Demand/supply-bounded so a herd of idle runners doesn't all
            # chase one trial; runners beyond the demand are RETIRED
            # (resize 0), freeing chips for pending bigger spawns. The
            # worker COUNT never grows back after retirement (chips
            # re-aggregate, they don't re-split), which is the honest
            # trade for a push-free pool protocol.
            if suggestion in (None, "IDLE") and cap is not None \
                    and self._maybe_migrate(partition_id, cap):
                return
        if suggestion is None:
            # The controller has no more work — but the experiment is only
            # over once nothing is in flight: a trial held by a (possibly
            # dying) runner may yet come back through LOST and need this
            # runner to pick it up.
            with self._store_lock:
                in_flight = bool(self._trial_store)
            if in_flight:
                suggestion = "IDLE"
            else:
                self.experiment_done = True
        if suggestion == "IDLE":
            # Requeue after the idle tick from a timer, NOT by sleeping on the
            # single worker thread (64 idle runners would stall METRIC/FINAL
            # processing by ~0.6 s per cycle otherwise).
            self._rearm_idle(partition_id)
        elif suggestion is not None:
            self._mint_span(suggestion)
            with self._store_lock:
                # Trial ids hash the params; a controller emitting two
                # distinct units of work with identical params silently
                # collapses them here (one store slot) and loses a
                # schedule entry — exactly how a PBT id-collision bug
                # dropped 2 of 9 segments. Make it loud.
                # ERRORED entries don't count: a controller retrying a
                # failed unit of work (PBT segment retry) legitimately
                # re-issues the identical params/id. A store entry that IS
                # this object is no collision either — prefetched
                # suggestions enter the store at admit time and come back
                # through here at dispatch.
                existing = self._trial_store.get(suggestion.trial_id)
                duplicate = ((existing is not None
                              and existing is not suggestion)
                             or any(t.trial_id == suggestion.trial_id
                                    and t.final_metric is not None
                                    for t in self._final_store))
                self._trial_store[suggestion.trial_id] = suggestion
            if duplicate:
                self._log("WARNING: controller re-issued trial id {} "
                          "(params hash-collide with an in-flight or "
                          "finalized trial); the schedule may lose an "
                          "entry".format(suggestion.trial_id))
            # The controller just mutated its schedule (Hyperband bound the
            # new run to a bracket slot) — persist so resume=True can pick
            # the bracket up mid-flight.
            self._checkpoint_pruner()
            # Gang trials are never assigned to ONE runner: park the
            # trial for assembly (the placer reserves a contiguous chip
            # block; runners are conscripted as they free), then give
            # THIS runner another turn — it may itself become the first
            # conscript, else it takes the next (possibly 1-chip)
            # suggestion.
            spec = self._gang_spec_for(suggestion)
            if spec is not None and spec.chips > 1:
                with self._store_lock:
                    if suggestion.trial_id not in self._gang_wait:
                        self._gang_wait.append(suggestion.trial_id)
                self._log("trial {} needs a {}-chip gang ({}); awaiting "
                          "assembly".format(suggestion.trial_id, spec.chips,
                                            spec.strategy))
                if self._service_gangs_locked(partition_id):
                    self._rearm_idle(partition_id)
                    return None
                return True  # runner still free: pull the next suggestion
            # 1-chip work must not land on a runner whose chip is
            # reserved for a waiting gang (the block would re-busy
            # instead of draining): retain the suggestion in the backlog
            # for an unreserved runner and conscript this one.
            if self._gang_mode and self._placer is not None and \
                    self._placer.owner_of(
                        self._chip_of(partition_id)) is not None:
                with self._store_lock:
                    if suggestion.trial_id not in self._requeue:
                        self._requeue.append(suggestion.trial_id)
                self._service_gangs_locked(partition_id)
                self._rearm_idle(partition_id)
                return
            # Elastic sub-slices: a trial whose budget calls for a different
            # chip count than this runner is pinned to gets PARKED, and the
            # runner is told to exit + respawn at the right size (pinning
            # happens before backend init; it cannot resize in place).
            need = self._chips_for(suggestion)
            cap = self.server.reservations.capacity(partition_id)
            if need is not None and cap is not None and need != cap:
                with self._store_lock:
                    self._parked.append(suggestion.trial_id)
                    # Count toward the herd bound: this runner is already
                    # on its way to ``need``, so idle runners must not
                    # also chase the same trial.
                    self._resize_inflight[need] = \
                        self._resize_inflight.get(need, 0) + 1
                    self._resize_watch[partition_id] = (
                        time.monotonic(), need, self._pool_spawn_stamp(
                            partition_id))
                self.server.reservations.request_resize(partition_id, need)
                self._log("trial {} needs {} chip(s); runner {} (capacity "
                          "{}) asked to resize".format(
                              suggestion.trial_id, need, partition_id, cap))
                return
            # Parent affinity: a fresh FORKED suggestion prefers the
            # runner holding its parent's warm slot + local checkpoint;
            # this runner pulls the next suggestion instead.
            if self._maybe_hold_for_parent(suggestion, partition_id):
                self._log("trial {} held for runner {} (fork parent "
                          "affinity)".format(
                              suggestion.trial_id,
                              self._parent_partition(
                                  suggestion.info_dict.get(
                                      "forked_from", {}).get("trial"))))
                return True  # runner still free: pull the next suggestion
            suggestion.set_status(Trial.SCHEDULED)
            if self._vmap_lanes > 1 and \
                    self._assemble_vmap_block_locked(suggestion,
                                                     partition_id):
                return
            self.server.reservations.assign_trial(partition_id, suggestion.trial_id)
            self.telemetry.trial_event(suggestion.trial_id, "assigned",
                                       partition=partition_id)
            self._journal_fork_edge(suggestion, partition_id)

    # --------------------------------- vectorized micro-trials (vmap blocks)

    def _vmap_blockable_locked(self, trial: Trial) -> bool:
        """Can this trial ride a vectorized block? Unhashable params (no
        program key), gang trials (multi-chip mesh), and checkpoint
        resumers/forks (per-lane state restore has no vmapped analogue)
        all fall back to scalar dispatch. A BO near-duplicate keeps its
        ``parent`` tag and is admitted as a FORK LANE — it trains from
        scratch next to its parent's family (warm-started-neighbor, not
        checkpoint-restored)."""
        try:
            hash(tuple(sorted(trial.params.items())))
        except TypeError:
            return False
        spec = self._gang_spec_for(trial)
        if spec is not None and spec.chips > 1:
            return False
        with trial.lock:
            info = dict(trial.info_dict)
        if info.get("resume_step") is not None or info.get("forked_from"):
            return False
        if info.get("parent") and not info.get("near_duplicate"):
            return False
        return True

    @staticmethod
    def _vmap_compatible(a: Trial, b: Trial) -> bool:
        """Same vmapped program? Proxy for the PR-6 warm-cache program key
        the runner will resolve: identical trial type and param names, and
        identical NON-FLOAT param values — float params are the stacked
        hyperparameter axis (swept_transform traces them as inputs, so any
        value shares one HLO), while ints/strings/bools steer model
        config, shapes, or optimizer family and force a separate program."""
        if a.trial_type != b.trial_type or set(a.params) != set(b.params):
            return False
        for key, va in a.params.items():
            vb = b.params[key]
            if isinstance(va, float) and isinstance(vb, float):
                continue
            if va != vb:
                return False
        return True

    # locked-by: _sched_lock
    def _assemble_vmap_block_locked(self, leader: Trial,
                                    partition_id: int) -> bool:
        """Assemble up to K program-compatible suggestions (the leader +
        prefetched candidates) into ONE block delivery. True = the block
        was assigned (>= 2 lanes); False = nothing to vectorize (or the
        leader itself is block-incompatible) — the caller dispatches the
        leader scalar, bit-for-bit the K=1 path."""
        if not self._vmap_blockable_locked(leader):
            return False
        lanes = [leader]
        for cand in list(self._prefetched):
            if len(lanes) >= self._vmap_lanes:
                break
            if not self._vmap_blockable_locked(cand) or \
                    not self._vmap_compatible(leader, cand):
                continue
            self._prefetched.remove(cand)
            self._prefetch_versions.pop(cand.trial_id, None)
            lanes.append(cand)
        if len(lanes) < 2:
            return False
        # The queue just drained by K-1: let the suggester top it up.
        self._suggest_wake.set()
        lane_descs = []
        for i, t in enumerate(lanes):
            if i > 0:
                # Prefetched lanes were admitted but never dispatched:
                # mint their spans now (queued edge), like the scalar
                # dispatch path does for the leader.
                self._mint_span(t)
                t.set_status(Trial.SCHEDULED)
            with t.lock:
                if t.info_dict.get("near_duplicate") and \
                        t.info_dict.get("parent"):
                    # BO fork_eps under lanes: the near-duplicate rides
                    # the block as a fork lane — fresh init next to the
                    # parent's program family, NOT a checkpoint restore
                    # (strip any fork stamp _mint_span applied).
                    t.info_dict.pop("forked_from", None)
                    t.info_dict.pop("resume_step", None)
                    t.info_dict["fork_lane"] = {
                        "parent": t.info_dict["parent"]}
                t.info_dict["vmap"] = {"lane": i, "block": leader.trial_id}
                t.info_dict["epoch"] = t.run_epoch
                lane_descs.append({"trial_id": t.trial_id, "lane": i,
                                   "params": dict(t.params),
                                   "span": t.info_dict.get("span"),
                                   "epoch": t.run_epoch,
                                   "fork_lane": t.info_dict.get(
                                       "fork_lane")})
        with leader.lock:
            leader.info_dict["vmap_block"] = {"lanes": lane_descs}
        with self._store_lock:
            self._vmap_blocks[leader.trial_id] = {
                "lanes": [t.trial_id for t in lanes],
                "partition": partition_id}
            for t in lanes:
                self._lane_leader[t.trial_id] = leader.trial_id
        self.server.reservations.assign_trial(partition_id,
                                              leader.trial_id)
        for i, t in enumerate(lanes):
            self.telemetry.trial_event(t.trial_id, "assigned",
                                       partition=partition_id, lane=i,
                                       block=leader.trial_id)
        self._log("vmap block {}: {} lanes assigned to runner {}".format(
            leader.trial_id, len(lanes), partition_id))
        return True

    def _mint_span(self, trial: Trial) -> None:
        """Mint the trial's telemetry span when the driver commits to it
        ("queued") and plant the span id in its info_dict — the TRIAL reply
        ships info, so the span travels to the runner for free and comes
        back on its METRIC/FINAL messages. The queued edge carries the
        trial's PARAMS: the journal is crash recovery's source of truth,
        and a committed-but-unfinalized trial must be reconstructible
        from it alone (trial ids are content-addressed over the params,
        so recovery can verify the round trip). The scheduler half of
        info_dict rides along too — an ASHA promotion's rung/parent or a
        PBT segment's member/generation must survive the crash, or the
        re-run's FINAL would bookkeep into the wrong ledger slot — and
        the fork stamp below is applied FIRST so forked_from/resume_step
        land on the queued edge and a driver crash cannot orphan a fork
        mid-flight (recovery rebuilds the lineage from exactly this
        event); dispatch-time keys (span/gang/partition/epoch) are
        rebuilt by recovery itself and stay out."""
        self._stamp_fork(trial)
        with trial.lock:
            sched_info = {k: v for k, v in trial.info_dict.items()
                          if k not in ("span", "gang", "partition", "epoch")}
        span = self.telemetry.trial_event(trial.trial_id, "queued",
                                          params=trial.params,
                                          trial_type=trial.trial_type,
                                          info=sched_info)
        if span is not None:
            with trial.lock:
                trial.info_dict["span"] = span

    # -------------------------------------------- checkpoint-forking search

    def _stamp_fork(self, trial: Trial) -> None:
        """Turn a parent-carrying suggestion into a checkpoint FORK: if
        the parent left an ack'd checkpoint, stamp ``forked_from`` =
        (parent, step) + ``resume_step`` into the trial's info so the
        TRIAL payload ships them, the executor stages the parent's
        checkpoint into the child's dir, and a ctx-aware train fn
        resumes at ``step + 1`` instead of re-training the prefix. A
        parent with no checkpoint (ctx-less train fn, GC'd dir) leaves
        the trial untouched — from-scratch promotion, the pre-fork
        behavior. config.fork=False disables the stamp wholesale
        (bit-for-bit from-scratch promotions)."""
        if not self._fork_enabled:
            return
        with trial.lock:
            parent = trial.info_dict.get("parent")
            already = trial.info_dict.get("forked_from")
        if parent is None or already is not None:
            return
        with self._store_lock:
            cached = self._fork_step_cache.get(parent, _UNRESOLVED)
        if cached is not _UNRESOLVED:
            step = cached
        else:
            from maggy_tpu.train.checkpoint import \
                latest_checkpoint_step_env

            try:
                step = latest_checkpoint_step_env(
                    self.env, "{}/{}".format(self.exp_dir, parent))
            except Exception:  # noqa: BLE001 - an unreadable dir = no fork
                step = None
            with self._store_lock:
                self._fork_step_cache[parent] = step
        if step is None:
            return
        with trial.lock:
            trial.info_dict["forked_from"] = {"trial": parent,
                                              "step": int(step)}
            trial.info_dict["resume_step"] = int(step)

    def _journal_fork_edge(self, trial: Trial, partition_id: int) -> None:
        """The genealogy span edge (once per span — a requeued fork's
        re-dispatch does not repeat it): parent -> child with the forked
        step, rendered by trace.py as a Perfetto flow arrow and counted
        by derive()'s fork block."""
        with trial.lock:
            fork = trial.info_dict.get("forked_from")
        if not fork:
            return
        self.telemetry.trial_event(trial.trial_id, "forked_from",
                                   once=True, partition=partition_id,
                                   parent=fork.get("trial"),
                                   step=fork.get("step"))

    def _verify_fork_source(self, trial: Trial, partition_id: int) -> None:
        """Before re-dispatching a requeued FORKED trial: its resume
        point must still exist — either the child's staged checkpoint
        (the first attempt got far enough to stage) or the parent's
        original (GC keeps it while a fork is schedulable, but disk loss
        or an operator wipe can race). A vanished source downgrades the
        trial to from-scratch LOUDLY (requeued reason=fork_source_lost +
        stripped fork keys) instead of letting the runner crash opening
        a checkpoint that is not there."""
        with trial.lock:
            fork = trial.info_dict.get("forked_from")
        if not fork:
            return
        step = fork.get("step")
        child = "{}/{}/checkpoints/{}".format(self.exp_dir, trial.trial_id,
                                              step)
        parent = "{}/{}/checkpoints/{}".format(self.exp_dir,
                                               fork.get("trial"), step)
        try:
            ok = self.env.isdir(child) or self.env.isdir(parent)
        except Exception:  # noqa: BLE001 - unreadable = assume gone
            ok = False
        if ok:
            return
        with trial.lock:
            trial.info_dict.pop("forked_from", None)
            trial.info_dict.pop("resume_step", None)
        self.telemetry.trial_event(trial.trial_id, "requeued",
                                   partition=partition_id,
                                   reason="fork_source_lost",
                                   parent=fork.get("trial"), step=step)
        self._log("fork source for trial {} (parent {} step {}) vanished; "
                  "re-running from scratch".format(
                      trial.trial_id, fork.get("trial"), step))

    def _parent_partition(self, parent_id: str) -> Optional[int]:
        """The partition that last ran (and checkpointed) the parent —
        where its warm slot and locally-staged checkpoint live."""
        return self.telemetry.spans.partition_of(parent_id)

    # locked-by: _sched_lock
    def _maybe_hold_for_parent(self, trial: Trial,
                               partition_id: int) -> bool:
        """Parent-affinity (the PR-14 prewarm hints extended from family
        to parent scope): a forked trial dispatched while the parent's
        runner is alive is briefly HELD for that runner — it already
        holds the family's warm slot AND the parent's checkpoint on
        local disk, so the fork loads without a cross-runner copy. Held
        at most once per trial and at most FORK_AFFINITY_HOLD_S (then
        any runner takes it), so affinity can never starve the trial.
        Returns True when held — the asking runner pulls its next
        suggestion."""
        if not self._fork_enabled or self._chips_map is not None:
            # Elastic pools size runners per budget: an affinity hold
            # would bypass the capacity matching below.
            return False
        with trial.lock:
            fork = trial.info_dict.get("forked_from")
        if not fork:
            return False
        preferred = self._parent_partition(fork.get("trial"))
        if preferred is None or int(preferred) == int(partition_id):
            return False
        with self._store_lock:
            if trial.trial_id in self._fork_held:
                return False
        if self._partition_state(int(preferred)) != "live":
            return False
        with self._store_lock:
            self._fork_held.add(trial.trial_id)
            self._fork_hold.append(
                (time.monotonic() + constants.FORK_AFFINITY_HOLD_S,
                 int(preferred), trial.trial_id))
        return True

    def _pop_fork_hold(self, partition_id: int) -> Optional[Trial]:
        """A trial held for THIS partition (parent affinity), or any
        EXPIRED hold — whoever idles first past the deadline takes it."""
        now = time.monotonic()
        with self._store_lock:
            for i, (deadline, preferred, tid) in enumerate(self._fork_hold):
                if preferred != int(partition_id) and now < deadline:
                    continue
                del self._fork_hold[i]
                trial = self._trial_store.get(tid)
                if trial is not None:
                    return trial
        return None

    # locked-by: _sched_lock
    def _sweep_fork_gc(self) -> None:
        """Checkpoint GC: retire a parent's checkpoint dir once the
        controller reports no live or schedulable child can still fork
        from it (Asha: the promotion child finalized; PBT: the segment
        was superseded as its member's population state). Never touches
        a LIVE trial — anything still in the store/backlogs keeps its
        latest ack'd step — and each retirement journals a ``ckpt_gc``
        event, so a forking sweep's disk stays bounded and auditable.
        Only the ELIGIBILITY decision runs here (cheap dict ops, sched
        lock held); the recursive dir deletions happen on a short-lived
        daemon thread — on the prefetch inline FINAL path this method
        runs on the RPC event loop before the reply is written, and
        tree deletions there would stall every tenant heartbeat."""
        if not self._fork_enabled:
            return
        eligible = getattr(self.controller, "fork_gc_eligible", None)
        if eligible is None:
            return
        try:
            candidates = list(eligible())
        except Exception:  # noqa: BLE001 - GC is an optimization, never fatal
            return
        todo = []
        with self._store_lock:
            for tid in candidates:
                if tid in self._ckpt_gced:
                    continue
                if (tid in self._trial_store or tid in self._requeue
                        or tid in self._parked):
                    continue
                # Claimed now so a racing next sweep cannot double-GC;
                # a failed delete un-claims for retry.
                self._ckpt_gced.add(tid)
                todo.append(tid)
        if todo:
            threading.Thread(target=self._fork_gc_worker, args=(todo,),
                             daemon=True, name="fork-gc").start()

    def _fork_gc_worker(self, todo: List[str]) -> None:
        """Off-hot-path half of checkpoint GC: the env I/O. Runs without
        any driver lock — a GC'd trial is finalized and non-live by the
        sweep's claim above, so nothing races the deletion (and even a
        pathological race only costs a fork its source, which the
        fork_source_lost downgrade absorbs loudly)."""
        for tid in todo:
            path = "{}/{}/checkpoints".format(self.exp_dir, tid)
            try:
                had = self.env.isdir(path)
                if had:
                    self.env.delete(path, recursive=True)
            except Exception:  # noqa: BLE001 - a failed delete retries next sweep
                with self._store_lock:
                    self._ckpt_gced.discard(tid)
                continue
            if had:
                with self._store_lock:
                    # A later stamp against this parent (a BO
                    # near-duplicate may pick ANY finalized trial) must
                    # see "no checkpoint", not the stale pre-GC step.
                    self._fork_step_cache[tid] = None
                try:
                    self.telemetry.event("ckpt_gc", trial=tid,
                                         why="no_schedulable_child")
                    self._log("ckpt_gc: retired checkpoints of "
                              "{}".format(tid))
                except Exception:  # noqa: BLE001 - the final sweep's worker may
                    # outlive experiment teardown (journal closed); the
                    # deletion itself already happened.
                    pass

    # -------------------------------------------------------------- results

    def _update_result(self, trial: Trial) -> None:
        if trial.final_metric is None:
            return
        metric, maximize = trial.final_metric, self.direction == "max"
        r = self.result
        r["num_trials"] += 1
        if r["best_val"] is None or (metric > r["best_val"] if maximize else metric < r["best_val"]):
            r.update(best_id=trial.trial_id, best_val=metric,
                     best_hp=self.controller._strip_budget(trial.params))
        if r["worst_val"] is None or (metric < r["worst_val"] if maximize else metric > r["worst_val"]):
            r.update(worst_id=trial.trial_id, worst_val=metric,
                     worst_hp=self.controller._strip_budget(trial.params))
        n = r["num_trials"]
        r["avg"] = metric if r["avg"] is None else r["avg"] + (metric - r["avg"]) / n

    def _exp_startup_callback(self) -> None:
        self.job_start = time.time()
        util.write_hparams_config(self.exp_dir, self.config.searchspace)

    def _exp_final_callback(self, job_end, exp_json):
        with self._store_lock:
            finalized = list(self._final_store)
        self.controller._finalize_experiment(finalized)
        duration = job_end - (self.job_start or job_end)
        self.result["duration_s"] = duration
        self.env.dump(json.dumps(self.result, indent=2, default=str),
                      self.exp_dir + "/result.json")
        # Aggregate per-trial artifacts (.hparams.json/.outputs.json) into
        # .summary.json (reference `util.py:126-148`).
        try:
            util.build_summary(self.exp_dir, env=self.env)
        except Exception:  # noqa: BLE001 - summary is best-effort
            pass
        self.maggy_log = self._result_summary(duration)
        if getattr(self.config, "verbose", False):
            print(self.maggy_log, flush=True)
        # Make the telemetry artifact durable at the finish line (the
        # flusher thread's cadence must not decide whether the last trials'
        # spans land), and mirror the derived scheduling numbers into
        # TensorBoard scalars next to the experiment's hparams config.
        self.telemetry.event("experiment", phase="finalized",
                             duration_s=duration)
        self.telemetry.flush()
        try:
            from maggy_tpu import tensorboard as tb

            tb.write_telemetry_scalars(self.exp_dir,
                                       self.telemetry.snapshot(fresh=True))
        except Exception:  # noqa: BLE001 - telemetry mirrors are best-effort
            pass
        self.env.finalize_experiment(
            self.exp_dir, "FINISHED",
            {"result": {k: self.result[k] for k in
                        ("best_id", "best_val", "avg", "num_trials", "early_stopped")}},
        )
        return dict(self.result)

    def _exp_exception_callback(self, exc) -> None:
        self.env.finalize_experiment(self.exp_dir, "FAILED", {"error": repr(exc)})
        raise exc

    def stop(self) -> None:
        # Retire the suggester BEFORE the base teardown: a mid-wait
        # suggester must not refill from a stopping controller (and a
        # mid-fit one gets the join bound; it is a daemon either way).
        # unguarded-ok: monotonic completion latch, polled lock-free by design
        self.experiment_done = True
        self._suggest_wake.set()
        t = self._suggester_thread
        if t is not None and t.is_alive():
            t.join(timeout=5)
        super().stop()

    def _result_summary(self, duration: float) -> str:
        """Human-readable final summary (the reference prints one to the
        notebook, `optimization_driver.py:172-194`)."""
        r = self.result
        lines = [
            "------ {} results ------ direction({})".format(
                type(self.controller).__name__, self.direction),
            "BEST combination {} -- metric {}".format(
                json.dumps(r["best_hp"], default=str), r["best_val"]),
            "WORST combination {} -- metric {}".format(
                json.dumps(r["worst_hp"], default=str), r["worst_val"]),
            "AVERAGE metric -- {}".format(r["avg"]),
            "EARLY STOPPED trials -- {}".format(r["early_stopped"]),
            "Total job time {:.2f} s ({} trials)".format(
                duration, r["num_trials"]),
        ]
        return "\n".join(lines)

    def obs_status(self) -> Dict[str, Any]:
        """Extend the base /status document with the HPO driver's live
        scheduling state: trial-store/backlog counts, assembled gangs (+
        placer blocks), and the fleet scheduler's share snapshot when
        fleet-attached. Locks are taken one at a time, never nested —
        this runs on an obs handler thread."""
        out = super().obs_status()
        with self._store_lock:
            out["store"] = {
                "trials": len(self._trial_store),
                "finalized": len(self._final_store),
                "requeue": len(self._requeue),
                "parked": len(self._parked),
                "gang_wait": len(self._gang_wait),
            }
            out["gangs"] = {
                tid: {"chips": info.get("chips"),
                      "members": list(info.get("members") or []),
                      "leader": info.get("leader"),
                      "strategy": info.get("strategy"),
                      "revoking": bool(info.get("revoking"))}
                for tid, info in self._gangs.items()}
        if self._placer is not None:
            out["pack"] = self._placer.snapshot()
        binding = getattr(self.config, "fleet", None)
        if binding is not None:
            out["fleet"] = binding.fleet.scheduler.snapshot()
        return out

    def progress_snapshot(self) -> Dict[str, Any]:
        with self._store_lock:
            done = len(self._final_store)
        with self._log_lock:
            log_total = len(self.executor_logs)
            log_tail = list(self.executor_logs[-20:])
        return {"num_trials": self.num_trials, "finalized": done,
                "best_val": self.result["best_val"],
                "early_stopped": self.result["early_stopped"],
                # Executor-log stream for the monitor CLI (reference's LOG
                # RPC carried executor prints to sparkmagic, rpc.py:369-377):
                # total count + tail window lets a poller print only new lines.
                "log_total": log_total, "log_tail": log_tail}
