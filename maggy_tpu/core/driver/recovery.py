"""Crash-only driver recovery: rebuild control-plane state from the journal.

The telemetry journal is the experiment's replayable source of truth
(durable up to the FINAL-path barrier, torn-tail tolerant), and the split
report/suggest optimizer contract makes controller state reconstructible
from the FINAL stream — so a driver that dies mid-sweep restarts with
``lagom(..., resume=True)``, replays its own journal, and continues
instead of losing the run (ROADMAP item 2; the Podracer paper's
crash-only controller design).

What is REPLAYED vs RE-ADOPTED vs REQUEUED (docs/developer.md):

- **Replayed** (this module, pure over journal events): the set of
  committed-but-unfinalized trials, each with its params (journaled on
  the ``queued`` edge), its last run epoch (journaled on the ``running``
  edge), its last holding partition, and any preemption checkpoint step;
  plus every partition the dead incarnation had registered, with its
  capacity. Span state and the finalized half (trial.json artifacts +
  ``restore_from_finals``) are restored by the driver before this runs.
- **Re-adopted**: still-live runners. The server comes back on the same
  secret and address (``driver_state.json``); a surviving runner's next
  heartbeat / retried FINAL / GET re-binds it (journaled ``adopted``),
  and a restarted runner agent reclaims its slot through the ordinary
  JOIN resume path. A retried FINAL from the pre-crash incarnation is
  accepted exactly once — the reconstructed trial carries its pre-crash
  run epoch, so the stale-epoch guard passes until a post-recovery
  requeue bumps it.
- **Requeued**: trials on runners that died with (or after) the driver.
  Recovery seeds the reservation table with the pre-crash assignments
  and a FRESH liveness window; the ORDINARY slot-reclaim scan then
  requeues a silent partition's trial exactly once. Recovery itself adds
  no second requeue path.

Everything here is a pure function over journal events plus one applier
that writes into the driver's own stores under its own locks.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional


class TrialFacts:
    """Recovery facts about one trial, accumulated over its journal
    events (oldest first)."""

    __slots__ = ("trial_id", "params", "trial_type", "info", "queued_t",
                 "finalized", "epoch", "partition", "resume_step")

    def __init__(self, trial_id: str):
        self.trial_id = trial_id
        self.params: Optional[Dict[str, Any]] = None
        self.trial_type: str = "optimization"
        self.info: Dict[str, Any] = {}
        self.queued_t: Optional[float] = None
        self.finalized = False
        self.epoch = 0
        #: Partition holding the trial at the journal's end (None = the
        #: attempt ended: requeued/lost/preempted and not re-dispatched).
        self.partition: Optional[int] = None
        self.resume_step: Optional[int] = None


class ReplayedState:
    """The journal's recovery-relevant content: per-trial facts plus the
    dead incarnation's registered partitions (pid -> capacity)."""

    def __init__(self):
        self.trials: Dict[str, TrialFacts] = {}
        self.partitions: Dict[int, Optional[int]] = {}

    def inflight(self) -> List[TrialFacts]:
        """Committed (``queued``) but never finalized, params known —
        the trials recovery must put back into the schedule."""
        return [f for f in self.trials.values()
                if f.queued_t is not None and not f.finalized
                and f.params is not None]


def replay_recovery_state(events: List[Dict[str, Any]]) -> ReplayedState:
    """Fold a (possibly multi-incarnation) journal into ReplayedState.
    Pure — the same events always produce the same reconstruction."""
    state = ReplayedState()
    for ev in events:
        kind = ev.get("ev")
        if kind == "runner":
            pid = ev.get("partition")
            if pid is not None:
                if ev.get("phase") == "registered":
                    # Capacity rides ONLY the registered edge; later
                    # runner events (a previous recovery's ``adopted``)
                    # carry none and must not clobber it — a second
                    # failover would otherwise restore elastic runners
                    # capacity-less.
                    state.partitions[int(pid)] = ev.get("capacity")
                else:
                    state.partitions.setdefault(int(pid), None)
            continue
        if kind != "trial":
            continue
        tid = ev.get("trial")
        if not tid:
            continue
        facts = state.trials.get(tid)
        if facts is None:
            facts = state.trials[tid] = TrialFacts(tid)
        phase = ev.get("phase")
        if phase == "queued":
            facts.queued_t = ev.get("t")
            if ev.get("params") is not None:
                facts.params = ev["params"]
                facts.trial_type = ev.get("trial_type", "optimization")
                facts.info = dict(ev.get("info") or {})
        elif phase == "assigned":
            if ev.get("partition") is not None:
                facts.partition = int(ev["partition"])
        elif phase == "running":
            if ev.get("partition") is not None:
                facts.partition = int(ev["partition"])
            if ev.get("epoch") is not None:
                facts.epoch = int(ev["epoch"])
        elif phase in ("requeued", "lost"):
            # The attempt ended; a later ``assigned`` re-sets the holder.
            facts.partition = None
        elif phase == "preempted":
            facts.partition = None
            if ev.get("step") is not None:
                facts.resume_step = int(ev["step"])
        elif phase == "finalized":
            facts.finalized = True
    return state


def recover_optimization_driver(driver) -> Optional[Dict[str, Any]]:
    """Apply the journal's replayed state to a freshly constructed
    (resuming) OptimizationDriver: reconstruct the in-flight half of the
    trial store, re-seed the reservation table with pre-crash partitions,
    and queue an IDLE nudge per recovered partition so adopted-but-idle
    runners get work without a REG. Returns the reconstruction stats for
    the ``recovered`` journal event, or None when there is no journal to
    replay (telemetry off — artifact-only legacy resume)."""
    from maggy_tpu.trial import Trial

    if driver.telemetry.journal is None:
        return None
    state = replay_recovery_state(driver.telemetry.events())
    if not state.trials and not state.partitions:
        return None
    with driver._store_lock:
        already_final = {t.trial_id for t in driver._final_store}
    restored_inflight = 0
    requeued = 0
    restored_forks = 0
    held: Dict[int, str] = {}
    for facts in state.inflight():
        if facts.trial_id in already_final:
            # The FINAL's trial.json landed but its journal edge was lost
            # to the crash window: the artifact is authoritative — the
            # trial is done, not in flight.
            continue
        trial = Trial(facts.params, trial_type=facts.trial_type)
        if trial.trial_id != facts.trial_id:
            # Params did not round-trip the journal faithfully (exotic
            # non-JSON hparam types): reconstruction would mint a
            # different schedule entry — skip it and let the seeded
            # controller re-derive the config instead.
            driver._log("recovery: journaled params for trial {} do not "
                        "reproduce its id (got {}); leaving it to the "
                        "controller to re-derive".format(
                            facts.trial_id, trial.trial_id))
            continue
        with trial.lock:
            trial.status = Trial.SCHEDULED
            trial.run_epoch = facts.epoch
            trial.info_dict.update(facts.info)
            if facts.resume_step is not None:
                trial.info_dict["resume_step"] = facts.resume_step
            span = driver.telemetry.spans.span_id(trial.trial_id)
            if span is not None:
                trial.info_dict["span"] = span
        with driver._store_lock:
            driver._trial_store[trial.trial_id] = trial
        restored_inflight += 1
        if facts.info.get("forked_from") is not None:
            # The fork lineage rode the queued edge (forked_from +
            # resume_step in the journaled info), so a driver crash
            # cannot orphan a fork mid-flight: the reconstructed trial
            # re-dispatches resuming from the SAME fork point.
            restored_forks += 1
        if facts.partition is not None:
            # The pre-crash holder: restore the assignment so a live
            # runner's retried FINAL matches, and a dead one's silence
            # requeues via the ordinary slot-reclaim scan.
            held[facts.partition] = trial.trial_id
        else:
            with driver._store_lock:
                if trial.trial_id not in driver._requeue:
                    driver._requeue.append(trial.trial_id)
            requeued += 1
    res = driver.server.reservations
    for pid, cap in sorted(state.partitions.items()):
        res.restore(pid, trial_id=held.get(pid), capacity=cap)
    for pid in sorted(set(held) - set(state.partitions)):
        res.restore(pid, trial_id=held[pid])
    # Idle nudge: an adopted-but-idle runner never re-REGs — it just
    # keeps GET-polling — so nothing would ever assign it work. One IDLE
    # message per recovered partition (drained once the worker starts)
    # routes it through the ordinary assignment path; dead partitions'
    # nudges park work on them at worst one liveness window before the
    # loss scan reclaims it.
    recovered_pids = sorted(set(state.partitions) | set(held))
    for pid in recovered_pids:
        if held.get(pid) is None:
            driver.enqueue({"type": "IDLE", "partition_id": pid,
                            "last_trial": None})
    return {
        "inflight": restored_inflight,
        "held_partitions": len(held),
        "backlogged": requeued,
        "recovered_partitions": len(recovered_pids),
        "forks": restored_forks,
    }
