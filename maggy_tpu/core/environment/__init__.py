from maggy_tpu.core.environment.abstractenvironment import AbstractEnv
from maggy_tpu.core.environment.singleton import EnvSing

__all__ = ["AbstractEnv", "EnvSing"]
