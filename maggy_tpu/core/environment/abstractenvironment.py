"""Environment abstraction: filesystem + platform services.

Parity: reference `maggy/core/environment/abstractenvironment.py:20-169`
(27-method interface over HDFS/Hopsworks). Redesigned: a compact fs/registry
interface whose default implementation is a LOCAL filesystem that works out
of the box — unlike the reference, which hard-fails outside Hopsworks
(`singleton.py:36-39`). A GCS implementation slots in for TPU pods (shared
experiment dirs across VMs).
"""

from __future__ import annotations

import json
import os
import shutil
import socket
from abc import ABC
from typing import Any, Dict, List, Optional


class AbstractEnv(ABC):
    """Filesystem + experiment-registry services used by driver & executors."""

    @staticmethod
    def _chaos_write_check(path: str) -> None:
        """Fault-injection seam (maggy_tpu.chaos ``env_write_fail``):
        raises OSError when an armed chaos engine decides this write
        fails transiently. Unarmed (the default), one global read."""
        from maggy_tpu.chaos.injectors import active_engine

        engine = active_engine()
        if engine is not None:
            engine.on_env_write(path)

    #: True when dump() is a cheap local write (sub-ms): latency-sensitive
    #: callers (the driver's inline FINAL fast path runs on the RPC event
    #: loop) consult this before persisting artifacts inline; remote
    #: object-store backends keep their writes off that thread.
    FAST_LOCAL_WRITES = False

    # ------------------------------------------------------------------- fs

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def mkdir(self, path: str) -> None:
        raise NotImplementedError

    def dump(self, data: str, path: str) -> None:
        raise NotImplementedError

    def exclusive_create(self, data: str, path: str) -> bool:
        """Create ``path`` with ``data`` ONLY if it does not already exist;
        returns False when another writer got there first. This is the
        lost-update-proof primitive concurrent registrations need — dump()'s
        atomicity prevents torn files, not last-writer-wins. Default is a
        best-effort exists+dump (still TOCTOU-prone); LocalEnv and GCSEnv
        override with real exclusive primitives."""
        if self.exists(path):
            return False
        self.dump(data, path)
        return True

    def load(self, path: str) -> str:
        raise NotImplementedError

    def open_file(self, path: str, mode: str = "r"):
        raise NotImplementedError

    def isdir(self, path: str) -> bool:
        raise NotImplementedError

    def ls(self, path: str) -> List[str]:
        raise NotImplementedError

    def delete(self, path: str, recursive: bool = False) -> None:
        raise NotImplementedError

    def sweep_tmp_files(self, path: str) -> int:
        """Collect write artifacts orphaned by a crashed run under
        ``path``. Default: nothing to do (backends whose dump() writes
        in one shot leave no artifacts)."""
        return 0

    # -------------------------------------------------------------- registry

    def experiment_base_dir(self) -> str:
        raise NotImplementedError

    def register_experiment(self, app_id: str, run_id: int, meta: Dict[str, Any],
                            base_dir: Optional[str] = None) -> str:
        """Create the experiment directory and persist initial metadata;
        returns the experiment dir (reference `util.py:264-279`)."""
        raise NotImplementedError

    def update_experiment(self, exp_dir: str, meta: Dict[str, Any]) -> None:
        raise NotImplementedError

    def finalize_experiment(self, exp_dir: str, state: str, meta: Dict[str, Any]) -> None:
        raise NotImplementedError

    # ------------------------------------------------------------ networking

    def get_ip_address(self) -> str:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("10.255.255.255", 1))
            ip = s.getsockname()[0]
        except OSError:
            ip = "127.0.0.1"
        finally:
            s.close()
        return ip

    def connect_host(self, server, host: Optional[str] = None,
                     port: int = 0):
        """Bind the control-plane server and return (host, port). Platform
        implementations may additionally publish the address (the reference
        POSTs it to Hopsworks REST, `hopsworks.py:129-178`). ``port``
        pins the bind (crash-only recovery rebinds the pre-crash port so
        surviving runners' reconnects land); 0 = ephemeral."""
        return server.start(host=host or "127.0.0.1", port=port)

    @staticmethod
    def str_or_byte(value):
        return value.decode() if isinstance(value, bytes) else value


class LocalEnv(AbstractEnv):
    """Local-filesystem environment (default). Experiment artifacts live
    under ``base_dir`` (default ``~/maggy_tpu_experiments`` or
    ``$MAGGY_TPU_BASE_DIR``)."""

    FAST_LOCAL_WRITES = True

    def __init__(self, base_dir: Optional[str] = None):
        self.base_dir = base_dir or os.environ.get(
            "MAGGY_TPU_BASE_DIR",
            os.path.join(os.path.expanduser("~"), "maggy_tpu_experiments"),
        )

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def mkdir(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def dump(self, data: str, path: str) -> None:
        # Atomic (tmp + rename): artifacts like trial.json and the pruner
        # bracket state are read back by `resume=True` — a hard kill
        # mid-write must leave old-or-nothing, never a torn file.
        self._chaos_write_check(path)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        import threading

        tmp = "{}.tmp.{}.{}".format(path, os.getpid(), threading.get_ident())
        try:
            with open(tmp, "w") as f:
                f.write(data)
            os.replace(tmp, path)
        except BaseException:
            # Don't orphan the tmp file on a failed write/replace; a hard
            # kill can still leave one — sweep_tmp_files() at resume
            # startup collects those.
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def exclusive_create(self, data: str, path: str) -> bool:
        # Write a private tmp file fully, then os.link() it into place:
        # link is BOTH exclusive (EEXIST when the target exists — the
        # kernel arbitrates, exactly one of N concurrent creators wins,
        # unlike dump()'s os.replace which silently overwrites) AND
        # atomic (the target is complete-or-absent; a kill mid-write can
        # never leave a torn file squatting on the slot the way a direct
        # O_CREAT|O_EXCL write could).
        import threading

        self._chaos_write_check(path)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = "{}.tmp.{}.{}".format(path, os.getpid(), threading.get_ident())
        try:
            with open(tmp, "w") as f:
                f.write(data)
            try:
                os.link(tmp, path)
            except FileExistsError:
                return False
            except OSError:
                # Filesystem without hard links: fall back to O_EXCL (still
                # exclusive; torn-file window accepted on such fs only).
                try:
                    fd = os.open(path,
                                 os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o644)
                except FileExistsError:
                    return False
                with os.fdopen(fd, "w") as f:
                    f.write(data)
            return True
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def sweep_tmp_files(self, path: str, grace_s: float = 120.0) -> int:
        """Remove orphaned atomic-dump tmp files ('<name>.tmp.<pid>.<tid>')
        left by processes that died between write and rename. Called at
        resume startup. Only files older than ``grace_s`` are collected: a
        LIVE writer (e.g. a runner that outlived a crashed driver) holds
        its tmp for milliseconds between write and rename, so the age
        check — not the pid/tid suffix, which only prevents name
        collisions — is what makes the sweep safe against unlinking a
        write in flight."""
        import glob as _glob
        import time as _time

        removed = 0
        cutoff = _time.time() - grace_s
        for tmp in _glob.glob(os.path.join(path, "**", "*.tmp.*"),
                              recursive=True):
            try:
                if os.path.getmtime(tmp) < cutoff:
                    os.unlink(tmp)
                    removed += 1
            except OSError:
                pass
        return removed

    def load(self, path: str) -> str:
        with open(path) as f:
            return f.read()

    def open_file(self, path: str, mode: str = "r"):
        if "w" in mode or "a" in mode:
            os.makedirs(os.path.dirname(path), exist_ok=True)
        return open(path, mode)

    def isdir(self, path: str) -> bool:
        return os.path.isdir(path)

    def ls(self, path: str) -> List[str]:
        return sorted(os.listdir(path)) if os.path.isdir(path) else []

    def delete(self, path: str, recursive: bool = False) -> None:
        if os.path.isdir(path):
            if recursive:
                shutil.rmtree(path, ignore_errors=True)
            else:
                os.rmdir(path)
        elif os.path.exists(path):
            os.remove(path)

    def experiment_base_dir(self) -> str:
        return self.base_dir

    def register_experiment(self, app_id: str, run_id: int, meta: Dict[str, Any],
                            base_dir: Optional[str] = None) -> str:
        exp_dir = os.path.join(base_dir or self.base_dir, "{}_{}".format(app_id, run_id))
        self.mkdir(exp_dir)
        self.dump(json.dumps({**meta, "state": "RUNNING"}, indent=2, default=str),
                  os.path.join(exp_dir, "experiment.json"))
        return exp_dir

    def update_experiment(self, exp_dir: str, meta: Dict[str, Any]) -> None:
        path = os.path.join(exp_dir, "experiment.json")
        current = json.loads(self.load(path)) if self.exists(path) else {}
        current.update(meta)
        self.dump(json.dumps(current, indent=2, default=str), path)

    def finalize_experiment(self, exp_dir: str, state: str, meta: Dict[str, Any]) -> None:
        self.update_experiment(exp_dir, {**meta, "state": state})


class GCSEnv(LocalEnv):
    """GCS-backed environment for multi-host TPU pods: same interface over a
    ``gs://`` base dir via an fsspec filesystem (gcsfs by default).

    ``fs`` is injectable — tests drive the full contract against fsspec's
    in-memory filesystem; production omits it and gets gcsfs.
    """

    FAST_LOCAL_WRITES = False  # object-store round trips, not local fs

    def __init__(self, base_dir: str, fs=None):
        if not base_dir.startswith("gs://"):
            raise ValueError("GCSEnv requires a gs:// base dir")
        if fs is None:
            try:
                import gcsfs
            except ImportError as e:
                raise ImportError(
                    "GCSEnv requires gcsfs; install it or use LocalEnv with "
                    "an NFS-shared base dir."
                ) from e
            fs = gcsfs.GCSFileSystem()
        super().__init__(base_dir)
        self.fs = fs

    def exists(self, path: str) -> bool:
        return self.fs.exists(path)

    def mkdir(self, path: str) -> None:
        # Real, not a no-op: GCS itself has no directories, but fsspec
        # emulates them (placeholder entries) so isdir()/ls() on a freshly
        # registered experiment dir behave like LocalEnv before the first
        # object lands in it.
        self.fs.makedirs(path, exist_ok=True)

    def dump(self, data: str, path: str) -> None:
        # One-shot object write: object stores commit the whole object on
        # close (old-or-nothing), so no tmp+rename dance — and no rename
        # exists on GCS anyway. sweep_tmp_files() stays the base no-op.
        self._chaos_write_check(path)
        with self.fs.open(path, "w") as f:
            f.write(data)

    def exclusive_create(self, data: str, path: str) -> bool:
        # if_generation_match=0 is GCS's server-side O_CREAT|O_EXCL: the
        # write commits only if no generation (object) exists, so exactly
        # one concurrent creator wins. Backends without precondition
        # support (fsspec's memory fs in tests) silently ignore the kwarg,
        # which is why the exists() pre-check stays: best-effort there,
        # bulletproof on real gcsfs.
        self._chaos_write_check(path)
        if self.fs.exists(path):
            return False
        try:
            with self.fs.open(path, "w", if_generation_match=0) as f:
                f.write(data)
        except FileExistsError:
            return False
        except (OSError, ValueError) as e:
            # gcsfs surfaces the 412 precondition failure in several
            # shapes; "generation"/"precondition" in the message means we
            # LOST the race, anything else is a real I/O error.
            msg = str(e).lower()
            if "generation" in msg or "precondition" in msg or "412" in msg:
                return False
            raise
        except TypeError:
            # fs rejects the precondition kwarg outright: plain write
            # guarded only by the exists() check above.
            with self.fs.open(path, "w") as f:
                f.write(data)
        return True

    def load(self, path: str) -> str:
        with self.fs.open(path, "r") as f:
            return AbstractEnv.str_or_byte(f.read())

    def open_file(self, path: str, mode: str = "r"):
        return self.fs.open(path, mode)

    def isdir(self, path: str) -> bool:
        return self.fs.isdir(path)

    def ls(self, path: str) -> List[str]:
        # fsspec returns full object paths; the AbstractEnv contract (and
        # util.build_summary) expects bare entry names like LocalEnv, and
        # [] for a missing path.
        import os as _os

        if not self.fs.isdir(path):
            return []
        return sorted(
            _os.path.basename(AbstractEnv.str_or_byte(
                p["name"] if isinstance(p, dict) else p).rstrip("/"))
            for p in self.fs.ls(path)
        )

    def delete(self, path: str, recursive: bool = False) -> None:
        if self.fs.exists(path):
            self.fs.rm(path, recursive=recursive)

    def sweep_tmp_files(self, path: str) -> int:
        # Explicit no-op (NOT LocalEnv's local-glob sweep, which would be
        # path-typed wrong for gs:// dirs): GCS dump() writes one-shot
        # objects, so there are never tmp artifacts to collect.
        return 0
