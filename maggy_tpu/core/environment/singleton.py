"""Environment singleton.

Parity: reference `maggy/core/environment/singleton.py` — but where the
reference refuses to run outside Hopsworks (`singleton.py:36-39`), the
default here is a working LocalEnv; GCS is selected by a ``gs://`` base dir
(SURVEY.md §7.1 calls this out as a gap not to replicate).
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from maggy_tpu.core.environment.abstractenvironment import AbstractEnv, GCSEnv, LocalEnv


class EnvSing:
    _instance: Optional[AbstractEnv] = None
    _lock = threading.Lock()

    @classmethod
    def get_instance(cls) -> AbstractEnv:
        with cls._lock:
            if cls._instance is None:
                base = os.environ.get("MAGGY_TPU_BASE_DIR", "")
                cls._instance = GCSEnv(base) if base.startswith("gs://") else LocalEnv()
            return cls._instance

    @classmethod
    def set_instance(cls, env: AbstractEnv) -> None:
        with cls._lock:
            cls._instance = env

    @classmethod
    def reset(cls) -> None:
        with cls._lock:
            cls._instance = None
