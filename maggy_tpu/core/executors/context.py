"""Per-trial execution context handed to user train functions.

The reference passes only a ``reporter`` into ``train_fn`` (introspected at
`trial_executor.py:142-146`); trial state lives in hidden module globals and
a promoted ASHA trial re-runs from scratch (the wanted-but-missing
optimization noted at reference `hyperband.py:325-326`). Here a trial can
opt into a ``ctx`` argument the same way it opts into ``reporter`` — by
naming it in its signature — and gets:

- its identity (``trial_id``, ``trial_dir``, ``exp_dir``, raw ``params``),
- the multi-fidelity ``budget`` and, for promoted trials, the
  ``parent_trial_id`` (carried in the scheduler's ``info_dict`` and shipped
  with the TRIAL assignment),
- orbax checkpointing scoped to the trial dir (``save_checkpoint`` /
  ``restore_checkpoint``), and
- ``restore_parent(abstract_state)`` — warm-start from the parent's last
  checkpoint, turning ASHA/Hyperband promotions into *continuations*
  instead of re-runs (a direct trials/hour win on TPU, where re-training
  the low-budget prefix wastes MXU time).
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, Optional


def _note_ckpt(**fields: Any) -> None:
    """Route checkpoint I/O timing into the current trial's RunnerStats
    via the warm trial scope (same channel note_compile rides). Never
    fatal: checkpoint accounting must not break checkpointing itself."""
    try:
        from maggy_tpu.train.warm import note_ckpt

        note_ckpt(**fields)
    except Exception:  # noqa: BLE001 - accounting is best-effort
        pass


def info_needs_fresh_state(info: Dict[str, Any]) -> bool:
    """Does a trial's assignment ``info`` dict mark it as CONTINUING
    saved state (preemption resume / promoted parent / checkpoint
    fork)? The single home of this rule: ``TrialContext.
    needs_fresh_state`` and the executor's warm trial scope both
    consult it — widening it in one place but not the other would
    silently re-enable retired-buffer donation for exactly the trials
    that must restore a checkpoint instead. The fork case keeps the
    COMPILED step (the warm slot's executables are program identity,
    not values) while dropping the retired buffers the staged
    checkpoint replaces."""
    return (info.get("resume_step") is not None
            or info.get("parent") is not None
            or info.get("forked_from") is not None)


class TrialContext:
    def __init__(
        self,
        trial_id: str,
        trial_dir: str,
        exp_dir: str,
        params: Dict[str, Any],
        info: Optional[Dict[str, Any]] = None,
    ):
        self.trial_id = trial_id
        self.trial_dir = trial_dir
        self.exp_dir = exp_dir
        self.params = dict(params)
        self.info: Dict[str, Any] = dict(info or {})
        self._checkpointer = None

    # ----------------------------------------------------------- identity
    @property
    def budget(self) -> Optional[float]:
        """Multi-fidelity budget for this run (None if single-fidelity)."""
        b = self.info.get("run_budget", self.params.get("budget"))
        return None if b in (None, 0) else b

    @property
    def parent_trial_id(self) -> Optional[str]:
        """For a promoted ASHA/Hyperband trial: the trial it continues."""
        return self.info.get("parent")

    @property
    def forked_from(self) -> Optional[Dict[str, Any]]:
        """Checkpoint-fork lineage stamped by the driver (config.fork):
        ``{"trial": <parent id>, "step": <checkpoint step>}`` when this
        trial was dispatched to resume from another trial's checkpoint
        (ASHA promotion, PBT exploit/continue, BO near-duplicate). The
        executor stages the parent's checkpoint into THIS trial's dir
        before the train fn runs, so ``restore_checkpoint`` +
        ``resume_step`` work exactly like a same-trial preemption
        resume. None = from-scratch run."""
        fork = self.info.get("forked_from")
        return dict(fork) if fork else None

    def stage_fork(self) -> Optional[int]:
        """Stage the forked-from parent's checkpoint into this trial's
        dir (idempotent; see train/checkpoint.fork_checkpoint). Returns
        the staged step, or None when there is nothing to fork. The
        executor calls this before the train fn; it is exposed on the
        ctx so library code can re-stage explicitly."""
        fork = self.info.get("forked_from")
        if not fork or not fork.get("trial"):
            return None
        from maggy_tpu.core.environment import EnvSing
        from maggy_tpu.train.checkpoint import fork_checkpoint

        return fork_checkpoint(EnvSing.get_instance(), self.exp_dir,
                               fork["trial"], self.trial_dir,
                               step=fork.get("step"))

    @property
    def resume_step(self) -> Optional[int]:
        """For a preempted-then-requeued trial: the checkpoint step it was
        preempted at (restore via ``restore_checkpoint`` and continue from
        ``resume_step + 1``). None = fresh run (or it never checkpointed
        before preemption — requeue-from-scratch)."""
        step = self.info.get("resume_step")
        return None if step is None else int(step)

    @property
    def gang(self):
        """For a gang-scheduled multi-chip trial: the assembled
        ``maggy_tpu.gang.GangContext`` (member chips, mesh axes,
        strategy, ``build_mesh()``/``sharding_env()`` helpers) the
        driver stamped into the assignment info. None for 1-chip
        trials — a train function can branch on it to run sharded or
        single-device."""
        info = self.info.get("gang")
        if not info:
            return None
        from maggy_tpu.gang import GangContext

        # The member's own partition rides along so a REMOTE gang can
        # resolve this process's jax.distributed process id.
        return GangContext({**info, "partition": self.info.get("partition")})

    @property
    def needs_fresh_state(self) -> bool:
        """True when this trial CONTINUES saved state — a preemption
        resume (``resume_step``) or an ASHA/Hyperband promotion
        (``parent_trial_id``). The warm harness (train/warm.py) consults
        the same condition: such a trial must restore its checkpoint into
        freshly initialized buffers, never consume the previous trial's
        retired ones — the executor's trial scope arms ``fresh_state`` so
        the warm slot's donation path is skipped while the compiled
        executables are still reused."""
        return info_needs_fresh_state(self.info)

    # ------------------------------------------------------- checkpointing
    def checkpointer(self):
        if self._checkpointer is None:
            from maggy_tpu.train.checkpoint import TrialCheckpointer

            self._checkpointer = TrialCheckpointer(self.trial_dir)
        return self._checkpointer

    def save_checkpoint(self, step: int, state: Any) -> None:
        t0 = time.perf_counter()
        try:
            self.checkpointer().save(step, state)
        finally:
            _note_ckpt(save_ms=(time.perf_counter() - t0) * 1e3, saves=1)

    def restore_checkpoint(self, abstract_state: Any) -> Optional[Any]:
        """Resume this trial's own latest checkpoint (None if absent)."""
        if not os.path.isdir(os.path.join(self.trial_dir, "checkpoints")):
            return None
        t0 = time.perf_counter()
        try:
            return self.checkpointer().restore(abstract_state)
        finally:
            _note_ckpt(restore_ms=(time.perf_counter() - t0) * 1e3,
                       restores=1)

    def restore_parent(self, abstract_state: Any) -> Optional[Any]:
        """Warm-start from the promoted parent's checkpoint (None if this
        trial has no parent or the parent saved nothing)."""
        parent = self.parent_trial_id
        if parent is None:
            return None
        from maggy_tpu.train.checkpoint import restore_parent_state

        t0 = time.perf_counter()
        try:
            return restore_parent_state(self.exp_dir, parent, abstract_state)
        finally:
            _note_ckpt(restore_ms=(time.perf_counter() - t0) * 1e3,
                       restores=1)

    def close(self) -> None:
        if self._checkpointer is not None:
            self._checkpointer.close()
            self._checkpointer = None


class LaneSet:
    """The train fn's view of a vectorized K-lane block (config.vmap_lanes
    > 1): the per-lane hyperparameters to stack into a `VmapTrainer`, the
    per-lane stop signals the driver's early-stop rule raises, and the
    per-lane retirement hook that sends each lane's own FINAL. A train fn
    opts in by declaring a ``lanes`` keyword parameter; without it the
    executor degrades the block to sequential scalar runs."""

    def __init__(self, lanes, reporter, finalize):
        # Lane descriptors in lane order: {"trial_id", "lane", "params",
        # "span", "epoch", "fork_lane"} (from the block's TRIAL info).
        self.lanes = [dict(entry) for entry in lanes]
        self.reporter = reporter
        self._finalize = finalize
        self._by_id = {entry["trial_id"]: i
                       for i, entry in enumerate(self.lanes)}

    def __len__(self) -> int:
        return len(self.lanes)

    @property
    def trial_ids(self):
        return [entry["trial_id"] for entry in self.lanes]

    @property
    def hparams(self):
        """Per-lane param dicts, lane order — feed to VmapTrainer (the
        caller picks which keys form the stacked hyperparameter axis)."""
        return [dict(entry.get("params") or {}) for entry in self.lanes]

    def lane_of(self, trial_id: str) -> int:
        return self._by_id[trial_id]

    def take_stopped(self):
        """Lane INDICES newly flagged for early stop (each exactly once):
        poll between steps, mask them (`VmapTrainer.mask_lane`), then
        `retire()` each with its final metric."""
        return [self._by_id[tid]
                for tid in self.reporter.take_stopped_lanes()
                if tid in self._by_id]

    def retire(self, lane: int, metric) -> None:
        """Send lane ``lane``'s FINAL now (mid-block): its span closes at
        the moment it stopped contributing, so masked-lane idle time is
        attributable (goodput ``lane_idle``). Lanes never retired here are
        finalized by the executor when the train fn returns."""
        self._finalize(self.lanes[lane], metric)
