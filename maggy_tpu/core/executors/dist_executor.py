"""Distributed-training executor: one SPMD process of the training world.

Parity: reference `maggy/core/executors/dist_executor.py:40-224` — register +
heartbeat (logs), `await_reservations` barrier, coordinator rendezvous
(TORCH_CONFIG -> DIST_CONFIG), environment setup, process-group init,
model wrapping, train_fn invocation, FINAL metric.

Redesign (SURVEY.md §5.8): `dist.init_process_group("nccl")` + DDP becomes
`jax.distributed.initialize(coordinator, num_processes, process_id)` +
a `ShardingEnv` (mesh + named shardings). Gradient all-reduce is emitted by
GSPMD inside the user's jit step — there is no wrapper object. Seeding
mirrors the reference's determinism setup (`dist_executor.py:208-214`) via a
fixed `jax.random.PRNGKey` handed through the env.
"""

from __future__ import annotations

import inspect
import os
import traceback
from typing import Callable, Optional, Tuple

from maggy_tpu import util
from maggy_tpu.core.environment import EnvSing
from maggy_tpu.core.reporter import Reporter
from maggy_tpu.core.rpc import Client
from maggy_tpu.parallel.mesh import ShardingEnv, make_mesh


class DistExecutor:
    """Module-level class: picklable for process pools."""

    def __init__(
        self,
        server_addr: Tuple[str, int],
        secret: str,
        hb_interval: float,
        exp_dir: str,
        train_fn: Callable,
        config,
        num_workers: int,
        profile: bool = False,
    ):
        self.server_addr = server_addr
        self.secret = secret
        self.hb_interval = hb_interval
        self.exp_dir = exp_dir
        self.train_fn = train_fn
        self.config = config
        self.num_workers = num_workers
        self.profile = profile or bool(getattr(config, "profile", False))

    def __call__(self, partition_id: int) -> None:
        env = EnvSing.get_instance()
        util.apply_platform_env()
        util.enable_compile_cache()
        task_attempt = int(os.environ.get("MAGGY_TPU_TASK_ATTEMPT", "0"))
        reporter = Reporter(
            log_file="{}/worker_{}_{}.log".format(self.exp_dir, partition_id, task_attempt)
        )
        reporter.reset(trial_id="dist")
        client = Client(self.server_addr, partition_id, task_attempt,
                        self.hb_interval, self.secret)
        # Worker-side telemetry, same channel as trial runners: broadcast
        # cadence + heartbeat RTT + memory, delta-encoded onto heartbeats.
        # The whole job is one "trial" from the stats' point of view.
        from maggy_tpu.telemetry.runnerstats import RunnerStats

        stats = RunnerStats()
        stats.trial_start("dist")
        reporter.stats = stats
        client.runner_stats = stats
        try:
            # Advertise our coordinator endpoint; worker 0's is the rendezvous
            # address (reference `rpc.py:409-416`).
            coord_port = int(os.environ.get("MAGGY_TPU_COORD_PORT", "7733"))
            host = env.get_ip_address()
            client.register(host_port="{}:{}".format(host, coord_port))
            client.start_heartbeat(reporter)
            import time as _time

            t_barrier = _time.monotonic()
            client.await_reservations()
            dist_config = client.get_dist_config()
            # Registration-barrier + coordinator-rendezvous latency, as the
            # WORKER saw it; shipped on FINAL so the driver's telemetry can
            # histogram world bring-up without instrumenting each host.
            rendezvous_ms = (_time.monotonic() - t_barrier) * 1e3

            sharding_env = self._init_cluster(dist_config, partition_id, reporter)
            if self.profile:
                import jax

                logdir = "{}/tensorboard_worker{}".format(self.exp_dir, partition_id)
                with jax.profiler.trace(logdir):
                    metric = self._run_train_fn(sharding_env, reporter)
            else:
                metric = self._run_train_fn(sharding_env, reporter)
            client.finalize_metric(
                metric, reporter,
                extra={"telem": {"rendezvous_ms": round(rendezvous_ms, 3)}})
        except Exception:  # noqa: BLE001
            reporter.log("Distributed worker {} failed:\n{}".format(
                partition_id, traceback.format_exc()))
            with reporter.lock:
                client._request({"type": "FINAL", "trial_id": "dist", "value": None,
                                 "error": True, "logs": reporter.get_data()["logs"]})
                reporter.reset()
            raise
        finally:
            client.stop()

    def _init_cluster(self, dist_config, partition_id: int, reporter) -> ShardingEnv:
        """Bring up the JAX world and build the mesh (replaces
        `_init_cluster`'s NCCL setup, reference `dist_executor.py:197-223`)."""
        import jax

        num_processes = dist_config["num_processes"]
        multiprocess = (
            num_processes > 1
            and os.environ.get("MAGGY_TPU_DIST_INIT", "1") == "1"
            and not _in_thread_pool()
        )
        if multiprocess:
            jax.distributed.initialize(
                coordinator_address=dist_config["coordinator_address"],
                num_processes=num_processes,
                process_id=partition_id,
            )
            reporter.log("jax.distributed initialized: {}/{} at {}".format(
                partition_id, num_processes, dist_config["coordinator_address"]))
        mesh = make_mesh(self.config.mesh_shape or {})
        return ShardingEnv(
            mesh=mesh,
            process_index=jax.process_index() if multiprocess else partition_id,
            process_count=num_processes,
        )

    def _run_train_fn(self, sharding_env: ShardingEnv, reporter) -> Optional[float]:
        kwargs = {}
        sig = inspect.signature(self.train_fn).parameters
        if "model" in sig:
            kwargs["model"] = self.config.model
        if "train_set" in sig:
            kwargs["train_set"] = self.config.train_set
        if "test_set" in sig:
            kwargs["test_set"] = self.config.test_set
        if "sharding_env" in sig:
            kwargs["sharding_env"] = sharding_env
        if "reporter" in sig:
            kwargs["reporter"] = reporter
        retval = self.train_fn(**kwargs)
        if isinstance(retval, dict):
            return float(retval.get("metric", next(iter(retval.values()))))
        return float(retval) if retval is not None else None


def _in_thread_pool() -> bool:
    """True when running inside a ThreadRunnerPool (workers share one JAX
    runtime; per-process distributed init is impossible)."""
    import threading

    return threading.current_thread().name.startswith("runner-")


def dist_executor_fn(**kwargs) -> DistExecutor:
    return DistExecutor(**kwargs)
