"""Trial-runner executor loop for HPO / ablation experiments.

Parity: reference `maggy/core/executors/trial_executor.py:32-171` — the
wrapper each worker runs: connect client -> register -> start heartbeat ->
loop {get_suggestion -> prepare trial dir + .hparams.json -> call
train_fn(**params[, reporter]) -> validate/persist return -> catch
EarlyStopException and use its carried metric -> finalize_metric} until
GSTOP; ablation mode resolves declarative ablation specs before the call
(:103-108).

Redesign notes:
- the hand-off is pipelined (config.prefetch, default on): finalize_metric
  banks the next assignment piggybacked on the FINAL reply, so the
  get_suggestion at the top of the loop is usually wire-free — GET polling
  remains the fallback (first trial after registration, idle wake-ups,
  requeues).
- `builtins.print` is NOT patched by default (reference :71-81): the
  reporter tees to the runner log explicitly; user code gets the reporter
  for logging. ``ship_prints=True`` opts back into the reference behavior
  via a thread-scoped tee (prints inside train_fn also land in the
  reporter log channel and stream to the driver/monitor on heartbeats).
- per-trial TPU device pinning happens in the runner pool (process-level),
  not here: JAX binds devices at process start.
"""

from __future__ import annotations

import inspect
import os
import threading
import traceback
from typing import Callable, Optional, Tuple

# The JAX profiler allows one active trace per process.
_PROFILE_LOCK = threading.Lock()

# ---- opt-in print shipping (ship_prints=True) ----
# builtins.print is process-global but runners may be THREADS sharing it,
# so the installed tee dispatches through a thread-local: only the thread
# currently inside a shipping trial has a reporter registered; every other
# thread's prints pass through untouched. Installed once, never uninstalled
# (the pass-through is free), so concurrent experiments can't race the
# patch the way the reference's per-executor patching could.
_print_ship = threading.local()
_print_tee_lock = threading.Lock()
_orig_print = None


def _install_print_tee() -> None:
    global _orig_print
    with _print_tee_lock:
        if _orig_print is not None:
            return
        import builtins
        import sys

        _orig_print = builtins.print

        def tee_print(*args, **kwargs):
            _orig_print(*args, **kwargs)
            reporter = getattr(_print_ship, "reporter", None)
            if reporter is not None and kwargs.get("file") in (None, sys.stdout):
                try:
                    reporter.log(
                        str(kwargs.get("sep", " ")).join(str(a) for a in args),
                        verbose=False)
                except Exception:  # noqa: BLE001 - shipping must never break print
                    pass

        builtins.print = tee_print

from maggy_tpu import util
from maggy_tpu.core.environment import EnvSing
from maggy_tpu.core.reporter import Reporter
from maggy_tpu.core.rpc import Client
from maggy_tpu.exceptions import EarlyStopException


class TrialExecutor:
    """The worker each runner executes; a module-level class so process
    pools can pickle it (``train_fn`` must then be module-level too)."""

    def __init__(
        self,
        server_addr: Tuple[str, int],
        secret: str,
        hb_interval: float,
        exp_dir: str,
        optimization_key: str,
        train_fn: Callable,
        trial_type: str = "optimization",
        ablation_resolver: Optional[Callable] = None,
        profile: bool = False,
        ship_prints: bool = False,
        warm_start: bool = True,
        host_port: Optional[str] = None,
    ):
        self.server_addr = server_addr
        self.secret = secret
        self.hb_interval = hb_interval
        self.exp_dir = exp_dir
        self.optimization_key = optimization_key
        self.train_fn = train_fn
        self.trial_type = trial_type
        self.ablation_resolver = ablation_resolver
        self.profile = profile
        self.ship_prints = ship_prints
        self.warm_start = warm_start
        # Advertised "host:port" this runner can be reached on for
        # remote-gang rendezvous (a fleet agent's reserved coordinator
        # address). None for in-process runners — its presence in the
        # REG record is exactly how the driver tells a remote member
        # from a thread runner when stamping gang rendezvous info.
        self.host_port = host_port

    def __call__(self, partition_id: int) -> None:
        env = EnvSing.get_instance()
        exp_dir = self.exp_dir
        util.apply_platform_env()
        # Shared persistent XLA cache: successive trials (and sibling runner
        # processes) with recurring shapes skip recompilation (SURVEY.md
        # §7.3 "compile-cache churn").
        util.enable_compile_cache()
        # Warm-state harness: count warm-slot + persistent-cache events
        # through jax.monitoring so the journal carries the compile-once
        # hit rates (train/warm.py; never fatal).
        from maggy_tpu.train import warm

        warm.install_monitoring_listener()
        task_attempt = int(os.environ.get("MAGGY_TPU_TASK_ATTEMPT", "0"))
        reporter = Reporter(
            log_file="{}/executor_{}_{}.log".format(exp_dir, partition_id, task_attempt)
        )
        client = Client(self.server_addr, partition_id, task_attempt,
                        self.hb_interval, self.secret)
        # Runner-side telemetry: broadcast cadence + time-to-first-metric
        # feed in from the reporter, heartbeat RTT from the client, and
        # the client piggybacks the delta-encoded buffer on its METRIC
        # heartbeats (no new socket; driver merges it into the journal).
        from maggy_tpu.telemetry.runnerstats import RunnerStats

        stats = RunnerStats()
        reporter.stats = stats
        client.runner_stats = stats
        try:
            capacity = os.environ.get("MAGGY_TPU_CAPACITY")
            client.register(host_port=self.host_port,
                            capacity=int(capacity) if capacity else None)
            client.start_heartbeat(reporter)
            sig_params = inspect.signature(self.train_fn).parameters
            wants_reporter = "reporter" in sig_params
            wants_ctx = "ctx" in sig_params

            while not client.done:
                trial_id, params = client.get_suggestion()
                if trial_id is None:
                    break
                from maggy_tpu.core.rpc import RESIZE

                if trial_id == RESIZE:
                    # Elastic pool: exit so the dispatcher respawns this
                    # partition pinned to params["chips"] chips (the pin
                    # must precede backend init — no in-place resize).
                    resize_file = os.environ.get("MAGGY_TPU_RESIZE_FILE")
                    if resize_file:
                        import json as _json

                        with open(resize_file, "w") as f:
                            _json.dump({"chips": params["chips"]}, f)
                    reporter.log("resizing to {} chip(s); runner exiting "
                                 "for respawn".format(params["chips"]))
                    break
                if client.last_info.get("gang_role") == "member":
                    # Remote-gang MEMBER program: join the
                    # jax.distributed rendezvous and run the same SPMD
                    # program as the leader; only the leader reports and
                    # finalizes, so this path sends no FINAL and loops
                    # straight back to polling.
                    self._run_gang_member(trial_id, params, client,
                                          reporter)
                    continue
                if (client.last_info or {}).get("vmap_block"):
                    # Vectorized K-lane block (config.vmap_lanes): one
                    # delivery, K trials trained in lockstep as one
                    # vmapped program — or sequentially when the train fn
                    # doesn't take a ``lanes`` kwarg. Sends one FINAL per
                    # lane; the loop resumes polling after the last.
                    self._run_vmap_block(trial_id, params, client,
                                         reporter, stats, env, exp_dir,
                                         sig_params)
                    continue
                trial_dir = "{}/{}".format(exp_dir, trial_id)
                env.mkdir(trial_dir)
                env.dump(util.json_dumps_safe(params), trial_dir + "/.hparams.json")
                # The driver-minted telemetry span rides the TRIAL info;
                # arming the reporter with it makes every METRIC/FINAL this
                # trial sends attributable to its span timeline.
                reporter.reset(trial_id=trial_id,
                               span=client.last_info.get("span"))
                stats.trial_start(trial_id)
                try:
                    # Per-trial TensorBoard logdir + hparams record
                    # (reference `trial_executor.py:122-133`).
                    from maggy_tpu import tensorboard as tb

                    tb._register(os.path.join(trial_dir, "tensorboard"))
                    tb.write_hparams(params)
                except Exception:  # noqa: BLE001 - TB must never kill a trial
                    pass

                call_params = dict(params)
                if self.trial_type == "ablation":
                    # Declarative ablation spec -> concrete generators
                    # (replaces the reference's pickled callables,
                    # `loco.py:224-259`; SURVEY.md §7 hard part 3).
                    call_params = self.ablation_resolver(call_params)
                ctx = None
                try:
                    if wants_reporter:
                        call_params["reporter"] = reporter
                    if wants_ctx:
                        from maggy_tpu.core.executors.context import TrialContext

                        ctx = TrialContext(trial_id, trial_dir, exp_dir,
                                           params, client.last_info)
                        call_params["ctx"] = ctx
                    if (client.last_info or {}).get("forked_from"):
                        # Checkpoint fork (config.fork): stage the
                        # parent's checkpoint into THIS trial's dir so
                        # ctx.restore_checkpoint/resume_step behave
                        # exactly like a same-trial preemption resume.
                        # The load is timed into the trial's compile
                        # record (fork_load_ms) — the warm path keeps
                        # the compiled step while values come from the
                        # staged checkpoint, and the journal must show
                        # what the load cost.
                        self._stage_fork(ctx, trial_id, trial_dir,
                                         exp_dir, params, client,
                                         reporter, stats)
                    # Warm-slot lifecycle around the trial fn: inside the
                    # scope, Trainers default to the warm path
                    # (config.warm_start), compile telemetry lands in this
                    # runner's stats, and on exit the trial's state
                    # buffers retire into the warm slot for the next
                    # trial's donating re-init. A trial that RESUMES
                    # state (preemption resume / promoted parent) must
                    # restore its checkpoint, never touch retired
                    # buffers — fresh_state forbids their reuse.
                    from maggy_tpu.core.executors.context import \
                        info_needs_fresh_state

                    fresh = info_needs_fresh_state(client.last_info or {})
                    with warm.trial_scope(trial_id=trial_id,
                                          enabled=self.warm_start,
                                          stats=stats, fresh_state=fresh):
                        retval = self._run_trial(call_params, trial_dir,
                                                 reporter)
                    metric = util.handle_return_val(
                        retval, trial_dir, self.optimization_key, env
                    )
                    client.finalize_metric(metric, reporter)
                except EarlyStopException as e:
                    if reporter.take_preempt():
                        # Scheduler preemption (fleet rebalancing or a
                        # chaos preempt_trial fault), not an early-stop
                        # verdict: ack with the last checkpoint step so
                        # the driver requeues the trial to RESUME there
                        # (TrialCheckpointer layout under the trial dir;
                        # no checkpoint -> requeue-from-scratch).
                        from maggy_tpu.train.checkpoint import \
                            latest_checkpoint_step

                        step = latest_checkpoint_step(trial_dir)
                        reporter.log(
                            "Trial {} preempted{}.".format(
                                trial_id,
                                " at checkpoint step {}".format(step)
                                if step is not None
                                else " (no checkpoint; re-runs from "
                                     "scratch)"))
                        client.preempt_ack(trial_id, reporter, step=step)
                    else:
                        reporter.log(
                            "Trial {} early-stopped.".format(trial_id))
                        env.dump(
                            util.json_dumps_safe(
                                {self.optimization_key: e.metric}),
                            trial_dir + "/.outputs.json",
                        )
                        client.finalize_metric(e.metric, reporter)
                except Exception:  # noqa: BLE001 - report trial error, keep worker alive
                    reporter.log(
                        "Trial {} failed:\n{}".format(trial_id, traceback.format_exc())
                    )
                    # finalize_error, not a raw FINAL: the reply may
                    # piggyback this runner's next assignment (pipelined
                    # hand-off), which the next get_suggestion consumes
                    # without a round trip.
                    client.finalize_error(trial_id, reporter)
                finally:
                    stats.trial_end(trial_id)
                    if ctx is not None:
                        ctx.close()
        finally:
            try:
                # Close the last trial's TensorBoard session: writes its
                # hparams session_end record and flushes the event file
                # (short final trials would lose buffered events otherwise).
                from maggy_tpu import tensorboard as tb

                tb._close()
            except Exception:  # noqa: BLE001
                pass
            client.stop()


    def _stage_fork(self, ctx, trial_id: str, trial_dir: str,
                    exp_dir: str, params: dict, client, reporter,
                    stats) -> None:
        """Stage a forked trial's parent checkpoint into its trial dir
        (idempotent — a requeued fork re-stages to the SAME step). A
        staging failure (parent checkpoint vanished mid-flight, torn
        copy) downgrades the trial to a from-scratch run: the fork keys
        are stripped from the assignment info so ``ctx.resume_step``
        reads None and the train fn's resume branch never opens a
        checkpoint that is not there."""
        import time as _time

        fork = dict((client.last_info or {}).get("forked_from") or {})
        t0 = _time.monotonic()
        staged = None
        try:
            if ctx is not None:
                staged = ctx.stage_fork()
            else:
                from maggy_tpu.core.environment import EnvSing
                from maggy_tpu.train.checkpoint import fork_checkpoint

                staged = fork_checkpoint(
                    EnvSing.get_instance(), exp_dir, fork.get("trial"),
                    trial_dir, step=fork.get("step"))
        except Exception:  # noqa: BLE001 - a broken fork must not kill the trial
            staged = None
        if staged is None:
            reporter.log(
                "Trial {}: fork source {} step {} unavailable; running "
                "from scratch.".format(trial_id, fork.get("trial"),
                                       fork.get("step")))
            for key in ("forked_from", "resume_step"):
                client.last_info.pop(key, None)
                if ctx is not None:
                    ctx.info.pop(key, None)
            return
        stats.note_compile(fork_load_ms=(_time.monotonic() - t0) * 1e3,
                           forked=True)
        reporter.log("Trial {} forked from {} at checkpoint step {} "
                     "({}ms load).".format(
                         trial_id, fork.get("trial"), staged,
                         round((_time.monotonic() - t0) * 1e3, 1)))

    def _run_vmap_block(self, leader_id: str, params: dict, client,
                        reporter, stats, env, exp_dir: str,
                        sig_params) -> None:
        """Run a vectorized K-lane block: one delivery, K trials, one
        vmapped program (train/vmap.py). The train fn opts into
        vectorized execution by declaring a ``lanes`` keyword (a
        `LaneSet`); otherwise the block degrades to sequential scalar
        runs of each lane. Either way every lane sends its OWN FINAL —
        the last one (``last=True``) releases the partition and banks the
        piggybacked next assignment."""
        import traceback as _tb

        from maggy_tpu.core.executors.context import LaneSet
        from maggy_tpu.train import warm

        info = client.last_info or {}
        lane_descs = list((info.get("vmap_block") or {}).get("lanes") or ())
        if not lane_descs:
            # Defensive: a block stamp with no lanes — treat the leader
            # as a scalar trial failure rather than hanging the partition.
            client.finalize_error(leader_id, reporter)
            return
        for entry in lane_descs:
            lane_dir = "{}/{}".format(exp_dir, entry["trial_id"])
            env.mkdir(lane_dir)
            env.dump(util.json_dumps_safe(entry.get("params") or {}),
                     lane_dir + "/.hparams.json")
        if "lanes" not in sig_params:
            self._run_block_sequential(leader_id, lane_descs, client,
                                       reporter, stats, env, exp_dir,
                                       sig_params)
            return
        reporter.reset_lanes(leader_id, info.get("span"), lane_descs)
        stats.trial_start(leader_id)
        finalized = []

        def finalize(entry, metric, last=False, error=False):
            if entry["trial_id"] in finalized:
                return
            finalized.append(entry["trial_id"])
            if metric is not None and not error:
                lane_dir = "{}/{}".format(exp_dir, entry["trial_id"])
                env.dump(util.json_dumps_safe(
                    {self.optimization_key: metric}),
                    lane_dir + "/.outputs.json")
                env.dump(str(float(metric)), lane_dir + "/.metric")
            client.finalize_lane(entry["trial_id"], metric, reporter,
                                 lane=entry.get("lane", 0),
                                 block=leader_id,
                                 epoch=entry.get("epoch"),
                                 last=last, error=error)

        lanes = LaneSet(lane_descs, reporter, finalize)
        call_params = dict(params)
        call_params["lanes"] = lanes
        if "reporter" in sig_params:
            call_params["reporter"] = reporter
        try:
            with warm.trial_scope(trial_id=leader_id,
                                  enabled=self.warm_start, stats=stats,
                                  fresh_state=False):
                retval = self._run_trial(
                    call_params, "{}/{}".format(exp_dir, leader_id),
                    reporter)
            metrics = self._lane_metrics(retval, lane_descs)
            remaining = [e for e in lane_descs
                         if e["trial_id"] not in finalized]
            for i, entry in enumerate(remaining):
                finalize(entry, metrics.get(entry["trial_id"]),
                         last=(i == len(remaining) - 1))
            if not remaining:
                # Every lane was retired mid-block (all early-stopped):
                # the partition still holds the block — a release-shaped
                # FINAL (last=True, duplicate trial id the driver drops)
                # frees it and banks the piggybacked next assignment.
                client.finalize_lane(leader_id, None, reporter,
                                     lane=0, block=leader_id,
                                     epoch=lane_descs[0].get("epoch"),
                                     last=True)
        except EarlyStopException:
            if reporter.take_preempt():
                reporter.log("Block {} preempted; all lanes requeue."
                             .format(leader_id))
                client.preempt_ack(leader_id, reporter, step=None)
            else:
                # broadcast_lanes only raises on a whole-block stop
                # (preempt); anything else is a contract break — error
                # out the unfinalized lanes so none hangs the schedule.
                self._error_out_lanes(leader_id, lane_descs, finalized,
                                      client, reporter)
        except Exception:  # noqa: BLE001 - report block error, keep worker alive
            reporter.log("Block {} failed:\n{}".format(
                leader_id, _tb.format_exc()))
            self._error_out_lanes(leader_id, lane_descs, finalized,
                                  client, reporter)
        finally:
            stats.trial_end(leader_id)

    def _lane_metrics(self, retval, lane_descs) -> dict:
        """Normalize a lanes-capable train fn's return value to
        {trial_id: metric}: a dict keyed by lane trial id, or a sequence
        in lane order."""
        if isinstance(retval, dict):
            return {tid: retval.get(tid) for tid in
                    (e["trial_id"] for e in lane_descs)}
        if isinstance(retval, (list, tuple)) and \
                len(retval) == len(lane_descs):
            return {e["trial_id"]: float(v)
                    for e, v in zip(lane_descs, retval)}
        from maggy_tpu.exceptions import ReturnTypeError

        raise ReturnTypeError(self.optimization_key, retval)

    def _error_out_lanes(self, leader_id, lane_descs, finalized, client,
                         reporter) -> None:
        """FINAL every unfinalized lane as an error (last one releases
        the partition); if all lanes already finalized, send the
        release-shaped duplicate instead."""
        remaining = [e for e in lane_descs
                     if e["trial_id"] not in finalized]
        for i, entry in enumerate(remaining):
            finalized.append(entry["trial_id"])
            client.finalize_lane(entry["trial_id"], None, reporter,
                                 lane=entry.get("lane", 0),
                                 block=leader_id,
                                 epoch=entry.get("epoch"),
                                 last=(i == len(remaining) - 1),
                                 error=True)
        if not remaining:
            client.finalize_lane(leader_id, None, reporter, lane=0,
                                 block=leader_id,
                                 epoch=lane_descs[0].get("epoch"),
                                 last=True)

    def _run_block_sequential(self, leader_id: str, lane_descs, client,
                              reporter, stats, env, exp_dir: str,
                              sig_params) -> None:
        """Scalar fallback for a block whose train fn takes no ``lanes``
        kwarg: run each lane as an ordinary scalar trial on this runner,
        back to back — correctness degradation only, the block seam stays
        invisible to the user code (per-lane reporter resets, per-lane
        FINALs)."""
        import traceback as _tb

        from maggy_tpu.train import warm

        for i, entry in enumerate(lane_descs):
            tid = entry["trial_id"]
            last = i == len(lane_descs) - 1
            lane_dir = "{}/{}".format(exp_dir, tid)
            reporter.reset(trial_id=tid, span=entry.get("span"))
            stats.trial_start(tid)
            call_params = dict(entry.get("params") or {})
            if "reporter" in sig_params:
                call_params["reporter"] = reporter
            try:
                with warm.trial_scope(trial_id=tid,
                                      enabled=self.warm_start,
                                      stats=stats, fresh_state=False):
                    retval = self._run_trial(call_params, lane_dir,
                                             reporter)
                metric = util.handle_return_val(
                    retval, lane_dir, self.optimization_key, env)
                client.finalize_lane(tid, metric, reporter,
                                     lane=entry.get("lane", i),
                                     block=leader_id,
                                     epoch=entry.get("epoch"), last=last)
            except EarlyStopException as e:
                if reporter.take_preempt():
                    client.preempt_ack(leader_id, reporter, step=None)
                    return
                env.dump(util.json_dumps_safe(
                    {self.optimization_key: e.metric}),
                    lane_dir + "/.outputs.json")
                client.finalize_lane(tid, e.metric, reporter,
                                     lane=entry.get("lane", i),
                                     block=leader_id,
                                     epoch=entry.get("epoch"), last=last)
            except Exception:  # noqa: BLE001 - report lane error, run the rest
                reporter.log("Lane trial {} failed:\n{}".format(
                    tid, _tb.format_exc()))
                client.finalize_lane(tid, None, reporter,
                                     lane=entry.get("lane", i),
                                     block=leader_id,
                                     epoch=entry.get("epoch"), last=last,
                                     error=True)
            finally:
                stats.trial_end(tid)

    def _run_gang_member(self, trial_id: str, params: dict, client,
                         reporter) -> None:
        """One remote gang member's side of an SPMD gang trial: every
        process of the gang must call ``jax.distributed.initialize`` (or
        the leader's rendezvous hangs) and then run the SAME program so
        the collectives line up. The member's return value is discarded
        and it never finalizes — exactly one FINAL per trial, from the
        leader. Failures are logged, not raised: a broken member makes
        the leader's mesh fail, and the driver's member-loss/requeue
        machinery owns that recovery."""
        import traceback as _tb

        from maggy_tpu.core.executors.context import TrialContext

        trial_dir = "{}/{}".format(self.exp_dir, trial_id)
        try:
            ctx = TrialContext(trial_id, trial_dir, self.exp_dir, params,
                               client.last_info)
            gang = ctx.gang
            if gang is None:
                return
            gang.ensure_rendezvous()
            call_params = dict(params)
            sig_params = inspect.signature(self.train_fn).parameters
            if "ctx" in sig_params:
                call_params["ctx"] = ctx
            if "reporter" in sig_params:
                call_params["reporter"] = None
            self.train_fn(**call_params)
        except Exception:  # noqa: BLE001 - member failure: leader's mesh surfaces it
            reporter.log("gang member program for {} failed:\n{}".format(
                trial_id, _tb.format_exc()))

    def _run_trial(self, call_params: dict, trial_dir: str, reporter=None):
        """Invoke the user train_fn, optionally under a `jax.profiler`
        trace (SURVEY.md §5.1: the TPU-idiomatic stand-in for the
        reference's absent profiling — traces land in the trial's
        TensorBoard dir and open in its profile plugin).

        The JAX profiler is process-global (one trace at a time), so with
        an in-process thread pool tracing is best-effort: a trial whose
        start overlaps an already-traced trial runs untraced. Process/TPU
        pools have one trial per process and trace every trial."""
        if self.ship_prints:
            _install_print_tee()
            _print_ship.reporter = reporter
        try:
            if not self.profile:
                return self.train_fn(**call_params)
            if not _PROFILE_LOCK.acquire(blocking=False):
                # Another thread-pool trial holds the process-global
                # profiler: this trial runs UNTRACED. Report it through
                # the runner-stats channel so the journal carries a
                # profile_skipped trial event — a missing TensorBoard
                # trace must be explainable, not a mystery.
                stats = getattr(reporter, "stats", None) if reporter else None
                if stats is not None:
                    stats.note_profile_skipped(
                        getattr(reporter, "trial_id", None))
                if reporter is not None:
                    reporter.log("profiler busy (thread-pool contention); "
                                 "trial runs untraced")
                return self.train_fn(**call_params)
            try:
                import jax

                with jax.profiler.trace(os.path.join(trial_dir, "tensorboard")):
                    return self.train_fn(**call_params)
            finally:
                _PROFILE_LOCK.release()
        finally:
            _print_ship.reporter = None


def trial_executor_fn(**kwargs) -> TrialExecutor:
    """Factory kept for parity with the reference's
    `trial_executor.py:32` naming."""
    return TrialExecutor(**kwargs)
