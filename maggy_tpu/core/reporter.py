"""Executor-side reporter: bridges user code and the heartbeat thread.

Parity: reference `maggy/core/reporter.py` — `broadcast(metric, step)` with
type checks, monotonic-step enforcement, latest-value store, and raising
`EarlyStopException` inside the user's training loop once the driver's STOP
reply has set the flag (:78-102); `log()` buffered for heartbeat shipping
(:104-133); `get_data()` drain (:135-141); `reset()` between trials
(:143-156); `early_stop()` armed only after >=1 reported metric (:158-161).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import numpy as np

from maggy_tpu import exceptions


class Reporter:
    def __init__(self, log_file: Optional[str] = None, print_tee: bool = False):
        self.lock = threading.RLock()
        self.metric: Optional[float] = None  # guarded-by: lock
        self.step: Optional[int] = None  # guarded-by: lock
        self.trial_id: Optional[str] = None  # guarded-by: lock
        # Telemetry span id assigned by the driver for this trial; rides
        # the TRIAL reply and is echoed on METRIC/FINAL so driver-side
        # span timelines attribute every hop without guessing.
        self.span: Optional[str] = None
        # Runner-side stat buffer (telemetry.runnerstats.RunnerStats),
        # attached by the executor: broadcast() feeds it the step cadence
        # and time-to-first-metric signals. None = no-op.
        self.stats = None
        self._stop_flag = False  # guarded-by: lock
        # The current stop is a scheduler preemption (STOP reply carried
        # ``preempt``): the executor acks with a preempted FINAL instead
        # of finalizing. Consumed via take_preempt().
        self._preempt_flag = False  # guarded-by: lock
        self._log_buffer: List[str] = []  # guarded-by: lock
        self._log_file = log_file
        self._print_tee = print_tee
        self._metric_cache = None  # guarded-by: lock  # (device_array, float, step) identity triple
        self._async_kick = None  # guarded-by: lock  # device array with an in-flight D2H copy

    # ------------------------------------------------------------- user API

    @staticmethod
    def _scalar_like(metric) -> bool:
        """Accept plain numbers AND lazy single-element device arrays (jax
        Array / 0-d numpy) WITHOUT forcing a device sync — shape/dtype are
        metadata. Booleans are rejected either way."""
        if isinstance(metric, bool):
            return False
        if isinstance(metric, (int, float, np.number)):
            return True
        shape = getattr(metric, "shape", None)
        dtype = getattr(metric, "dtype", None)
        if shape is None or dtype is None:
            return False
        try:
            # Abstract tracers (broadcast called from INSIDE a jitted
            # function) have shape/dtype but no value — rejecting them here
            # keeps the user error in the user's thread instead of blowing
            # up the heartbeat thread at materialization time.
            from jax.core import Tracer

            if isinstance(metric, Tracer):
                return False
        except Exception:  # noqa: BLE001 - no jax in this process
            pass
        try:
            if not (np.issubdtype(dtype, np.floating) or np.issubdtype(dtype, np.integer)):
                return False
            return int(np.prod(shape)) == 1
        except TypeError:
            return False

    def broadcast(self, metric, step: Optional[int] = None) -> None:
        """Report an interim metric from the training loop. Raises
        `EarlyStopException` if the driver has flagged this trial.

        ``metric`` may be a plain number OR a single-element device array
        (e.g. the jax scalar a jitted train step returns). Device arrays are
        kept LAZY: the training loop never blocks on a device->host sync —
        the heartbeat thread materializes the newest value in `get_data()`.
        Over a high-latency device link a blocking `float(loss)` per
        reporting step would serialize the whole pipelined step stream
        (measured ~50 ms/sync on a tunneled TPU chip)."""
        with self.lock:
            if not self._scalar_like(metric):
                raise exceptions.BroadcastMetricTypeError(metric)
            if step is not None and (not isinstance(step, (int, np.integer)) or isinstance(step, bool)):
                raise exceptions.BroadcastStepTypeError(step)
            if step is None:
                step = self.step + 1 if self.step is not None else 0
            elif self.step is not None and step <= self.step:
                raise exceptions.BroadcastStepValueError(step, self.step)
            self.metric = float(metric) \
                if isinstance(metric, (int, np.number)) else metric
            self.step = int(step)
            stats = self.stats
            if stats is not None:
                # Pure arithmetic (runnerstats.RunnerStats.on_broadcast):
                # cadence + time-to-first-metric, recorded BEFORE the stop
                # check so the early-stopped step still counts.
                stats.on_broadcast(self.step)
            if self._stop_flag:
                raise exceptions.EarlyStopException(self._materialize(self.metric))

    @staticmethod
    def _materialize(metric):
        """Device array -> float (blocks until the step producing it ran)."""
        return metric if metric is None or isinstance(metric, float) else float(metric)

    def log(self, message: str, verbose: bool = True) -> None:
        with self.lock:
            self._log_buffer.append(str(message))
            if self._log_file:
                try:
                    with open(self._log_file, "a") as f:
                        f.write(str(message) + "\n")
                except OSError:
                    pass
        if verbose and self._print_tee:
            print(message)

    # ------------------------------------------------------- heartbeat side

    def get_data(self) -> Dict[str, Any]:
        with self.lock:
            metric, step, tid = self.metric, self.step, self.trial_id
            span = self.span
            cached = self._metric_cache
        if metric is not None and not isinstance(metric, float):
            # Materialize OUTSIDE the lock: the device sync (~50 ms over a
            # tunneled chip) must not block the training thread's broadcast.
            # Identity-cache so back-to-back heartbeats on the same value
            # don't re-fetch. Runs BEFORE the log drain below — if the
            # device value is poisoned and float() raises, the buffered
            # logs stay queued for the next beat instead of vanishing.
            #
            # NON-BLOCKING: if the step producing the value hasn't finished,
            # don't park the heartbeat thread on it (concurrent blocking
            # fetches from N runner heartbeats contend on the device link) —
            # kick an async D2H copy and ship the previous materialized
            # (metric, step) pair this beat; the driver dedups by step.
            if cached is not None and cached[0] is metric:
                metric = cached[1]
            else:
                try:
                    ready = metric.is_ready()
                except AttributeError:  # 0-d numpy etc.: materialize now
                    ready = True
                if not ready:
                    # Kick bookkeeping under the lock, with the same
                    # rolled-over guard as the cache below: reset()
                    # clears _async_kick when the trial rolls over, and
                    # an unlocked write landing after it would resurrect
                    # the RETIRED trial's device array as the next
                    # trial's in-flight kick (found by the guarded-by
                    # checker: every other _async_kick write holds the
                    # lock). copy_to_host_async is non-blocking.
                    with self.lock:
                        if self._async_kick is not metric \
                                and self.trial_id == tid:
                            metric.copy_to_host_async()
                            self._async_kick = metric
                if ready:
                    value = self._materialize(metric)
                    with self.lock:
                        # Only cache if the trial hasn't rolled over while
                        # materializing: a write landing after reset() would
                        # resurrect THIS trial's value into the next trial's
                        # ship-previous-pair branch below.
                        if self.trial_id == tid:
                            self._metric_cache = (metric, value, step)
                            self._async_kick = None
                    metric = value
                elif cached is not None:
                    metric, step = cached[1], cached[2]
                else:
                    metric, step = None, None
        with self.lock:
            logs = self._log_buffer
            self._log_buffer = []
        # trial_id/span are the ones the (metric, step) pair belongs to —
        # callers must ship THESE, not re-read reporter fields (which may
        # have rolled over to the next trial mid-call).
        return {"metric": metric, "step": step, "logs": logs,
                "trial_id": tid, "span": span}

    def early_stop(self, trial_id: Optional[str] = None,
                   preempt: bool = False) -> None:
        """Arm the stop flag (only once a metric exists, reference
        `reporter.py:158-161`). ``trial_id``, when given, must match the
        current trial: a STOP reply to a heartbeat that shipped the
        PREVIOUS trial's data must not stop the trial that replaced it.
        ``preempt`` marks the stop as a scheduler preemption."""
        with self.lock:
            if trial_id is not None and trial_id != self.trial_id:
                return
            if self.metric is not None:
                self._stop_flag = True
                if preempt:
                    self._preempt_flag = True

    def take_preempt(self) -> bool:
        """Consume the preemption marker: True exactly once per preempted
        stop (the executor's EarlyStopException handler decides between
        finalize and preempt-ack on it)."""
        with self.lock:
            flag = self._preempt_flag
            self._preempt_flag = False
            return flag

    def reset(self, trial_id: Optional[str] = None,
              span: Optional[str] = None) -> None:
        with self.lock:
            self.metric = None
            self.step = None
            self._stop_flag = False
            self._preempt_flag = False
            self._log_buffer = []
            self.trial_id = trial_id
            self.span = span
            self._metric_cache = None
            self._async_kick = None
