"""Executor-side reporter: bridges user code and the heartbeat thread.

Parity: reference `maggy/core/reporter.py` — `broadcast(metric, step)` with
type checks, monotonic-step enforcement, latest-value store, and raising
`EarlyStopException` inside the user's training loop once the driver's STOP
reply has set the flag (:78-102); `log()` buffered for heartbeat shipping
(:104-133); `get_data()` drain (:135-141); `reset()` between trials
(:143-156); `early_stop()` armed only after >=1 reported metric (:158-161).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import numpy as np

from maggy_tpu import exceptions


class Reporter:
    def __init__(self, log_file: Optional[str] = None, print_tee: bool = False):
        self.lock = threading.RLock()
        self.metric: Optional[float] = None
        self.step: Optional[int] = None
        self.trial_id: Optional[str] = None
        self._stop_flag = False
        self._log_buffer: List[str] = []
        self._log_file = log_file
        self._print_tee = print_tee

    # ------------------------------------------------------------- user API

    def broadcast(self, metric, step: Optional[int] = None) -> None:
        """Report an interim metric from the training loop. Raises
        `EarlyStopException` if the driver has flagged this trial."""
        with self.lock:
            if not isinstance(metric, (int, float, np.number)) or isinstance(metric, bool):
                raise exceptions.BroadcastMetricTypeError(metric)
            if step is not None and (not isinstance(step, (int, np.integer)) or isinstance(step, bool)):
                raise exceptions.BroadcastStepTypeError(step)
            if step is None:
                step = self.step + 1 if self.step is not None else 0
            elif self.step is not None and step <= self.step:
                raise exceptions.BroadcastStepValueError(step, self.step)
            self.metric = float(metric)
            self.step = int(step)
            if self._stop_flag:
                raise exceptions.EarlyStopException(self.metric)

    def log(self, message: str, verbose: bool = True) -> None:
        with self.lock:
            self._log_buffer.append(str(message))
            if self._log_file:
                try:
                    with open(self._log_file, "a") as f:
                        f.write(str(message) + "\n")
                except OSError:
                    pass
        if verbose and self._print_tee:
            print(message)

    # ------------------------------------------------------- heartbeat side

    def get_data(self) -> Dict[str, Any]:
        with self.lock:
            logs = self._log_buffer
            self._log_buffer = []
            return {"metric": self.metric, "step": self.step, "logs": logs}

    def early_stop(self) -> None:
        """Arm the stop flag (only once a metric exists, reference
        `reporter.py:158-161`)."""
        with self.lock:
            if self.metric is not None:
                self._stop_flag = True

    def reset(self, trial_id: Optional[str] = None) -> None:
        with self.lock:
            self.metric = None
            self.step = None
            self._stop_flag = False
            self._log_buffer = []
            self.trial_id = trial_id
