"""Executor-side reporter: bridges user code and the heartbeat thread.

Parity: reference `maggy/core/reporter.py` — `broadcast(metric, step)` with
type checks, monotonic-step enforcement, latest-value store, and raising
`EarlyStopException` inside the user's training loop once the driver's STOP
reply has set the flag (:78-102); `log()` buffered for heartbeat shipping
(:104-133); `get_data()` drain (:135-141); `reset()` between trials
(:143-156); `early_stop()` armed only after >=1 reported metric (:158-161).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import numpy as np

from maggy_tpu import exceptions


class Reporter:
    def __init__(self, log_file: Optional[str] = None, print_tee: bool = False):
        self.lock = threading.RLock()
        self.metric: Optional[float] = None  # guarded-by: lock
        self.step: Optional[int] = None  # guarded-by: lock
        self.trial_id: Optional[str] = None  # guarded-by: lock
        # Telemetry span id assigned by the driver for this trial; rides
        # the TRIAL reply and is echoed on METRIC/FINAL so driver-side
        # span timelines attribute every hop without guessing.
        self.span: Optional[str] = None
        # Runner-side stat buffer (telemetry.runnerstats.RunnerStats),
        # attached by the executor: broadcast() feeds it the step cadence
        # and time-to-first-metric signals. None = no-op.
        self.stats = None
        self._stop_flag = False  # guarded-by: lock
        # The current stop is a scheduler preemption (STOP reply carried
        # ``preempt``): the executor acks with a preempted FINAL instead
        # of finalizing. Consumed via take_preempt().
        self._preempt_flag = False  # guarded-by: lock
        self._log_buffer: List[str] = []  # guarded-by: lock
        self._log_file = log_file
        self._print_tee = print_tee
        self._metric_cache = None  # guarded-by: lock  # (device_array, float, step) identity triple
        self._async_kick = None  # guarded-by: lock  # device array with an in-flight D2H copy
        # ---- vectorized (K-lane) trial blocks (train/vmap.py) ----
        # Lane descriptors for the current block, in lane order:
        # [{"trial_id", "span", "lane"}, ...]. None = scalar trial.
        self._lanes = None  # guarded-by: lock
        self._lane_vec = None  # guarded-by: lock  # lazy (K,) loss vector
        self._lane_step: Optional[int] = None  # guarded-by: lock
        self._lane_cache = None  # guarded-by: lock  # (vec_identity, [floats], step)
        # Lane trial ids the driver flagged for early stop; _new holds the
        # ones the training loop hasn't consumed (take_stopped_lanes) yet.
        self._lane_stops: set = set()  # guarded-by: lock
        self._lane_stops_new: set = set()  # guarded-by: lock

    # ------------------------------------------------------------- user API

    @staticmethod
    def _scalar_like(metric) -> bool:
        """Accept plain numbers AND lazy single-element device arrays (jax
        Array / 0-d numpy) WITHOUT forcing a device sync — shape/dtype are
        metadata. Booleans are rejected either way."""
        if isinstance(metric, bool):
            return False
        if isinstance(metric, (int, float, np.number)):
            return True
        shape = getattr(metric, "shape", None)
        dtype = getattr(metric, "dtype", None)
        if shape is None or dtype is None:
            return False
        try:
            # Abstract tracers (broadcast called from INSIDE a jitted
            # function) have shape/dtype but no value — rejecting them here
            # keeps the user error in the user's thread instead of blowing
            # up the heartbeat thread at materialization time.
            from jax.core import Tracer

            if isinstance(metric, Tracer):
                return False
        except Exception:  # noqa: BLE001 - no jax in this process
            pass
        try:
            if not (np.issubdtype(dtype, np.floating) or np.issubdtype(dtype, np.integer)):
                return False
            return int(np.prod(shape)) == 1
        except TypeError:
            return False

    def broadcast(self, metric, step: Optional[int] = None) -> None:
        """Report an interim metric from the training loop. Raises
        `EarlyStopException` if the driver has flagged this trial.

        ``metric`` may be a plain number OR a single-element device array
        (e.g. the jax scalar a jitted train step returns). Device arrays are
        kept LAZY: the training loop never blocks on a device->host sync —
        the heartbeat thread materializes the newest value in `get_data()`.
        Over a high-latency device link a blocking `float(loss)` per
        reporting step would serialize the whole pipelined step stream
        (measured ~50 ms/sync on a tunneled TPU chip)."""
        with self.lock:
            if not self._scalar_like(metric):
                raise exceptions.BroadcastMetricTypeError(metric)
            if step is not None and (not isinstance(step, (int, np.integer)) or isinstance(step, bool)):
                raise exceptions.BroadcastStepTypeError(step)
            if step is None:
                step = self.step + 1 if self.step is not None else 0
            elif self.step is not None and step <= self.step:
                raise exceptions.BroadcastStepValueError(step, self.step)
            self.metric = float(metric) \
                if isinstance(metric, (int, np.number)) else metric
            self.step = int(step)
            stats = self.stats
            if stats is not None:
                # Pure arithmetic (runnerstats.RunnerStats.on_broadcast):
                # cadence + time-to-first-metric, recorded BEFORE the stop
                # check so the early-stopped step still counts.
                stats.on_broadcast(self.step)
            if self._stop_flag:
                raise exceptions.EarlyStopException(self._materialize(self.metric))

    def broadcast_lanes(self, values, step: Optional[int] = None) -> None:
        """Vectorized-trial analogue of `broadcast()`: report the per-lane
        loss vector of a K-lane block (train/vmap.py `VmapTrainer.step`
        output). ``values`` must have length K (one entry per lane, masked
        lanes included — their entries are dead compute and are dropped at
        ship time). Kept LAZY like `broadcast()`: a jax (K,) array is not
        synced here; the heartbeat thread materializes it in `get_data()`.

        Raises `EarlyStopException` when the whole BLOCK is stopped (a
        scheduler preemption) — per-lane stops never raise; they surface
        via `take_stopped_lanes()` so the training loop can mask the lane
        without tearing down the block."""
        with self.lock:
            if self._lanes is None:
                raise exceptions.BroadcastMetricTypeError(values)
            k = len(self._lanes)
            shape = getattr(values, "shape", None)
            n = shape[0] if shape else len(values)
            if shape is not None and len(shape) != 1 or n != k:
                raise exceptions.BroadcastMetricTypeError(values)
            if step is not None and (not isinstance(step, (int, np.integer)) or isinstance(step, bool)):
                raise exceptions.BroadcastStepTypeError(step)
            if step is None:
                step = self._lane_step + 1 if self._lane_step is not None else 0
            elif self._lane_step is not None and step <= self._lane_step:
                raise exceptions.BroadcastStepValueError(step, self._lane_step)
            self._lane_vec = values
            self._lane_step = int(step)
            # Mirror into the scalar fields so code keyed on "has this
            # trial reported yet" (early_stop arming, preempt acks) works:
            # the block's leader beat is step-aligned with the lanes.
            self.step = self._lane_step
            stats = self.stats
            if stats is not None:
                stats.on_broadcast(self._lane_step)
            if self._stop_flag:
                raise exceptions.EarlyStopException(None)

    def stop_lanes(self, trial_ids) -> None:
        """Flag individual lanes of the current block for early stop (the
        heartbeat thread applies the server's ``stop_lanes`` reply here).
        Unknown / stale trial ids are ignored."""
        with self.lock:
            if not self._lanes:
                return
            known = {entry["trial_id"] for entry in self._lanes}
            for tid in trial_ids or ():
                if tid in known and tid not in self._lane_stops:
                    self._lane_stops.add(tid)
                    self._lane_stops_new.add(tid)

    def take_stopped_lanes(self) -> List[str]:
        """Consume newly stop-flagged lane trial ids (each id is returned
        exactly once). The training loop polls this between steps and masks
        the named lanes (`VmapTrainer.mask_lane`) — no recompile, no
        exception."""
        with self.lock:
            fresh = sorted(self._lane_stops_new)
            self._lane_stops_new = set()
            return fresh

    def stopped_lanes(self) -> List[str]:
        """All lane trial ids flagged so far this block (consumed or not)."""
        with self.lock:
            return sorted(self._lane_stops)

    @staticmethod
    def _materialize(metric):
        """Device array -> float (blocks until the step producing it ran)."""
        return metric if metric is None or isinstance(metric, float) else float(metric)

    def log(self, message: str, verbose: bool = True) -> None:
        with self.lock:
            self._log_buffer.append(str(message))
            if self._log_file:
                try:
                    with open(self._log_file, "a") as f:
                        f.write(str(message) + "\n")
                except OSError:
                    pass
        if verbose and self._print_tee:
            print(message)

    # ------------------------------------------------------- heartbeat side

    def get_data(self) -> Dict[str, Any]:
        with self.lock:
            metric, step, tid = self.metric, self.step, self.trial_id
            span = self.span
            cached = self._metric_cache
        if metric is not None and not isinstance(metric, float):
            # Materialize OUTSIDE the lock: the device sync (~50 ms over a
            # tunneled chip) must not block the training thread's broadcast.
            # Identity-cache so back-to-back heartbeats on the same value
            # don't re-fetch. Runs BEFORE the log drain below — if the
            # device value is poisoned and float() raises, the buffered
            # logs stay queued for the next beat instead of vanishing.
            #
            # NON-BLOCKING: if the step producing the value hasn't finished,
            # don't park the heartbeat thread on it (concurrent blocking
            # fetches from N runner heartbeats contend on the device link) —
            # kick an async D2H copy and ship the previous materialized
            # (metric, step) pair this beat; the driver dedups by step.
            if cached is not None and cached[0] is metric:
                metric = cached[1]
            else:
                try:
                    ready = metric.is_ready()
                except AttributeError:  # 0-d numpy etc.: materialize now
                    ready = True
                if not ready:
                    # Kick bookkeeping under the lock, with the same
                    # rolled-over guard as the cache below: reset()
                    # clears _async_kick when the trial rolls over, and
                    # an unlocked write landing after it would resurrect
                    # the RETIRED trial's device array as the next
                    # trial's in-flight kick (found by the guarded-by
                    # checker: every other _async_kick write holds the
                    # lock). copy_to_host_async is non-blocking.
                    with self.lock:
                        if self._async_kick is not metric \
                                and self.trial_id == tid:
                            metric.copy_to_host_async()
                            self._async_kick = metric
                if ready:
                    value = self._materialize(metric)
                    with self.lock:
                        # Only cache if the trial hasn't rolled over while
                        # materializing: a write landing after reset() would
                        # resurrect THIS trial's value into the next trial's
                        # ship-previous-pair branch below.
                        if self.trial_id == tid:
                            self._metric_cache = (metric, value, step)
                            self._async_kick = None
                    metric = value
                elif cached is not None:
                    metric, step = cached[1], cached[2]
                else:
                    metric, step = None, None
        lanes_out = self._lane_data(tid)
        with self.lock:
            logs = self._log_buffer
            self._log_buffer = []
        # trial_id/span are the ones the (metric, step) pair belongs to —
        # callers must ship THESE, not re-read reporter fields (which may
        # have rolled over to the next trial mid-call).
        data = {"metric": metric, "step": step, "logs": logs,
                "trial_id": tid, "span": span}
        if lanes_out is not None:
            data["lanes"] = lanes_out
        return data

    def _lane_data(self, tid) -> Optional[List[Dict[str, Any]]]:
        """Materialize the newest per-lane loss vector into lane-tagged beat
        entries (one dict per LIVE lane). None when not in lane mode or no
        vector was broadcast yet. Runs on the heartbeat thread — the single
        (K,) device sync here replaces K scalar syncs."""
        with self.lock:
            lanes, vec, vstep = self._lanes, self._lane_vec, self._lane_step
            stops = set(self._lane_stops)
            cached = self._lane_cache
        if lanes is None or vec is None:
            return None
        if cached is not None and cached[0] is vec:
            values, vstep = cached[1], cached[2]
        else:
            values = [float(v) for v in np.asarray(vec).reshape(-1)]
            with self.lock:
                if self.trial_id == tid:
                    self._lane_cache = (vec, values, vstep)
        return [{"trial_id": entry["trial_id"], "value": values[i],
                 "step": vstep, "span": entry.get("span"),
                 "lane": entry.get("lane", i)}
                for i, entry in enumerate(lanes)
                if entry["trial_id"] not in stops]

    def early_stop(self, trial_id: Optional[str] = None,
                   preempt: bool = False) -> None:
        """Arm the stop flag (only once a metric exists, reference
        `reporter.py:158-161`). ``trial_id``, when given, must match the
        current trial: a STOP reply to a heartbeat that shipped the
        PREVIOUS trial's data must not stop the trial that replaced it.
        ``preempt`` marks the stop as a scheduler preemption."""
        with self.lock:
            if self._lanes is not None:
                # Vectorized block: a preempt stops the WHOLE block (the
                # executor acks and the driver requeues every lane); a
                # plain per-lane stop is routed to the lane-mask path.
                lane_ids = {entry["trial_id"] for entry in self._lanes}
                if trial_id is not None and trial_id != self.trial_id \
                        and trial_id not in lane_ids:
                    return
                if preempt:
                    if self._lane_step is not None or self.metric is not None:
                        self._stop_flag = True
                        self._preempt_flag = True
                elif trial_id is not None:
                    self.stop_lanes([trial_id])
                return
            if trial_id is not None and trial_id != self.trial_id:
                return
            if self.metric is not None:
                self._stop_flag = True
                if preempt:
                    self._preempt_flag = True

    def take_preempt(self) -> bool:
        """Consume the preemption marker: True exactly once per preempted
        stop (the executor's EarlyStopException handler decides between
        finalize and preempt-ack on it)."""
        with self.lock:
            flag = self._preempt_flag
            self._preempt_flag = False
            return flag

    def reset(self, trial_id: Optional[str] = None,
              span: Optional[str] = None) -> None:
        with self.lock:
            self.metric = None
            self.step = None
            self._stop_flag = False
            self._preempt_flag = False
            self._log_buffer = []
            self.trial_id = trial_id
            self.span = span
            self._metric_cache = None
            self._async_kick = None
            self._lanes = None
            self._lane_vec = None
            self._lane_step = None
            self._lane_cache = None
            self._lane_stops = set()
            self._lane_stops_new = set()

    def reset_lanes(self, trial_id: str, span: Optional[str],
                    lanes: List[Dict[str, Any]]) -> None:
        """Arm the reporter for a vectorized K-lane block. ``trial_id`` /
        ``span`` are the block LEADER's (the trial the partition is
        assigned); ``lanes`` are the per-lane descriptors from the TRIAL
        reply's ``vmap_block`` info — each needs at least trial_id/span/lane.
        """
        self.reset(trial_id=trial_id, span=span)
        with self.lock:
            self._lanes = [{"trial_id": entry["trial_id"],
                            "span": entry.get("span"),
                            "lane": entry.get("lane", i)}
                           for i, entry in enumerate(lanes)]
