"""DCN control plane: driver <-> trial-runner RPC.

Parity: reference `maggy/core/rpc.py` — message vocabulary
REG/QUERY/METRIC/FINAL/GET/LOG (+DIST_CONFIG replacing TORCH_CONFIG) with
replies OK/ERR/STOP/GSTOP/TRIAL (:295-437); `Reservations` barrier registry
(:35-113); length-prefixed wire protocol (:116-162); select-loop server in a
daemon thread with per-message shared-secret auth (:250-286); client with a
dedicated heartbeat socket, reconnect retries, and blocking suggestion polls
(:440-593); re-registration failure detection queueing BLACK (:308-326).

Deliberate redesigns (SURVEY.md §2.3 "TPU-native equivalent"):

- **msgpack, not cloudpickle**: the reference unpickles network input
  (`rpc.py:24,146,160`) — arbitrary code execution from any process that
  knows the port. Here every frame is a fixed-schema msgpack map; trial
  params are declarative data, never callables.
- **per-message HMAC** instead of plaintext secret comparison: the secret
  never travels on the wire after registration.
- The gradient plane is NOT here: that is `jax.distributed` + XLA collectives
  over ICI. This layer only brokers the coordinator rendezvous (DIST_CONFIG)
  the way the reference brokers MASTER_ADDR/PORT (`rpc.py:409-416`).
"""

from __future__ import annotations

import hashlib
import hmac
import queue
import secrets as pysecrets
import selectors
import socket
import struct
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

import msgpack

from maggy_tpu import constants
from maggy_tpu.chaos.injectors import ChaosKilled
from maggy_tpu.chaos.injectors import active_engine as chaos_engine
from maggy_tpu.exceptions import AuthenticationError
from maggy_tpu.telemetry.metrics import MetricsRegistry
from maggy_tpu.trial import Trial

_LEN = struct.Struct(">I")
MAX_FRAME = 64 * 1024 * 1024

#: Process-wide client-side RPC metrics (retries/reconnects). Module-level
#: because clients outlive no experiment and may run in runner processes
#: with no driver telemetry; in-process pools share it with the driver, so
#: chaos soaks can assert the retry paths actually ran.
CLIENT_METRICS = MetricsRegistry()

# Sentinel trial id returned by Client.get_suggestion when the driver asks
# this runner to exit and respawn pinned to a different chip count.
RESIZE = "__resize__"


# --------------------------------------------------------------------- wire


def _sign(secret: bytes, payload: bytes) -> bytes:
    from maggy_tpu import native

    return native.hmac_sha256(secret, payload)


class MessageSocket:
    """Framed transport: 4-byte big-endian length || 32-byte HMAC || msgpack."""

    @staticmethod
    def send_msg(sock: socket.socket, msg: Dict[str, Any], secret: bytes) -> None:
        payload = msgpack.packb(msg, use_bin_type=True)
        if len(payload) > MAX_FRAME:
            raise ValueError("Frame too large: {} bytes".format(len(payload)))
        mac = _sign(secret, payload)
        sock.sendall(_LEN.pack(len(payload)) + mac + payload)

    @staticmethod
    def recv_msg(sock: socket.socket, secret: bytes) -> Dict[str, Any]:
        header = MessageSocket._recv_exact(sock, 4 + 32)
        (length,) = _LEN.unpack(header[:4])
        if length > MAX_FRAME:
            raise AuthenticationError("Oversized frame.")
        mac = header[4:]
        payload = MessageSocket._recv_exact(sock, length)
        if not hmac.compare_digest(mac, _sign(secret, payload)):
            raise AuthenticationError("Bad message HMAC.")
        return msgpack.unpackb(payload, raw=False, strict_map_key=False)

    @staticmethod
    def _recv_exact(sock: socket.socket, n: int) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            chunk = sock.recv(min(constants.RPC_RECV_BUFSIZE, n - len(buf)))
            if not chunk:
                raise ConnectionError("Socket closed mid-frame.")
            buf.extend(chunk)
        return bytes(buf)


# -------------------------------------------------------------- reservations


class Reservations:
    """Thread-safe registry partition_id -> executor record, with barrier
    semantics (reference `rpc.py:35-113`)."""

    def __init__(self, required: int):
        self.required = required
        self.lock = threading.RLock()
        self._table: Dict[int, Dict[str, Any]] = {}  # guarded-by: lock
        # Evictions requested before the partition registered (fleet
        # preemption racing a fresh lease's REG): applied at add() so the
        # release is delivered instead of silently lost.
        self._pending_evict: set = set()  # guarded-by: lock

    def add(self, meta: Dict[str, Any]) -> None:
        with self.lock:
            rec = dict(meta)
            rec["last_beat"] = time.monotonic()
            pid = int(meta["partition_id"])
            if pid in self._pending_evict:
                self._pending_evict.discard(pid)
                rec["evict"] = True
            self._table[pid] = rec

    def touch(self, partition_id) -> None:
        """Record liveness: any message from the runner counts as a beat.
        A chaos mute window (see ``age_beat``) suppresses the update."""
        with self.lock:
            rec = self._table.get(int(partition_id))
            if rec is not None and \
                    rec.get("mute_until", 0.0) <= time.monotonic():
                rec["last_beat"] = time.monotonic()

    def age_beat(self, partition_id, age_s: float,
                 mute_s: float = 0.0) -> None:
        """Fault-injection support (maggy_tpu.chaos ``fake_preemption``):
        push the partition's last_beat ``age_s`` into the past and ignore
        fresh beats for ``mute_s`` seconds, so the heartbeat-loss scan
        sees a silent runner while the runner itself stays alive — the
        falsely-declared-lost race, injected on demand."""
        with self.lock:
            rec = self._table.get(int(partition_id))
            if rec is not None:
                now = time.monotonic()
                rec["last_beat"] = min(rec.get("last_beat", now),
                                       now - age_s)
                if mute_s > 0:
                    rec["mute_until"] = now + mute_s

    # locked-by: lock
    def _silent_locked(self, timeout: float):
        now = time.monotonic()
        return [
            pid for pid, rec in self._table.items()
            if not rec.get("released")
            and now - rec.get("last_beat", now) > timeout
        ]

    def silent(self, timeout: float):
        """Registered, unreleased partitions silent for longer than
        ``timeout`` — regardless of trial assignment (distributed workers
        hold no trials but must heartbeat for their whole run)."""
        with self.lock:
            return self._silent_locked(timeout)

    def is_silent(self, partition_id, timeout: float) -> bool:
        """Single-partition form of `silent`: registered, unreleased, and
        beat-less for longer than ``timeout``. The ONE home of the
        last_beat liveness predicate — JOIN admission and the driver's
        dead-partition checks both consult it."""
        with self.lock:
            rec = self._table.get(int(partition_id))
            if rec is None or rec.get("released"):
                return False
            return time.monotonic() - rec.get("last_beat", 0) > timeout

    def lost_assignments(self, timeout: float):
        """Silent partitions that hold a trial: [(partition_id, trial_id)].
        Read-only; the caller decides recovery."""
        with self.lock:
            return [
                (pid, self._table[pid]["trial_id"])
                for pid in self._silent_locked(timeout)
                if self._table[pid].get("trial_id") is not None
            ]

    def get(self, partition_id: int) -> Optional[Dict[str, Any]]:
        with self.lock:
            rec = self._table.get(int(partition_id))
            return dict(rec) if rec else None

    def capacity(self, partition_id: int) -> Optional[int]:
        """The runner's advertised chip capacity (None = not elastic)."""
        with self.lock:
            rec = self._table.get(int(partition_id))
            return rec.get("capacity") if rec else None

    def live_count(self) -> int:
        """Registered, unreleased partitions — the prefetch pipeline's
        queue bound (one pre-materialized suggestion per live runner)."""
        with self.lock:
            return sum(1 for rec in self._table.values()
                       if not rec.get("released"))

    def capacities(self) -> Dict[int, int]:
        """Count of live (registered, unreleased) runners by capacity."""
        with self.lock:
            out: Dict[int, int] = {}
            for rec in self._table.values():
                cap = rec.get("capacity")
                if cap is not None and not rec.get("released"):
                    out[cap] = out.get(cap, 0) + 1
            return out

    def request_resize(self, partition_id: int, chips: int) -> None:
        """Ask a runner to exit and respawn pinned to ``chips`` chips (the
        elastic pool does the respawn). Delivered on its next GET."""
        with self.lock:
            rec = self._table.get(int(partition_id))
            if rec is not None:
                rec["resize"] = int(chips)

    def pop_resize(self, partition_id: int) -> Optional[int]:
        with self.lock:
            rec = self._table.get(int(partition_id))
            if rec is None:
                return None
            return rec.pop("resize", None)

    def done(self) -> bool:
        with self.lock:
            return len(self._table) >= self.required

    def remaining(self) -> int:
        with self.lock:
            return max(0, self.required - len(self._table))

    def assign_trial(self, partition_id: int, trial_id: Optional[str]) -> None:
        with self.lock:
            if int(partition_id) in self._table:
                self._table[int(partition_id)]["trial_id"] = trial_id

    def clear_trial_if(self, partition_id: int,
                       trial_id: Optional[str]) -> None:
        """Clear the partition's assignment ONLY if it still names
        ``trial_id``. The FINAL handler must use this, not a blind
        assign_trial(None): under at-least-once delivery (reply lost,
        client retries) the retried FINAL arrives AFTER the driver has
        already assigned the partition its NEXT trial, and a blind wipe
        strands that trial in the store forever — the experiment never
        completes. Found by the chaos harness's sever_conn fault."""
        with self.lock:
            rec = self._table.get(int(partition_id))
            if rec is not None and rec.get("trial_id") == trial_id:
                rec["trial_id"] = None

    def mark_released(self, partition_id) -> None:
        """The runner has been told GSTOP — it will send nothing more."""
        with self.lock:
            rec = self._table.get(int(partition_id))
            if rec is not None:
                rec["released"] = True

    # ------------------------------------------------------- crash recovery

    def restore(self, partition_id, trial_id: Optional[str] = None,
                capacity: Optional[int] = None,
                host_port: Optional[str] = None) -> None:
        """Crash-only recovery: re-seed a pre-crash partition's record
        from the replayed journal. The record starts with a FRESH
        last_beat — every recovered partition gets exactly one liveness
        window to prove itself: a still-live runner's next heartbeat /
        retried FINAL re-binds it (``pop_recovered`` journals the
        ``adopted`` edge), a dead one goes silent past the loss bound and
        the ORDINARY slot-reclaim scan requeues its trial — recovery adds
        no second requeue path. Never overwrites a live registration."""
        with self.lock:
            pid = int(partition_id)
            if pid in self._table:
                return
            self._table[pid] = {
                "partition_id": pid, "trial_id": trial_id,
                "capacity": capacity, "host_port": host_port,
                "task_attempt": 0, "recovered": True,
                "last_beat": time.monotonic(),
            }

    def pop_recovered(self, partition_id) -> bool:
        """Consume the partition's recovered flag: True exactly once, on
        the first post-recovery message — the caller journals the
        ``adopted`` runner edge on it."""
        with self.lock:
            rec = self._table.get(int(partition_id))
            if rec is not None and rec.get("recovered"):
                rec.pop("recovered", None)
                return True
            return False

    # ------------------------------------------------------------ gang holds

    def hold_for_gang(self, partition_id, trial_id: str) -> None:
        """Conscript the runner into a gang: while held it is not free —
        the driver hands it no 1-chip work — but it keeps heartbeating
        and idle-polling; its chip belongs to ``trial_id``'s mesh slice
        until the gang releases."""
        with self.lock:
            rec = self._table.get(int(partition_id))
            if rec is not None:
                rec["gang"] = trial_id

    def gang_of(self, partition_id) -> Optional[str]:
        with self.lock:
            rec = self._table.get(int(partition_id))
            return rec.get("gang") if rec else None

    def release_gang(self, trial_id: str) -> list:
        """Free every member held for ``trial_id``; returns their pids so
        the driver can restart their work loops."""
        with self.lock:
            freed = []
            for pid, rec in self._table.items():
                if rec.get("gang") == trial_id:
                    rec.pop("gang", None)
                    rec.pop("gang_served", None)
                    freed.append(pid)
            return freed

    def mark_gang_served(self, partition_id, trial_id: str) -> bool:
        """One-shot delivery latch for a REMOTE gang's member program:
        True the first time this held member is served ``trial_id``'s
        member assignment, False on every retry/re-poll — the member
        runs the SPMD program exactly once per assembly (the latch
        clears with the hold in ``release_gang``, so a revoked-and-
        reassembled gang serves its members again)."""
        with self.lock:
            rec = self._table.get(int(partition_id))
            if rec is None or rec.get("gang") != trial_id:
                return False
            if rec.get("gang_served") == trial_id:
                return False
            rec["gang_served"] = trial_id
            return True

    def gang_members(self, trial_id: str) -> list:
        with self.lock:
            return sorted(pid for pid, rec in self._table.items()
                          if rec.get("gang") == trial_id)

    def free_pids(self) -> list:
        """Runners available for new work: registered, unreleased, not
        evicted, holding no trial and conscripted into no gang. The gang
        assembler's free set."""
        with self.lock:
            return sorted(
                pid for pid, rec in self._table.items()
                if not rec.get("released") and not rec.get("evict")
                and rec.get("trial_id") is None and rec.get("gang") is None)

    def request_stop(self, partition_id, trial_id: str) -> None:
        """Gang revocation: arm a one-shot preempt-STOP for the
        partition's next heartbeat about ``trial_id``. Used to abort a
        HEALTHY gang leader whose gang lost a member — the trial is
        already requeued, so the leader's preempt ack is dropped by the
        driver's idempotent preemption path and the runner returns to
        the pool. Reservation-level (not a trial flag) so the abort
        cannot be mistaken for a schedulable preemption."""
        with self.lock:
            rec = self._table.get(int(partition_id))
            if rec is not None:
                rec["stop_trial"] = trial_id

    def pop_stop(self, partition_id, trial_id) -> bool:
        """Consume an armed revocation STOP if it names ``trial_id``."""
        with self.lock:
            rec = self._table.get(int(partition_id))
            if rec is not None and trial_id is not None \
                    and rec.get("stop_trial") == trial_id:
                rec.pop("stop_trial", None)
                return True
            return False

    def request_evict(self, partition_id) -> bool:
        """Fleet preemption: ask that this partition's runner be released
        from the experiment (GSTOP) at its next reply opportunity — after
        its preempted FINAL lands, or on its next GET when idle. Cleared
        naturally when a future runner re-registers the slot (``add``
        builds a fresh record). An unknown partition's eviction is parked
        and applied at its registration — a fleet preemption may race the
        fresh lease's REG, and the release must not be silently lost."""
        with self.lock:
            rec = self._table.get(int(partition_id))
            if rec is None:
                self._pending_evict.add(int(partition_id))
                return True
            rec["evict"] = True
            return True

    def evict_requested(self, partition_id) -> bool:
        with self.lock:
            rec = self._table.get(int(partition_id))
            return bool(rec and rec.get("evict"))

    def all_released(self) -> bool:
        with self.lock:
            return all(rec.get("released") for rec in self._table.values())

    def get_assigned_trial(self, partition_id: int) -> Optional[str]:
        with self.lock:
            rec = self._table.get(int(partition_id))
            return rec.get("trial_id") if rec else None

    def all(self) -> Dict[int, Dict[str, Any]]:
        with self.lock:
            return {k: dict(v) for k, v in self._table.items()}


# --------------------------------------------------------------------- server


class Server:
    """Event-loop RPC server running in a daemon thread.

    The driver registers message callbacks keyed by type; unknown types get
    an ERR reply (reference `rpc.py:207-233,250-286`).
    """

    def __init__(self, num_executors: int, secret: Optional[str] = None):
        self.num_executors = num_executors
        # Telemetry facade (maggy_tpu.telemetry.Telemetry), attached by the
        # driver. None = no TELEM verb, no verb timing. Handlers must treat
        # it as optional: the server also runs driverless in tests.
        self.telemetry = None
        # One-shot flag so a broken periodic_check hook logs ONCE instead of
        # spamming (or silently dying) on every event-loop tick.
        self._periodic_check_failed = False
        self.secret_hex = secret or pysecrets.token_hex(16)
        self.secret = self.secret_hex.encode()
        self.reservations = Reservations(num_executors)
        # Remote-runner admission: the driver publishes the executor config
        # here when runners are external agents; None rejects JOINs.
        self.join_info: Optional[Dict[str, Any]] = None
        self._join_lock = threading.Lock()
        # pid -> monotonic issue time. A slot is "taken" while its JOIN is
        # fresher than the liveness bound or its holder has registered; an
        # issued-but-never-registered slot expires and becomes reclaimable
        # (the joining agent died before REG).
        self._issued_pids: Dict[int, float] = {}  # guarded-by: _join_lock
        # Heartbeat-liveness bound used by JOIN slot-reclaim checks (and, in
        # OptimizationServer, the loss scan). None disables.
        self.hb_loss_timeout: Optional[float] = None
        self._buffers: Dict[socket.socket, bytearray] = {}
        self._sel = selectors.DefaultSelector()
        self._listener: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None
        self._stop_event = threading.Event()
        # Set when this server is published on a fleet SharedServer
        # instead of its own listener: frames arrive through the shared
        # event loop (routed by which experiment secret authenticates
        # them) and stop() detaches rather than tearing a socket down.
        self._shared: Optional["SharedServer"] = None
        self._handlers: Dict[str, Callable[[Dict[str, Any]], Dict[str, Any]]] = {}
        self._register_handlers()

    # subclasses override
    def _register_handlers(self) -> None:
        self._handlers["QUERY"] = lambda msg: {
            "type": "QUERY",
            "done": self.reservations.done(),
        }
        self._handlers["JOIN"] = self._join
        # rpc-ok: TELEM produced by monitor --telem via a generic send_msg
        self._handlers["TELEM"] = self._telem

    def _telem(self, msg):
        """Telemetry snapshot: live metric registry + span-derived
        scheduling numbers. Same auth as every verb (per-message HMAC —
        an unauthenticated peer never reaches this handler); consumed by
        ``maggy_tpu.monitor --telem`` from any machine that can reach the
        control plane."""
        telem = self.telemetry
        if telem is None:
            return {"type": "ERR",
                    "error": "telemetry is not enabled for this experiment"}
        return {"type": "TELEM", **telem.snapshot()}

    def _join(self, msg):
        """Admit a remote runner agent: assign it a partition id and ship
        the executor config (exp_dir, hb_interval, ...). The DCN analogue of
        Spark handing a partition to an executor — but pull, not push: agents
        on other hosts dial in with the shared secret."""
        info = self.join_info
        if info is None:
            return {"type": "ERR",
                    "error": "this experiment does not accept remote runners"}
        want = msg.get("partition_id")
        liveness = self.hb_loss_timeout or 10.0
        now = time.monotonic()
        with self._join_lock:
            if want is not None and int(want) >= 0:
                # Explicit pid: a restarted agent resuming its slot (its REG
                # will take the re-registration BLACK path). Refuse slots
                # outside the experiment, slots whose holder is still alive,
                # AND slots issued to a not-yet-registered joiner — two
                # agents sharing a pid would interleave GET/FINAL and corrupt
                # trial bookkeeping (the adjacent-JOIN race: both JOIN before
                # either REGs).
                pid = int(want)
                if pid >= self.num_executors:
                    return {"type": "ERR",
                            "error": "partition_id {} out of range (experiment "
                                     "has {} slots)".format(pid, self.num_executors)}
                rec = self.reservations.get(pid)
                if rec is not None and not rec.get("released") and \
                        not self.reservations.is_silent(pid, liveness):
                    return {"type": "ERR",
                            "error": "slot {} is held by a live runner".format(pid)}
                # A fresh issue means another agent just took this slot (it
                # may not have REG'd yet) — checked on every path, stale or
                # released record included, or two replacements racing for
                # the same dead/released slot would both be admitted.
                issued = self._issued_pids.get(pid)
                if issued is not None and now - issued < liveness:
                    return {"type": "ERR",
                            "error": "slot {} was just issued to another "
                                     "joining runner".format(pid)}
                self._issued_pids[pid] = now
            else:
                registered = self.reservations.all()
                taken = set(registered) | {
                    p for p, t in self._issued_pids.items()
                    if now - t < liveness
                }
                pid = next((i for i in range(self.num_executors)
                            if i not in taken), None)
                if pid is None:
                    return {"type": "ERR", "error": "experiment full"}
                self._issued_pids[pid] = now
        return {"type": "JOIN", "partition_id": pid, **info}

    def start(self, host: str = "127.0.0.1", port: int = 0) -> Tuple[str, int]:
        # Warm the native codec BEFORE the event loop exists: the lazy g++
        # build (up to ~minutes on a loaded host) must not run inside the
        # single server thread while registrations queue up.
        from maggy_tpu import native

        native.get_lib()
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host, port))
        srv.listen(128)
        srv.setblocking(False)
        self._listener = srv
        self._sel.register(srv, selectors.EVENT_READ, self._accept)
        self._thread = threading.Thread(target=self._loop, daemon=True, name="rpc-server")
        self._thread.start()
        return srv.getsockname()

    def _accept(self, sock, mask):
        conn, _ = sock.accept()
        # Non-blocking with a per-connection reassembly buffer: a stalled or
        # half-dead client must never freeze the event loop (runner crashes
        # mid-send are exactly what this layer detects).
        conn.setblocking(False)
        self._buffers[conn] = bytearray()
        self._sel.register(conn, selectors.EVENT_READ, self._serve)

    def _serve(self, conn, mask):
        try:
            chunk = conn.recv(constants.RPC_RECV_BUFSIZE)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._drop(conn)
            return
        if not chunk:
            self._drop(conn)
            return
        buf = self._buffers[conn]
        buf.extend(chunk)
        # Stop at the first drop: a dispatch may sever the connection
        # (chaos sever, send failure) while MORE complete frames sit in
        # the local buffer — processing them would reply into a closed
        # socket and, in the shared-server subclass, resurrect the
        # connection's routing entry (the sever-mid-frame leak).
        while conn in self._buffers:
            frame = self._try_extract_frame(conn, buf)
            if frame is None:
                return
            self._dispatch(conn, frame)

    def _try_extract_frame(self, conn, buf: bytearray):
        """Pop one complete authenticated frame from the buffer, or None.

        Scanning + HMAC verification run in the native codec
        (native/framing.cpp) when built; -1/-2 results (oversized frame /
        MAC mismatch) drop the connection."""
        from maggy_tpu import native

        result = native.frame_scan(buf, self.secret, MAX_FRAME)
        if result == 0:
            return None
        if result < 0:
            self._drop(conn)
            return None
        header = 4 + 32
        payload = bytes(buf[header:result])
        del buf[:result]
        return payload

    def _dispatch(self, conn, payload: bytes):
        sever_reply = False
        try:
            msg = msgpack.unpackb(payload, raw=False, strict_map_key=False)
            engine = chaos_engine()
            if engine is not None:
                action = engine.on_server_message(msg)
                if action is not None:
                    if action[0] == "drop":
                        # Message lost + connection reset: the client's
                        # retry/reconnect path re-delivers.
                        self._drop(conn)
                        return
                    if action[0] == "delay":
                        # Deliberately ON the event loop: a stalled
                        # control plane stalls every client, which is the
                        # fault being simulated.
                        time.sleep(action[1])
                    elif action[0] == "sever":
                        # Handle, then cut the connection INSTEAD of
                        # replying — the client retries and the handler
                        # runs twice (at-least-once delivery).
                        sever_reply = True
            resp = self.handle_message(msg)
        except (ConnectionError, socket.timeout, OSError):
            self._drop(conn)
            return
        except Exception as e:  # noqa: BLE001 - a bad message must never kill the loop
            resp = {"type": "ERR", "error": "handler error: {!r}".format(e)}
        if sever_reply:
            self._drop(conn)
            return
        try:
            conn.setblocking(True)
            MessageSocket.send_msg(conn, resp, self.secret)
        except OSError:
            self._drop(conn)
        finally:
            try:
                conn.setblocking(False)
            except OSError:
                pass

    def handle_message(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        """Handler lookup + per-verb service-time timing — the transport-
        free core of a dispatch, shared by this server's own event loop
        and a fleet ``SharedServer`` routing frames to it. Timing is
        recorded even when the handler raises: every registered verb MUST
        show up as an rpc.handle_ms.<verb> histogram after one dispatch
        (the conformance test pins it). Buffer-only recording (telemetry
        journals never write on this thread), so event loops stay
        I/O-free."""
        handler = self._handlers.get(msg.get("type"))
        if handler is None:
            return {"type": "ERR", "error": "unknown message type"}
        t0 = time.monotonic()
        try:
            return handler(msg)
        finally:
            telem = self.telemetry
            if telem is not None:
                telem.observe_ms(
                    "rpc.handle_ms.{}".format(msg.get("type")),
                    (time.monotonic() - t0) * 1e3)

    def _batch(self, msg):
        """Coalesced heartbeat batch: a client whose beats failed to ship
        (driver stall, reconnect storm) re-delivers them as ONE frame —
        ``beats`` is an oldest-first list of METRIC payloads, coalesced
        client-side per trial. Each beat runs through the ordinary METRIC
        handler (so liveness touches, rstats merges, and driver metric
        history all land), and the reply is the NEWEST beat's reply — a
        STOP/preempt decision about a retired beat's trial is stale by
        definition, and heartbeats re-draw STOP until honored anyway."""
        metric = self._handlers.get("METRIC")
        if metric is None:
            return {"type": "ERR",
                    "error": "this server does not accept heartbeats"}
        reply: Dict[str, Any] = {"type": "OK"}
        for beat in msg.get("beats") or []:
            b = dict(beat)
            b["type"] = "METRIC"
            b["partition_id"] = msg["partition_id"]
            b["task_attempt"] = msg.get("task_attempt")
            reply = metric(b)
        return reply

    def _drop(self, conn):
        self._buffers.pop(conn, None)
        try:
            self._sel.unregister(conn)
        except (KeyError, ValueError):
            pass
        try:
            conn.close()
        except OSError:
            pass

    def _loop(self):
        while not self._stop_event.is_set():
            events = self._sel.select(timeout=0.2)
            for key, mask in events:
                key.data(key.fileobj, mask)
            self._tick()
            engine = chaos_engine()
            if engine is not None:
                # Elapsed-time fault triggers ride the event-loop tick —
                # the same cadence the heartbeat-loss scan runs on.
                engine.tick()

    def _tick(self) -> None:
        """Periodic hook run on the event-loop thread between selects."""

    def await_reservations(
        self, timeout: float = constants.REGISTRATION_TIMEOUT_S,
        on_timeout: Optional[Callable[[], None]] = None,
    ) -> Dict[int, Dict[str, Any]]:
        """Driver-side registration barrier (reference `rpc.py:182-205`)."""
        deadline = time.monotonic() + timeout
        while not self.reservations.done():
            if time.monotonic() > deadline:
                if on_timeout:
                    on_timeout()
                raise TimeoutError(
                    "Registration barrier timed out: {} of {} executors missing.".format(
                        self.reservations.remaining(), self.num_executors
                    )
                )
            time.sleep(0.1)
        return self.reservations.all()

    def stop(self):
        if self._shared is not None:
            # Published on a fleet's shared listener: detach this
            # experiment's routing; the shared socket outlives it. The
            # OWN selector was allocated in __init__ but never used —
            # close it or a long-lived fleet host leaks one epoll fd per
            # submitted experiment.
            self._shared.detach(self)
            self._shared = None
            try:
                self._sel.close()
            except OSError:
                pass
            return
        self._stop_event.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        for key in list(self._sel.get_map().values()):
            self._drop(key.fileobj)
        self._sel.close()


class _TenantDispatcher:
    """Bounded per-tenant handler pool: one daemon worker draining one
    FIFO queue of (conn, payload) frames for ONE attached experiment.
    A single worker per tenant keeps the ordering guarantee a dedicated
    listener gave — frames from one connection are handled and replied
    in arrival order — while isolating the tenant's handler latency from
    every other tenant. ``submit`` never blocks: a full queue returns
    False and the caller sheds the frame (per-tenant backpressure)."""

    def __init__(self, shared: "SharedServer", server: "Server",
                 depth: int):
        self.depth = int(depth)
        self._shared = shared
        self._server = server
        self._q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name="rpc-dispatch-{}".format(server.secret_hex[:8]))
        self._thread.start()

    def submit(self, conn, payload: bytes) -> bool:
        try:
            self._q.put_nowait((conn, payload))
            return True
        except queue.Full:
            return False

    def qsize(self) -> int:
        return self._q.qsize()

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        self._thread.join(timeout=timeout)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                conn, payload = self._q.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                self._shared._dispatch(conn, self._server, payload)
            except Exception:  # noqa: BLE001 - one bad frame must not kill the tenant's pool
                pass


class SharedServer:
    """One listening socket multiplexing MANY experiments' control
    planes (fleet mode): each attached per-experiment ``Server`` keeps
    its own handlers, reservations, and secret, and frames route to the
    server whose HMAC secret authenticates them — the first authenticated
    frame binds the connection, so steady-state verification is one HMAC
    like a dedicated listener. Runner re-binding across experiments needs
    no new sockets on the driver host: the runner reconnects to the SAME
    address with the NEW experiment's secret.

    Dispatch architecture: the event loop does PURE frame work — accept,
    reassemble, authenticate/route — and hands each complete frame to the
    target experiment's ``_TenantDispatcher``, a bounded FIFO queue
    drained by one dedicated worker thread per attached server. Handlers
    (and their replies) run on that worker, so one tenant's slow handler
    (a FINAL fast path waiting out its bounded sched-lock timeout, a
    chaos ``delay_msg``, a degraded controller) stalls ONLY its own
    tenant's queue; every other experiment's replies keep flowing at
    loop speed. Ordering: one worker per tenant + in-order enqueue from
    the loop = per-connection FIFO handling and replies, exactly the
    guarantee a dedicated listener gave. Backpressure: a tenant whose
    queue is full has its overflowing frame AND connection shed (counted
    as ``rpc.tenant.backpressure_drops`` on the tenant's registry and
    journaled as a ``shed`` event with ``scope="rpc"``); the client's
    jittered retry/backoff path re-delivers, so a congested tenant slows
    itself down without consuming loop time. ``dispatch_pool=False`` (or
    MAGGY_TPU_SHARED_DISPATCH_POOL=0) restores the legacy
    handlers-on-the-loop behavior for A/B measurement — bench.py --scale
    uses exactly that switch to show the head-of-line isolation.

    The shared event loop also drives each attached server's ``_tick``
    (heartbeat-loss scans) and the chaos engine's elapsed-time triggers,
    exactly as a dedicated loop would."""

    def __init__(self, dispatch_pool: Optional[bool] = None,
                 tenant_queue_depth: Optional[int] = None):
        import os

        if dispatch_pool is None:
            dispatch_pool = os.environ.get(
                "MAGGY_TPU_SHARED_DISPATCH_POOL", "1").strip().lower() \
                not in ("0", "false", "off")
        self.dispatch_pool = bool(dispatch_pool)
        self.tenant_queue_depth = int(
            tenant_queue_depth
            if tenant_queue_depth is not None
            else os.environ.get("MAGGY_TPU_TENANT_QUEUE_DEPTH",
                                constants.TENANT_DISPATCH_QUEUE_DEPTH))
        self._lock = threading.RLock()
        self._servers: Dict[bytes, Server] = {}  # guarded-by: _lock
        self._dispatchers: Dict[bytes, _TenantDispatcher] = {}  # guarded-by: _lock
        self._conn_server: Dict[socket.socket, Server] = {}  # guarded-by: _lock
        self._buffers: Dict[socket.socket, bytearray] = {}  # guarded-by: _lock
        self._sel = selectors.DefaultSelector()
        self._listener: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None
        self._stop_event = threading.Event()
        self.addr: Optional[Tuple[str, int]] = None

    def attach(self, server: Server,
               host: str = "127.0.0.1") -> Tuple[str, int]:
        """Publish ``server`` on the shared listener (started lazily);
        returns the shared (host, port)."""
        with self._lock:
            self._servers[server.secret] = server
            if self.dispatch_pool:
                self._dispatchers[server.secret] = _TenantDispatcher(
                    self, server, self.tenant_queue_depth)
            server._shared = self
            if self._listener is None:
                self._start_locked(host)
        return self.addr

    def detach(self, server: Server) -> None:
        with self._lock:
            self._servers.pop(server.secret, None)
            dispatcher = self._dispatchers.pop(server.secret, None)
            stale = [c for c, s in self._conn_server.items() if s is server]
        for conn in stale:
            self._drop(conn)
        if dispatcher is not None:
            dispatcher.stop()

    def _start_locked(self, host: str, port: int = 0) -> None:
        from maggy_tpu import native

        native.get_lib()  # warm the codec off the event loop (see Server)
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host, port))
        srv.listen(128)
        srv.setblocking(False)
        self._listener = srv
        self.addr = srv.getsockname()
        self._sel.register(srv, selectors.EVENT_READ, self._accept)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="rpc-shared-server")
        self._thread.start()

    def _accept(self, sock, mask):
        conn, _ = sock.accept()
        conn.setblocking(False)
        with self._lock:
            self._buffers[conn] = bytearray()
        self._sel.register(conn, selectors.EVENT_READ, self._serve)

    def _serve(self, conn, mask):
        try:
            chunk = conn.recv(constants.RPC_RECV_BUFSIZE)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._drop(conn)
            return
        if not chunk:
            self._drop(conn)
            return
        with self._lock:
            buf = self._buffers.get(conn)
        if buf is None:
            return
        buf.extend(chunk)
        # Stop at the first drop: routing (shed), a pool-less dispatch,
        # or a bad frame may sever the connection while MORE complete
        # frames sit in the local buffer — continuing would dispatch
        # frames of a closed socket and re-bind it into _conn_server
        # (the sever-mid-frame bookkeeping leak).
        while self._tracked(conn):
            extracted = self._try_extract_frame(conn, buf)
            if extracted is None:
                return
            server, payload = extracted
            self._route(conn, server, payload)

    def _tracked(self, conn) -> bool:
        with self._lock:
            return conn in self._buffers

    def _try_extract_frame(self, conn, buf: bytearray):
        """Pop one complete frame and resolve which experiment it belongs
        to: a bound connection verifies against its server's secret only;
        an unbound one tries every attached secret and binds to the first
        match. No match = unauthenticated peer -> drop."""
        header = 4 + 32
        if len(buf) < header:
            return None
        (length,) = _LEN.unpack(bytes(buf[:4]))
        if length > MAX_FRAME:
            self._drop(conn)
            return None
        if len(buf) < header + length:
            return None
        mac = bytes(buf[4:header])
        payload = bytes(buf[header:header + length])
        with self._lock:
            bound = self._conn_server.get(conn)
            candidates = [bound] if bound is not None \
                else list(self._servers.values())
        server = next(
            (s for s in candidates
             if hmac.compare_digest(mac, _sign(s.secret, payload))), None)
        if server is None:
            self._drop(conn)
            return None
        if bound is None:
            with self._lock:
                # Bind only while the connection is still tracked: a
                # concurrent drop (pool-thread send failure) must not be
                # resurrected as a routing entry for a closed socket.
                if conn not in self._buffers:
                    return None
                self._conn_server[conn] = server
        del buf[:header + length]
        return server, payload

    def _route(self, conn, server: Server, payload: bytes) -> None:
        """Hand one authenticated frame to the tenant's dispatch pool —
        the event loop's ONLY job besides framing. Pool off (legacy /
        A/B) dispatches inline on the loop."""
        with self._lock:
            dispatcher = self._dispatchers.get(server.secret)
        if dispatcher is None:
            self._dispatch(conn, server, payload)
            return
        if not dispatcher.submit(conn, payload):
            # Per-tenant backpressure: THIS tenant's queue is full —
            # shed the frame and the connection (the client's jittered
            # retry re-delivers), leaving other tenants untouched.
            telem = server.telemetry
            if telem is not None:
                telem.metrics.counter(
                    "rpc.tenant.backpressure_drops").inc()
                telem.event("shed", scope="rpc",
                            queue_depth=dispatcher.depth)
            self._drop(conn)

    def _dispatch(self, conn, server: Server, payload: bytes):
        """Mirror of ``Server._dispatch`` with the target server resolved
        per frame: same chaos hooks, same error wrapping, reply signed
        with THAT experiment's secret. Runs on the tenant's dispatcher
        worker (pool mode), so a chaos ``delay_msg`` stalls only the
        targeted tenant — the fault's blast radius matches the new
        architecture's isolation claim."""
        sever_reply = False
        try:
            msg = msgpack.unpackb(payload, raw=False, strict_map_key=False)
            engine = chaos_engine()
            if engine is not None:
                action = engine.on_server_message(msg)
                if action is not None:
                    if action[0] == "drop":
                        self._drop(conn)
                        return
                    if action[0] == "delay":
                        time.sleep(action[1])
                    elif action[0] == "sever":
                        sever_reply = True
            resp = server.handle_message(msg)
        except (ConnectionError, socket.timeout, OSError):
            self._drop(conn)
            return
        except Exception as e:  # noqa: BLE001 - a bad message must never kill the loop
            resp = {"type": "ERR", "error": "handler error: {!r}".format(e)}
        if sever_reply:
            self._drop(conn)
            return
        try:
            conn.setblocking(True)
            MessageSocket.send_msg(conn, resp, server.secret)
        except OSError:
            self._drop(conn)
        finally:
            try:
                conn.setblocking(False)
            except OSError:
                pass

    def _drop(self, conn):
        """Thread-safe teardown of one connection's state — called from
        the event loop AND the tenant dispatcher workers (reply/send
        failures), so every table it touches is lock-guarded and every
        step tolerates a concurrent double-drop."""
        with self._lock:
            self._buffers.pop(conn, None)
            self._conn_server.pop(conn, None)
        try:
            self._sel.unregister(conn)
        except (KeyError, ValueError):
            pass
        try:
            conn.close()
        except OSError:
            pass

    def _loop(self):
        while not self._stop_event.is_set():
            events = self._sel.select(timeout=0.2)
            for key, mask in events:
                key.data(key.fileobj, mask)
            with self._lock:
                servers = list(self._servers.values())
            for server in servers:
                try:
                    server._tick()
                except Exception:  # noqa: BLE001 - one experiment's tick must not kill the loop
                    pass
            engine = chaos_engine()
            if engine is not None:
                engine.tick()

    def stop(self):
        self._stop_event.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        with self._lock:
            servers = list(self._servers.values())
            self._servers.clear()
            dispatchers = list(self._dispatchers.values())
            self._dispatchers.clear()
        for dispatcher in dispatchers:
            dispatcher.stop()
        for server in servers:
            server._shared = None
        for key in list(self._sel.get_map().values()):
            self._drop(key.fileobj)
        self._sel.close()


class FleetAgentServer(Server):
    """The fleet host's control plane for REMOTE AGENTS — the daemon
    processes (``python -m maggy_tpu.fleet agent``) that turn the
    in-process fleet into a cross-process, cross-host one. Published on
    the fleet's ``SharedServer`` under the FLEET secret (the one the
    fleet ticket carries), so agent traffic shares the same listening
    socket as every tenant's control plane and re-binding an agent
    across experiments never needs a new driver-side socket.

    Verbs (the ABIND wire contract, docs/developer.md):

    - ``AJOIN``: an agent declares its capacity (host, chips, process
      index, optional ``coord_addr`` for remote-gang rendezvous, its OS
      pid for same-host chaos kills) and is admitted into an agent slot;
      the reply carries its ``agent`` id plus the poll cadence and
      liveness bound the fleet will hold it to.
    - ``ALEASE``: the agent's idle poll (doubles as its idle heartbeat).
      Replies: ``ABIND`` — a lease: the target experiment's SECRET,
      partition id, executor config, and the train function's dotted
      path (``warm_start`` rides along so the agent keeps warm slots
      across same-family re-leases within its process); ``OK`` — nothing
      to do; ``AGSTOP`` — the fleet is shutting down, exit.
    - ``ADONE``: the agent's executor loop returned (GSTOP observed or
      an error) — the lease closes and the agent returns to the idle
      pool instead of exiting.

    The handlers delegate to the attached ``fleet.agent.AgentPlane``;
    msg-key reads stay HERE so the rpcconf checker sees the full wire
    contract at the handler."""

    def __init__(self, max_agents: int, secret: Optional[str] = None):
        # The plane (maggy_tpu.fleet.agent.AgentPlane), attached by the
        # fleet. None rejects every agent verb.
        self.agent_plane = None
        super().__init__(max_agents, secret)

    def attach_plane(self, plane) -> None:
        self.agent_plane = plane

    def _register_handlers(self) -> None:
        super()._register_handlers()
        self._handlers.update(
            AJOIN=self._ajoin,
            ALEASE=self._alease,
            ADONE=self._adone,
        )

    def _ajoin(self, msg):
        plane = self.agent_plane
        if plane is None:
            return {"type": "ERR",
                    "error": "this fleet does not accept remote agents"}
        return plane.agent_join(
            host=msg.get("host"), chips=msg.get("chips"),
            process_index=msg.get("process_index"),
            coord_addr=msg.get("coord_addr"), os_pid=msg.get("os_pid"),
            agent=msg.get("agent"))

    def _alease(self, msg):
        plane = self.agent_plane
        if plane is None:
            return {"type": "ERR",
                    "error": "this fleet does not accept remote agents"}
        return plane.agent_lease(agent=msg.get("agent"),
                                 offset_s=msg.get("offset_s"),
                                 rtt_s=msg.get("rtt_s"))

    def _adone(self, msg):
        plane = self.agent_plane
        if plane is None:
            return {"type": "ERR",
                    "error": "this fleet does not accept remote agents"}
        return plane.agent_done(agent=msg.get("agent"),
                                error=msg.get("error"))


class SinkServer(Server):
    """The fleet host's JOURNAL SINK tenant (telemetry/sink.py): one
    more server published on the fleet's shared listener, under its OWN
    secret (a journal shipper must not be able to lease agents or speak
    any experiment's control plane). A single verb:

    - ``JSINK``: a batch of journal events from one SOURCE (a fleet-
      attached tenant or a remote agent), each stamped with the source's
      monotonic ``sid`` event id, plus an optional metric-counter
      snapshot for fleet-side federation. The reply acks the highest
      sid the sink now holds — at-least-once shipping with sink-side
      dedup makes delivery exactly-once per event id.

    Batches land on this tenant's ordinary dispatch pool, so journal
    ingestion is isolated from every experiment's control traffic and a
    full sink queue sheds frames (per-tenant backpressure) — which the
    shipper treats as sink death and degrades to its local journal.
    The handler delegates to the attached ``telemetry.sink.JournalSink``;
    msg-key reads stay HERE so the rpcconf checker sees the wire
    contract at the handler."""

    def __init__(self, secret: Optional[str] = None):
        # The sink service (maggy_tpu.telemetry.sink.JournalSink),
        # attached by the fleet. None rejects JSINK.
        self.sink = None
        super().__init__(1, secret)

    def attach_sink(self, sink) -> None:
        self.sink = sink

    def _register_handlers(self) -> None:
        super()._register_handlers()
        self._handlers.update(JSINK=self._jsink)

    def _jsink(self, msg):
        sink = self.sink
        if sink is None:
            return {"type": "ERR",
                    "error": "this fleet has no journal sink attached"}
        return sink.ingest(source=msg.get("source"),
                           events=msg.get("events"),
                           counters=msg.get("counters"),
                           client_t=msg.get("client_t"))


class OptimizationServer(Server):
    """HPO/ablation message semantics (reference `rpc.py:295-388`).

    The driver attaches itself via `attach_driver` so handlers can read
    trial state and enqueue worker messages.
    """

    def __init__(self, num_executors: int, secret: Optional[str] = None):
        self.driver = None
        self._last_loss_scan = time.monotonic()
        super().__init__(num_executors, secret)

    def attach_driver(self, driver) -> None:
        self.driver = driver

    def _register_handlers(self) -> None:
        super()._register_handlers()
        self._handlers.update(
            REG=self._reg,
            METRIC=self._metric,
            BATCH=self._batch,
            FINAL=self._final,
            GET=self._get,
            LOG=self._log,
        )

    def _note_adopted(self, partition_id) -> None:
        """First post-recovery message from a pre-crash partition: the
        runner survived the driver restart and re-bound (same secret,
        same address) — journal the ``adopted`` runner edge exactly once
        (the recovered flag is consumed)."""
        if self.reservations.pop_recovered(partition_id):
            telem = self.telemetry
            if telem is not None:
                telem.event("runner", phase="adopted",
                            partition=int(partition_id))

    def _tick(self) -> None:
        if self.driver is None:
            return
        now = time.monotonic()
        gate = min(1.0, self.hb_loss_timeout / 4) \
            if self.hb_loss_timeout is not None else 1.0
        if now - self._last_loss_scan < gate:
            return
        self._last_loss_scan = now
        check = getattr(self.driver, "periodic_check", None)
        if check is not None:
            try:
                check()
            except Exception:  # noqa: BLE001 - never kill the event loop
                if not self._periodic_check_failed:
                    self._periodic_check_failed = True
                    import traceback

                    traceback.print_exc()
        if self.hb_loss_timeout is None:
            return
        for pid, trial_id in self.reservations.lost_assignments(self.hb_loss_timeout):
            # Clear the assignment first so a racing re-registration takes
            # the BLACK path instead of double-requeueing this trial.
            self.reservations.assign_trial(pid, None)
            self.driver.enqueue({"type": "LOST", "trial_id": trial_id,
                                 "partition_id": pid})

    def _reg(self, msg):
        # Failure detection (reference `rpc.py:308-326`): a re-registration
        # from a partition already holding a trial means the executor died
        # and was relaunched -> mark that trial ERROR, queue BLACK.
        prev = self.reservations.get_assigned_trial(msg["partition_id"])
        self.reservations.add(
            {"partition_id": msg["partition_id"], "host_port": msg.get("host_port"),
             "task_attempt": msg.get("task_attempt", 0), "trial_id": prev,
             "capacity": msg.get("capacity")}
        )
        if prev is not None:
            self.driver.enqueue({"type": "BLACK", "trial_id": prev,
                                 "partition_id": msg["partition_id"]})
        else:
            # First registration: ask the driver worker for a first assignment.
            self.driver.enqueue({"type": "REG",
                                 "partition_id": msg["partition_id"],
                                 "capacity": msg.get("capacity")})
        telem = self.telemetry
        if telem is not None:
            telem.event("runner", phase="registered",
                        partition=int(msg["partition_id"]),
                        capacity=msg.get("capacity"),
                        reregistration=prev is not None)
        return {"type": "OK"}

    def _metric(self, msg):
        self.reservations.touch(msg["partition_id"])
        self._note_adopted(msg["partition_id"])
        telem = self.telemetry
        rstats = msg.pop("rstats", None)
        if rstats and telem is not None:
            # Runner-side stats piggybacked on the heartbeat (bounded,
            # delta-encoded): merge + journal with partition attribution.
            # Popped first so the driver worker's METRIC callback sees the
            # same payload shape it always did.
            telem.record_runner_stats(msg["partition_id"], rstats)
        self.driver.enqueue(dict(msg))
        trial_id = msg.get("trial_id")
        if trial_id and self.reservations.pop_stop(msg["partition_id"],
                                                  trial_id):
            # Gang revocation abort: preempt-shaped so the runner acks
            # and frees itself; the driver already requeued the trial.
            return {"type": "STOP", "span": msg.get("span"),
                    "preempt": True}
        if msg.get("lanes"):
            return self._metric_lanes(msg, trial_id)
        stop = False
        if trial_id:
            trial = self.driver.get_trial(trial_id)
            stop = bool(trial and trial.get_early_stop())
        if stop:
            # The moment the runner is FIRST told to stop: early-stop
            # reaction latency (stop_flagged -> finalized) brackets this
            # hop. once=True — heartbeats keep drawing STOP replies until
            # the training loop honors the flag, and re-journaling each
            # would bloat the journal by heartbeat rate x stop latency.
            # The STOP reply echoes the span so the runner side can
            # attribute the abort without re-deriving it.
            telem = self.telemetry
            if telem is not None:
                telem.trial_event(trial_id, "stop_sent", once=True,
                                  partition=int(msg["partition_id"]))
            # ``preempt``: this stop is a scheduler preemption, not an
            # early-stop verdict — the runner acks with a preempted FINAL
            # (carrying its last checkpoint step) instead of finalizing.
            return {"type": "STOP", "span": msg.get("span"),
                    "preempt": bool(trial and trial.get_preempt())}
        return {"type": "OK"}

    def _metric_lanes(self, msg, leader_id):
        """STOP routing for a vectorized block's heartbeat (one beat, K
        lane-tagged metric entries). Early stopping a lane must NOT tear
        down the block — the reply carries ``stop_lanes`` and the runner
        masks those lanes in place (train/vmap.py). A STOP reply is
        reserved for scheduler preemption, which aborts the whole block."""
        telem = self.telemetry
        stop_lanes = []
        preempt = False
        for beat in msg["lanes"]:
            lane_trial = self.driver.get_trial(beat.get("trial_id"))
            if lane_trial is None or not lane_trial.get_early_stop():
                continue
            if lane_trial.get_preempt():
                preempt = True
                continue
            stop_lanes.append(beat["trial_id"])
            if telem is not None:
                # once=True for the same reason as the scalar stop_sent:
                # the lane keeps appearing in beats until the runner's
                # training loop reaches its next mask boundary.
                telem.trial_event(beat["trial_id"], "stop_sent", once=True,
                                  partition=int(msg["partition_id"]),
                                  lane=beat.get("lane"))
        leader = self.driver.get_trial(leader_id) if leader_id else None
        if preempt or (leader and leader.get_early_stop()
                       and leader.get_preempt()):
            return {"type": "STOP", "span": msg.get("span"),
                    "preempt": True}
        reply = {"type": "OK"}
        if stop_lanes:
            reply["stop_lanes"] = stop_lanes
        return reply

    def _final(self, msg):
        """FINAL dispatch wrapper: the durability barrier runs AFTER the
        handler, BEFORE the reply is written (the dispatcher sends the
        returned dict) — so the journal, crash recovery's source of
        truth, can never trail a FINAL the runner saw acknowledged. On
        the inline fast path the finalized span edge and trial.json are
        both durable by the time the reply leaves; on the worker
        fallback the FINAL is still queued when the reply is written —
        a crash in that window re-runs the trial (at-least-once, never
        lost), documented in docs/developer.md."""
        try:
            return self._final_unbarriered(msg)
        finally:
            telem = self.telemetry
            if telem is not None:
                telem.barrier()

    def _final_unbarriered(self, msg):
        self.reservations.touch(msg["partition_id"])
        self._note_adopted(msg["partition_id"])
        if msg.get("block") is not None and not msg.get("last"):
            # Per-lane FINAL of a vectorized block (one FINAL per lane,
            # train/vmap.py): the partition still holds the block — no
            # assignment clear, no piggybacked hand-off. The driver
            # reports the lane's result to the controller inline so the
            # optimizer sees it at masking time, not at block teardown.
            fast = getattr(self.driver, "process_final_inline", None)
            if fast is None or not fast(msg):
                self.driver.enqueue(dict(msg))
            return {"type": "OK"}
        # Conditional, not assign_trial(None): a RETRIED final (severed /
        # lost reply) must not wipe the next trial assigned in between.
        # For a block's LAST lane the partition's assignment is the block
        # LEADER, which the closing lane need not be — clear by leader.
        self.reservations.clear_trial_if(msg["partition_id"],
                                         msg.get("block") or msg.get("trial_id"))
        # Pipelined hand-off (config.prefetch): the driver processes the
        # FINAL inline on this thread — report to the controller, drop any
        # schedule-stale prefetched suggestion, pick the next assignment —
        # and the reply carries it, so the freed runner skips the GET
        # round trip entirely. False = not processed (prefetch off, lock
        # briefly held by a mid-fit suggester, or an internal error): the
        # legacy path enqueues to the driver worker and the runner falls
        # back to GET polling.
        fast = getattr(self.driver, "process_final_inline", None)
        if fast is None or not fast(msg):
            self.driver.enqueue(dict(msg))
            if self.reservations.evict_requested(msg["partition_id"]) and \
                    msg.get("preempted"):
                # Worker-path preempt ack of an evicted runner: release it
                # now — the enqueued message only requeues the trial, and
                # the runner must not GET-poll an experiment it has been
                # preempted out of.
                self.reservations.mark_released(msg["partition_id"])
                return {"type": "GSTOP"}
            return {"type": "OK"}
        pid = msg["partition_id"]
        telem = self.telemetry
        reply = self._serve_assigned(pid)
        if reply is not None:
            if telem is not None and reply.get("type") == "TRIAL":
                # once=True: a retried FINAL (lost/severed reply)
                # re-serves the same undelivered assignment — one
                # hand-off, one hit, however many deliveries it takes.
                telem.trial_event(reply["trial_id"], "prefetch_hit",
                                  once=True, partition=int(pid))
            return reply
        if self.reservations.evict_requested(pid):
            # Fleet preemption: the runner's ack doubles as its release —
            # it re-binds to another experiment, not to this one's GET.
            self.reservations.mark_released(pid)
            return {"type": "GSTOP"}
        if self.driver.experiment_done:
            # Inline release: the runner's last FINAL doubles as its GSTOP.
            self.reservations.mark_released(pid)
            return {"type": "GSTOP"}
        if telem is not None and not msg.get("preempted"):
            # Nothing ready (controller IDLE / rung barrier / expensive
            # suggest still fitting): the runner falls back to GET.
            # once=True matches the hit side under retried FINALs. A
            # preempted ack is not a hand-off attempt — it must not count
            # as a pipeline miss.
            telem.trial_event(msg.get("trial_id"), "prefetch_miss",
                              once=True, partition=int(pid))
        return {"type": "OK"}

    def _serve_assigned(self, partition_id):
        """The TRIAL reply for the partition's currently-assigned trial —
        shared by GET and the FINAL piggyback. None = no assignment (the
        caller decides between GSTOP/RESIZE/OK)."""
        trial_id = self.reservations.get_assigned_trial(partition_id)
        if trial_id is None:
            return None
        trial = self.driver.get_trial(trial_id)
        if trial is None:
            return {"type": "OK", "trial_id": None}
        trial.set_status(Trial.RUNNING)
        trial.start = time.time()
        # Which runner served it: lets offline analysis (bench.py) compute
        # true per-partition hand-off gaps from the trial.json artifacts.
        with trial.lock:
            trial.info_dict["partition"] = partition_id
            # The run epoch rides in info so the FINAL can echo it: the
            # driver drops a dead run's in-flight FINAL by epoch mismatch
            # (same-partition re-dispatch makes partition checks blind).
            trial.info_dict["epoch"] = trial.run_epoch
            info = dict(trial.info_dict)
        telem = self.telemetry
        if telem is not None:
            # "running" = the TRIAL reply leaves the driver: the hand-off
            # gap's closing edge (its opening edge is the previous trial's
            # "finalized" on the same partition). The run epoch rides
            # along so crash recovery can reconstruct an in-flight
            # trial's epoch — a pre-crash runner's retried FINAL then
            # passes the stale-epoch guard (accepted exactly once), while
            # a dead incarnation's FINAL after a post-recovery requeue
            # (epoch bumped) still drops.
            telem.trial_event(trial.trial_id, "running",
                              partition=int(partition_id),
                              epoch=info.get("epoch"))
        block = info.get("vmap_block")
        if block:
            # Vectorized block delivery: every lane enters RUNNING with the
            # leader — each gets its own running edge so per-lane spans
            # (queued -> running -> finalized) close without inference.
            for entry in block.get("lanes", ()):
                if entry["trial_id"] == trial.trial_id:
                    continue
                lane_trial = self.driver.get_trial(entry["trial_id"])
                if lane_trial is None:
                    continue
                lane_trial.set_status(Trial.RUNNING)
                lane_trial.start = time.time()
                with lane_trial.lock:
                    lane_trial.info_dict["partition"] = partition_id
                    lane_trial.info_dict["epoch"] = lane_trial.run_epoch
                if telem is not None:
                    telem.trial_event(entry["trial_id"], "running",
                                      partition=int(partition_id),
                                      epoch=entry.get("epoch"),
                                      lane=entry.get("lane"),
                                      block=trial.trial_id)
        return {"type": "TRIAL", "trial_id": trial.trial_id,
                "params": trial.params, "info": info,
                "span": info.get("span")}

    def _get(self, msg):
        self.reservations.touch(msg["partition_id"])
        self._note_adopted(msg["partition_id"])
        pid = msg["partition_id"]
        if self.reservations.evict_requested(pid):
            # Fleet preemption of an idle (or between-trials) runner: hand
            # any undelivered assignment back to the schedule as a
            # never-started preemption (requeue-from-scratch) and release
            # the runner so it can re-bind to another experiment.
            tid = self.reservations.get_assigned_trial(pid)
            if tid is not None:
                self.reservations.clear_trial_if(pid, tid)
                self.driver.enqueue({"type": "FINAL", "trial_id": tid,
                                     "partition_id": pid, "preempted": True,
                                     "step": None, "logs": []})
            self.reservations.mark_released(pid)
            return {"type": "GSTOP"}
        # Serve an already-assigned trial BEFORE honoring experiment-done:
        # the last suggestion may be assigned concurrently with another
        # FINAL ending the experiment, and must still run.
        reply = self._serve_assigned(msg["partition_id"])
        if reply is not None:
            return reply
        member = self._serve_gang_member(pid)
        if member is not None:
            return member
        if self.driver.experiment_done:
            self.reservations.mark_released(msg["partition_id"])
            return {"type": "GSTOP"}
        resize = self.reservations.pop_resize(msg["partition_id"])
        if resize is not None:
            # The runner exits and its pool respawns it pinned to
            # ``chips`` chips; released here so liveness checks ignore
            # the gap until it re-registers.
            self.reservations.mark_released(msg["partition_id"])
            return {"type": "RESIZE", "chips": resize}
        return {"type": "OK", "trial_id": None}

    def _serve_gang_member(self, partition_id):
        """REMOTE-gang member delivery: a gang-held member whose gang
        carries a ``rendezvous`` block lives in ANOTHER process, so it
        must run the SPMD program itself (every process of a
        jax.distributed world runs the same program, or the leader's
        collectives hang). Serve it the gang trial ONCE per assembly,
        flagged ``gang_role="member"`` — the executor joins the
        rendezvous, runs the program, discards the result, and never
        finalizes (exactly one FINAL, from the leader). In-process gangs
        (no rendezvous) never reach this: their members keep idling, the
        leader computes over all local chips as before."""
        res = self.reservations
        tid = res.gang_of(partition_id)
        if tid is None or res.get_assigned_trial(partition_id) == tid:
            return None
        gang_info = getattr(self.driver, "gang_info", None)
        info_g = gang_info(tid) if gang_info is not None else None
        if not info_g or not info_g.get("rendezvous"):
            return None
        if int(partition_id) == int(info_g.get("leader", -1)):
            # Assembly window: _gangs is stored a few statements before
            # assign_trial(leader) — a leader GET landing in between
            # must wait for its LEADER assignment, not burn the member
            # latch and run the program twice.
            return None
        if not res.mark_gang_served(partition_id, tid):
            return None
        trial = self.driver.get_trial(tid)
        if trial is None:
            return None
        with trial.lock:
            info = dict(trial.info_dict)
        info["partition"] = int(partition_id)
        info["gang_role"] = "member"
        return {"type": "TRIAL", "trial_id": trial.trial_id,
                "params": trial.params, "info": info,
                "span": info.get("span")}

    def _log(self, msg):
        return {"type": "LOG", **self.driver.progress_snapshot()}


class DistributedServer(Server):
    """Adds the coordinator rendezvous: DIST_CONFIG returns partition-0's
    advertised host plus world size, replacing the reference's TORCH_CONFIG
    MASTER_ADDR/PORT brokering (`rpc.py:391-437`). Runners pass it to
    `jax.distributed.initialize`."""

    def __init__(self, num_executors: int, secret: Optional[str] = None):
        self.driver = None
        self._last_loss_scan = time.monotonic()
        super().__init__(num_executors, secret)

    def attach_driver(self, driver) -> None:
        self.driver = driver

    def _register_handlers(self) -> None:
        super()._register_handlers()
        self._handlers.update(
            REG=self._reg,
            METRIC=self._metric,
            BATCH=self._batch,
            FINAL=self._final,
            DIST_CONFIG=self._dist_config,
            LOG=self._log,
        )

    def _reg(self, msg):
        self.reservations.add(
            {"partition_id": msg["partition_id"], "host_port": msg.get("host_port"),
             "task_attempt": msg.get("task_attempt", 0), "trial_id": None}
        )
        telem = self.telemetry
        if telem is not None:
            telem.event("worker", phase="registered",
                        partition=int(msg["partition_id"]))
        return {"type": "OK"}

    def _metric(self, msg):
        self.reservations.touch(msg["partition_id"])
        telem = self.telemetry
        rstats = msg.pop("rstats", None)
        if rstats and telem is not None:
            telem.record_runner_stats(msg["partition_id"], rstats)
        if self.driver is not None:
            self.driver.enqueue(dict(msg))
        return {"type": "OK"}

    def _final(self, msg):
        # FINAL is a dist worker's last message — it never polls GET/GSTOP,
        # so release its slot here for the remote pool's teardown ack.
        self.reservations.touch(msg["partition_id"])
        self.reservations.mark_released(msg["partition_id"])
        if self.driver is not None:
            self.driver.enqueue(dict(msg))
        telem = self.telemetry
        if telem is not None:
            telem.event("worker", phase="finalized",
                        partition=int(msg["partition_id"]),
                        error=bool(msg.get("error")))
            # Worker-measured rendezvous latency rides the FINAL payload
            # (the dist analogue of a trial span's phase timestamps).
            stats = msg.get("telem") or {}
            if stats.get("rendezvous_ms") is not None:
                telem.observe_ms("dist.rendezvous_ms",
                                 float(stats["rendezvous_ms"]))
        return {"type": "OK"}

    def _tick(self) -> None:
        """An SPMD worker whose heartbeats stopped is dead, and a dead rank
        wedges every collective in the world — surface it instead of letting
        the experiment (and a remote pool's completion wait) hang forever."""
        if self.hb_loss_timeout is None or self.driver is None:
            return
        now = time.monotonic()
        if now - self._last_loss_scan < min(1.0, self.hb_loss_timeout / 4):
            return
        self._last_loss_scan = now
        for pid in self.reservations.silent(self.hb_loss_timeout):
            self.reservations.mark_released(pid)
            self.driver.enqueue({"type": "DEAD_WORKER", "partition_id": pid})

    def _dist_config(self, msg):
        rec = self.reservations.get(0)
        if rec is None or not self.reservations.done():
            return {"type": "OK", "config": None}
        return {
            "type": "DIST_CONFIG",
            "config": {
                "coordinator_address": rec["host_port"],
                "num_processes": self.num_executors,
            },
        }

    def _log(self, msg):
        snap = self.driver.progress_snapshot() if self.driver else {}
        return {"type": "LOG", **snap}


# --------------------------------------------------------------------- client


class Client:
    """Executor-side control-plane client (reference `rpc.py:440-593`).

    One request socket + one dedicated heartbeat socket; the heartbeat
    daemon ships (metric, step, logs) every ``hb_interval`` and applies STOP
    replies to the reporter.
    """

    def __init__(
        self,
        server_addr: Tuple[str, int],
        partition_id: int,
        task_attempt: int,
        hb_interval: float,
        secret: str,
    ):
        self.server_addr = tuple(server_addr)
        self.partition_id = partition_id
        self.task_attempt = task_attempt
        self.hb_interval = hb_interval
        self.secret = secret.encode() if isinstance(secret, str) else secret
        self.done = False
        self.last_info: dict = {}
        # Next assignment piggybacked on a FINAL reply (pipelined
        # hand-off): (trial_id, params, info), consumed by the next
        # get_suggestion call without any round trip.
        self._piggyback: Optional[tuple] = None
        # Reconnect generation (bumped by _request's reconnect path): lets
        # pollers notice a reconnect happened mid-loop and restart their
        # adaptive backoff from the fast end.
        self.reconnects = 0
        # Runner-side stat buffer (telemetry.runnerstats.RunnerStats),
        # attached by the executor. When set, the heartbeat loop measures
        # its round-trip time into it and piggybacks the delta-encoded
        # stats on the METRIC payload ("rstats" field) — no new socket.
        self.runner_stats = None
        self._sock = self._connect()
        self._hb_sock = self._connect()
        self._hb_thread: Optional[threading.Thread] = None
        self._hb_stop = threading.Event()
        self._lock = threading.Lock()  # serializes the request socket

    def _connect(self) -> socket.socket:
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.settimeout(30.0)
        sock.connect(self.server_addr)
        return sock

    def _request(self, msg: Dict[str, Any], sock: Optional[socket.socket] = None,
                 lock: bool = True) -> Dict[str, Any]:
        """Send one message with reconnect retries (reference `rpc.py:465-493`).

        Retries back off exponentially with full jitter, capped: the fixed
        cadence this replaces synchronized every client's retry storm onto
        a recovering server (64 runners reconnecting in lockstep after a
        driver stall is its own outage). Retries and reconnects are
        counted in ``CLIENT_METRICS`` so chaos soaks can assert the
        degraded paths actually ran."""
        import random as _random

        target = sock or self._sock
        msg = {**msg, "partition_id": self.partition_id,
               "task_attempt": self.task_attempt}
        last_err = None
        delay = constants.CLIENT_RETRY_BACKOFF_BASE_S
        for attempt in range(constants.CLIENT_MAX_RETRIES + 1):
            engine = chaos_engine()
            if engine is not None:
                # May sleep (cooperative stall) or raise ChaosKilled (a
                # condemned runner dies here, outside the retry net).
                engine.on_client_request(msg)
            try:
                if lock and target is self._sock:
                    with self._lock:
                        MessageSocket.send_msg(target, msg, self.secret)
                        return MessageSocket.recv_msg(target, self.secret)
                MessageSocket.send_msg(target, msg, self.secret)
                return MessageSocket.recv_msg(target, self.secret)
            except ChaosKilled:
                raise
            except (ConnectionError, socket.timeout, OSError) as e:
                last_err = e
                if attempt >= constants.CLIENT_MAX_RETRIES:
                    break
                CLIENT_METRICS.counter("rpc.client.retries").inc()
                # Full jitter in [delay/2, delay]: staggered, still bounded.
                time.sleep(delay * (0.5 + 0.5 * _random.random()))
                delay = min(delay * 2, constants.CLIENT_RETRY_BACKOFF_CAP_S)
                try:
                    fresh = self._connect()
                except OSError as conn_err:
                    # Server not back yet: keep the stale socket as the
                    # nominal target and burn another attempt.
                    last_err = conn_err
                    continue
                CLIENT_METRICS.counter("rpc.client.reconnects").inc()
                self.reconnects += 1
                if target is self._sock:
                    self._sock = fresh
                elif target is self._hb_sock:
                    self._hb_sock = fresh
                target = fresh
        raise ConnectionError("RPC request failed after retries: {}".format(last_err))

    # ----------------------------------------------------------------- calls

    def register(self, host_port: Optional[str] = None,
                 capacity: Optional[int] = None) -> None:
        """``capacity``: chips this runner is pinned to (elastic pools);
        None for non-elastic runners."""
        msg = {"type": "REG", "host_port": host_port}
        if capacity is not None:
            msg["capacity"] = int(capacity)
        self._request(msg)

    def await_reservations(self, timeout: float = constants.REGISTRATION_TIMEOUT_S) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            resp = self._request({"type": "QUERY"})
            if resp.get("done"):
                return
            time.sleep(constants.CLIENT_POLL_INTERVAL_S)
        raise TimeoutError("Registration barrier not reached.")

    @staticmethod
    def _queue_beat(pending: list, payload: Dict[str, Any]) -> None:
        """Bank a failed beat for BATCH re-delivery: coalesce with the
        newest pending beat when both describe the SAME trial (keep the
        fresher metric/step/span, concatenate logs — the driver only
        wants the latest sample plus every log line), and bound the
        backlog to CLIENT_MAX_PENDING_BEATS, dropping oldest-first (the
        pre-batching behavior for ALL failed beats). The caller strips
        ``rstats`` first: that delta requeues through the runner-stats
        buffer's own ledger and must not ship twice."""
        beat = {k: v for k, v in payload.items() if k != "rstats"}
        if pending and pending[-1].get("trial_id") == beat.get("trial_id"):
            merged = dict(beat)
            # Bounded, newest-last: an unbounded concatenation would let
            # a chatty trial grow one banked beat past MAX_FRAME over a
            # long outage — the beat-count bound alone caps nothing.
            merged["logs"] = ((pending[-1].get("logs") or [])
                              + (beat.get("logs") or []))[
                -constants.CLIENT_MAX_PENDING_LOG_LINES:]
            pending[-1] = merged
            return
        pending.append(beat)
        del pending[:-constants.CLIENT_MAX_PENDING_BEATS]

    def start_heartbeat(self, reporter) -> None:
        def beat():
            # Beats whose ship failed, oldest first — re-delivered as ONE
            # BATCH frame on the next successful beat instead of being
            # silently lost (and instead of a reconnect storm replaying
            # them one frame at a time against a recovering driver).
            pending: list = []
            while not self._hb_stop.is_set():
                try:
                    data = reporter.get_data()
                except Exception as e:  # noqa: BLE001
                    # Metric materialization failures (poisoned device
                    # value) must neither kill this thread NOR silence the
                    # beat: a missed beat reads as runner death -> false
                    # LOST -> duplicate trial run. Beat with no metric.
                    try:
                        reporter.log("heartbeat error: {!r}".format(e))
                    except Exception:  # noqa: BLE001
                        pass
                    data = {"metric": None, "step": None, "logs": []}
                sent_tid = data.get("trial_id", reporter.trial_id)
                payload = {"type": "METRIC", "trial_id": sent_tid,
                           "value": data["metric"], "step": data["step"],
                           "logs": data["logs"],
                           # The span the (metric, step) pair belongs to —
                           # same rollover rule as sent_tid.
                           "span": data.get("span")}
                if data.get("lanes"):
                    # Vectorized block: one beat, K lane-tagged metric
                    # entries (the batched-beat path ships them as one
                    # frame either way).
                    payload["lanes"] = data["lanes"]
                stats = self.runner_stats
                delta = None
                if stats is not None:
                    delta = stats.snapshot_delta()
                    if delta:
                        payload["rstats"] = delta
                if pending:
                    # The current beat rides LAST so the server's reply
                    # (STOP decisions included) is about the newest data.
                    send = {"type": "BATCH", "beats": pending + [payload]}
                else:
                    send = payload
                t_send = time.monotonic()
                try:
                    resp = self._request(send, sock=self._hb_sock,
                                         lock=False)
                    if pending:
                        CLIENT_METRICS.counter(
                            "rpc.client.batched_beats").inc(len(pending))
                        pending = []
                    if stats is not None:
                        # Retries/backoff included ON PURPOSE: this is the
                        # control-plane latency the runner experiences, the
                        # signal the health engine's RTT-degradation check
                        # feeds on.
                        stats.observe_hb_rtt(
                            (time.monotonic() - t_send) * 1e3)
                    if resp.get("type") == "STOP":
                        # Only stop the trial the beat was ABOUT: the
                        # runner may have rolled over to the next trial
                        # while this beat was in flight. ``preempt``
                        # marks a scheduler preemption (ack with a
                        # preempted FINAL, not a finalize).
                        reporter.early_stop(trial_id=sent_tid,
                                            preempt=bool(
                                                resp.get("preempt")))
                    elif resp.get("stop_lanes"):
                        # Per-lane early stops of a vectorized block: the
                        # training loop consumes these via
                        # take_stopped_lanes() and masks the lanes in
                        # place — the block keeps running.
                        reporter.stop_lanes(resp["stop_lanes"])
                except ConnectionError:
                    if stats is not None and delta:
                        # The ship failed — put the delta back so the next
                        # beat re-sends it instead of silently losing it.
                        stats.requeue_delta(delta)
                    self._queue_beat(pending, payload)
                except ValueError:
                    # Frame too large (send_msg's MAX_FRAME guard): the
                    # banked batch can never ship — drop it rather than
                    # retry-grow it forever or kill this thread (a dead
                    # heartbeat thread reads as runner death).
                    pending = []
                    if stats is not None and delta:
                        stats.requeue_delta(delta)
                self._hb_stop.wait(self.hb_interval)

        self._hb_thread = threading.Thread(target=beat, daemon=True, name="heartbeat")
        self._hb_thread.start()

    def get_suggestion(self, timeout: Optional[float] = None):
        """Blocking poll for the next trial; returns (trial_id, params) or
        (None, None) when the experiment is over (reference `rpc.py:537-546`).

        Zero-round-trip fast path: an assignment piggybacked on the last
        FINAL reply (see ``finalize_metric``) is returned immediately
        without touching the wire — GET polling is the fallback for
        registration, idle wake-ups, and requeues.

        Adaptive poll: the common miss is the race between this GET and the
        driver worker processing the FINAL we just sent (sub-ms), so the
        first retries come fast (5 ms doubling) and only a genuinely idle
        wait (rung barrier) backs off to the 0.1 s driver tick — per-trial
        hand-off latency stays in single-digit ms instead of a flat 0.1 s.
        The backoff restarts from the fast end after a reconnect: the
        post-reconnect state is a fresh race (the driver likely processed
        our retried message already), not a continuation of the idle wait
        the decayed tick was calibrated for."""
        pg = self._piggyback
        if pg is not None:
            self._piggyback = None
            trial_id, params, info = pg
            self.last_info = info
            return trial_id, params
        if self.done:
            return None, None
        deadline = time.monotonic() + timeout if timeout else None
        delay = constants.CLIENT_GET_POLL_MIN_S
        reconnect_gen = self.reconnects
        while True:
            resp = self._request({"type": "GET"})
            if self.reconnects != reconnect_gen:
                reconnect_gen = self.reconnects
                delay = constants.CLIENT_GET_POLL_MIN_S
            rtype = resp.get("type")
            if rtype == "GSTOP":
                self.done = True
                return None, None
            if rtype == "TRIAL":
                # Scheduler metadata (budget, promoted-trial parent, sample
                # type) rides along for TrialContext consumers.
                self.last_info = resp.get("info", {})
                return resp["trial_id"], resp["params"]
            if rtype == "RESIZE":
                # Elastic pools: this process must exit and be respawned
                # pinned to resp["chips"] chips (pinning happens before
                # backend init, so it cannot resize in place).
                self.done = True
                return RESIZE, {"chips": resp["chips"]}
            if deadline and time.monotonic() > deadline:
                return None, None
            time.sleep(delay)
            delay = min(delay * 2, constants.DRIVER_IDLE_REQUEUE_TICK_S)

    def get_dist_config(self, timeout: float = constants.RENDEZVOUS_TIMEOUT_S):
        """Blocking poll for the coordinator rendezvous config. Same
        adaptive fast-start poll as GET (the common wait is the last
        sibling's REG landing milliseconds after ours), backing off to
        CLIENT_DIST_CONFIG_POLL_MAX_S for a genuinely slow world; resets
        after a reconnect like GET does."""
        deadline = time.monotonic() + timeout
        delay = constants.CLIENT_GET_POLL_MIN_S
        reconnect_gen = self.reconnects
        while time.monotonic() < deadline:
            resp = self._request({"type": "DIST_CONFIG"})
            if self.reconnects != reconnect_gen:
                reconnect_gen = self.reconnects
                delay = constants.CLIENT_GET_POLL_MIN_S
            if resp.get("config"):
                return resp["config"]
            time.sleep(delay)
            delay = min(delay * 2, constants.CLIENT_DIST_CONFIG_POLL_MAX_S)
        raise TimeoutError("Coordinator rendezvous timed out.")

    def _handle_final_reply(self, resp: Dict[str, Any]) -> None:
        """Bank a FINAL reply's piggybacked next assignment (TRIAL) or
        release (GSTOP) so the next get_suggestion is wire-free."""
        rtype = resp.get("type")
        if rtype == "TRIAL":
            self._piggyback = (resp["trial_id"], resp["params"],
                               resp.get("info", {}))
        elif rtype == "GSTOP":
            self.done = True

    def finalize_metric(self, metric, reporter,
                        extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Send FINAL and reset the reporter atomically under its lock
        (reference `rpc.py:584-593`). ``extra`` merges additional payload
        fields (e.g. a dist worker's telemetry stats). The reply may
        piggyback the next assignment (pipelined hand-off) — banked for
        the next get_suggestion call — and is returned for callers that
        want to inspect it."""
        with reporter.lock:
            data = reporter.get_data()
            # No "span" key: the driver attributes FINALs through the span
            # tracker by trial id (spans are per trial, not per attempt),
            # so a span echo here was dead payload — the rpcconf checker
            # flags any key no handler reads.
            resp = self._request(
                {"type": "FINAL", "trial_id": reporter.trial_id,
                 "value": metric, "logs": data["logs"],
                 "epoch": (self.last_info or {}).get("epoch"),
                 **(extra or {})}
            )
            reporter.reset()
        self._handle_final_reply(resp)
        return resp

    def finalize_error(self, trial_id: str, reporter) -> Dict[str, Any]:
        """Report a failed trial (train_fn raised): FINAL with the error
        flag, no metric. Routed through the same reply handling as
        finalize_metric so an errored trial's freed runner still gets its
        piggybacked next assignment."""
        with reporter.lock:
            data = reporter.get_data()
            resp = self._request(
                {"type": "FINAL", "trial_id": trial_id, "value": None,
                 "error": True, "logs": data["logs"],
                 "epoch": (self.last_info or {}).get("epoch")}
            )
            reporter.reset()
        self._handle_final_reply(resp)
        return resp

    def finalize_lane(self, trial_id: str, metric, reporter, *,
                      lane: int, block: str, epoch=None, last: bool = False,
                      error: bool = False) -> Dict[str, Any]:
        """Send one lane's FINAL for a vectorized K-lane block. Every lane
        gets its own FINAL; only the ``last`` one releases the partition
        (the server skips the assignment clear and the piggybacked
        hand-off for the others) and resets the reporter. ``epoch`` is the
        LANE trial's run epoch (stamped per lane in the block's TRIAL
        info) — the leader's epoch would let a stale lane FINAL through
        the driver's epoch guard."""
        with reporter.lock:
            data = reporter.get_data() if last else {"logs": []}
            payload = {"type": "FINAL", "trial_id": trial_id,
                       "value": None if error else metric,
                       "logs": data.get("logs") or [],
                       "epoch": epoch,
                       "lane": int(lane), "block": block,
                       "last": bool(last)}
            if error:
                payload["error"] = True
            resp = self._request(payload)
            if last:
                reporter.reset()
        if last:
            self._handle_final_reply(resp)
        return resp

    def preempt_ack(self, trial_id: str, reporter,
                    step: Optional[int] = None) -> Dict[str, Any]:
        """Acknowledge a scheduler preemption: FINAL flagged ``preempted``
        with the trial's last checkpoint ``step`` (None = it never
        checkpointed; the driver requeues from scratch). Routed through
        the same reply handling as finalize_metric so an evicted runner's
        GSTOP — or a surviving runner's piggybacked next assignment —
        lands the same way."""
        with reporter.lock:
            data = reporter.get_data()
            resp = self._request(
                {"type": "FINAL", "trial_id": trial_id, "value": None,
                 "preempted": True,
                 "step": int(step) if step is not None else None,
                 "logs": data["logs"]}
            )
            reporter.reset()
        self._handle_final_reply(resp)
        return resp

    def get_progress(self) -> Dict[str, Any]:
        return self._request({"type": "LOG"})

    def stop(self) -> None:
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2)
        stats = self.runner_stats
        if stats is not None:
            # Last-gasp stats flush: the final trial's pending records
            # (e.g. its ``compile_events`` ttfm breakdown, finalized at
            # trial end) would otherwise wait for a heartbeat that never
            # comes — the GSTOP that ended the work loop also ends the
            # beats. Idle-beat shaped (trial_id None), so the driver
            # worker treats it like any other metric-free beat. ONE
            # attempt, no retry loop, and a short socket deadline: a
            # server that is already gone (or half-open after a severed
            # connection) must not stall shutdown — without the clamp the
            # 30 s request timeout applies to send AND recv.
            try:
                delta = stats.snapshot_delta()
                if delta:
                    msg = {"type": "METRIC", "trial_id": None,
                           "value": None, "step": None, "logs": [],
                           "span": None, "rstats": delta,
                           "partition_id": self.partition_id,
                           "task_attempt": self.task_attempt}
                    with self._lock:
                        self._sock.settimeout(2.0)
                        MessageSocket.send_msg(self._sock, msg, self.secret)
                        MessageSocket.recv_msg(self._sock, self.secret)
            except Exception:  # noqa: BLE001 - shutdown must not fail
                pass
        for sock in (self._sock, self._hb_sock):
            try:
                sock.close()
            except OSError:
                pass
