"""Runner pools: the fan-out substrate replacing Spark executors.

The reference fans out via ``sc.parallelize(range(N), N).foreachPartition``
(`driver.py:96-106`) onto long-lived Spark executors. Here a RunnerPool
launches N trial-runner workers and blocks until all return:

- `ThreadRunnerPool`: N in-process threads. Default for single-host runs —
  JAX releases the GIL during XLA compute, and concurrent trials on one
  host naturally share the chip(s). Also the test substrate (SURVEY.md §4's
  "in-process fake runner" made real).
- `ProcessRunnerPool`: N forked/spawned local processes, one JAX runtime
  each; used when trials must not share a Python runtime.
- `TPURunnerPool`: N processes, each pinned to a disjoint TPU chip sub-slice
  via TPU_VISIBLE_CHIPS/TPU_PROCESS_BOUNDS env vars, so >=64 concurrent
  trials can run on a v4-32 pod (BASELINE north star). Process env setup
  must happen BEFORE jax/libtpu initialization, hence process pools.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import threading
import traceback
from abc import ABC, abstractmethod
from typing import Callable, List, Optional


class RunnerPool(ABC):
    def __init__(self, num_workers: int):
        self.num_workers = num_workers

    @abstractmethod
    def run(self, worker_fn: Callable[[int], None]) -> None:
        """Run ``worker_fn(partition_id)`` on all workers; block until done.

        Worker exceptions propagate after all workers finish (the driver's
        failure-detection path handles per-trial errors; an exception here
        means the runner itself is broken).
        """


class ThreadRunnerPool(RunnerPool):
    def run(self, worker_fn: Callable[[int], None]) -> None:
        errors: List[BaseException] = []
        lock = threading.Lock()

        def target(pid: int):
            try:
                worker_fn(pid)
            except BaseException as e:  # noqa: BLE001
                with lock:
                    errors.append(e)
                traceback.print_exc()

        threads = [
            threading.Thread(target=target, args=(i,), name="runner-{}".format(i))
            for i in range(self.num_workers)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]


def _process_entry(worker_fn, pid, chip_env):
    # Device pinning must precede any jax import in the child.
    for k, v in (chip_env or {}).items():
        os.environ[k] = v
    worker_fn(pid)


class ProcessRunnerPool(RunnerPool):
    """One OS process per runner. ``train_fn`` must be module-level picklable
    (declarative specs travel; closures need ThreadRunnerPool)."""

    def __init__(self, num_workers: int, start_method: str = "spawn",
                 chip_env_fn: Optional[Callable[[int], dict]] = None):
        super().__init__(num_workers)
        self.start_method = start_method
        self.chip_env_fn = chip_env_fn

    def run(self, worker_fn: Callable[[int], None]) -> None:
        ctx = mp.get_context(self.start_method)
        procs = []
        for i in range(self.num_workers):
            env = self.chip_env_fn(i) if self.chip_env_fn else {}
            p = ctx.Process(target=_process_entry, args=(worker_fn, i, env),
                            name="runner-{}".format(i))
            p.start()
            procs.append(p)
        failed = []
        for p in procs:
            p.join()
            if p.exitcode != 0:
                failed.append(p.name)
        if failed:
            raise RuntimeError("Runner processes failed: {}".format(failed))


class TPURunnerPool(ProcessRunnerPool):
    """Per-trial TPU chip pinning: runner i sees only its chip subset.

    On a TPU VM with C local chips and ``chips_per_trial`` k, runner i gets
    chips [i*k, (i+1)*k). libtpu reads TPU_VISIBLE_CHIPS (v4+: bounds via
    TPU_PROCESS_BOUNDS/TPU_CHIPS_PER_PROCESS_BOUNDS) before backend init —
    this is the TPU analogue of the reference pinning one GPU per Spark
    executor.
    """

    def __init__(self, num_workers: int, chips_per_trial: int = 1,
                 total_chips: Optional[int] = None):
        if total_chips is not None and num_workers * chips_per_trial > total_chips:
            raise ValueError(
                "{} workers x {} chips/trial exceeds the {} chips on this "
                "host.".format(num_workers, chips_per_trial, total_chips)
            )

        def chip_env(i: int) -> dict:
            k = chips_per_trial
            chips = ",".join(str(c) for c in range(i * k, (i + 1) * k))
            # TPU_VISIBLE_CHIPS alone defines the per-process sub-slice;
            # libtpu derives its bounds from the visible set, so forcing
            # 1x1x1 bounds here would contradict multi-chip trials.
            return {
                "TPU_VISIBLE_CHIPS": chips,
                "ALLOW_MULTIPLE_LIBTPU_LOAD": "1",
            }

        super().__init__(num_workers, start_method="spawn", chip_env_fn=chip_env)
        self.chips_per_trial = chips_per_trial
        self.total_chips = total_chips
