"""Runner pools: the fan-out substrate replacing Spark executors.

The reference fans out via ``sc.parallelize(range(N), N).foreachPartition``
(`driver.py:96-106`) onto long-lived Spark executors. Here a RunnerPool
launches N trial-runner workers and blocks until all return:

- `ThreadRunnerPool`: N in-process threads. Default for single-host runs —
  JAX releases the GIL during XLA compute, and concurrent trials on one
  host naturally share the chip(s). Also the test substrate (SURVEY.md §4's
  "in-process fake runner" made real).
- `ProcessRunnerPool`: N forked/spawned local processes, one JAX runtime
  each; used when trials must not share a Python runtime.
- `TPURunnerPool`: N processes, each pinned to a disjoint TPU chip sub-slice
  via TPU_VISIBLE_CHIPS/TPU_PROCESS_BOUNDS env vars, so >=64 concurrent
  trials can run on a v4-32 pod (BASELINE north star). Process env setup
  must happen BEFORE jax/libtpu initialization, hence process pools.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import threading
import time
import traceback
from abc import ABC, abstractmethod
from typing import Callable, List, Optional


# Env vars that make a TPU-plugin sitecustomize bootstrap (and therefore
# import jax + dial the accelerator tunnel) at interpreter startup in EVERY
# child python process. A child that is pinned to CPU must never pay that
# cost: it cannot use the chip, the bootstrap import dominates spawn latency
# on a loaded host, and a wedged accelerator claim can hang the child before
# it reaches user code. Interpreter-startup hooks run before
# ``_process_entry`` executes, so these must be stripped in the PARENT
# around ``Process.start()``.
_ACCEL_BOOTSTRAP_VARS = ("PALLAS_AXON_POOL_IPS",)

_spawn_env_lock = threading.Lock()


class _cpu_child_env:
    """Context manager: while spawning, drop accelerator-bootstrap env vars
    when the child is CPU-bound (JAX_PLATFORMS=cpu), so its interpreter
    starts without importing jax or touching the accelerator. No-op when
    the child may need the accelerator."""

    def __enter__(self):
        self._saved = {}
        self._active = os.environ.get("JAX_PLATFORMS", "") == "cpu"
        if not self._active:
            return self
        _spawn_env_lock.acquire()
        for k in _ACCEL_BOOTSTRAP_VARS:
            if k in os.environ:
                self._saved[k] = os.environ.pop(k)
        return self

    def __exit__(self, *exc):
        if self._active:
            os.environ.update(self._saved)
            _spawn_env_lock.release()
        return False


_DEVICE_PROBE_CODE = """\
import os, sys
if os.environ.get("JAX_PLATFORMS", "") == "cpu":
    # Env alone can lose to a site-preimported TPU plugin; force it.
    import jax
    jax.config.update("jax_platforms", "cpu")
import jax
ds = jax.local_devices()
coords = {getattr(d, "coords", None) for d in ds}
n_chips = len(ds) if None in coords else len(coords)
sys.stdout.write("{} {}".format(n_chips, len(ds)))
"""


def _probe_local_devices(timeout_s: float = 120.0):
    """(chips, devices) counted in a THROWAWAY subprocess. The driver
    process must never initialize the JAX/libtpu backend itself: for
    process/TPU pools the children pin chips via env vars read at THEIR
    backend init, and a driver-side init would claim every local chip
    first (the exact hazard process pools exist to avoid). Chips are
    counted by distinct device.coords — on 2-TensorCore chips (v2/v3)
    devices != chips and TPU_VISIBLE_CHIPS pinning is per chip."""
    import subprocess
    import sys

    env = None
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        env = {k: v for k, v in os.environ.items()
               if k not in _ACCEL_BOOTSTRAP_VARS}
    out = subprocess.run(
        [sys.executable, "-c", _DEVICE_PROBE_CODE],
        timeout=timeout_s, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, env=env).stdout
    chips, devices = out.decode().split()
    return int(chips), int(devices)


def resolve_num_workers(config) -> int:
    """``num_workers="auto"``: size the pool from the runtime device
    inventory instead of a hardcoded count — the TPU-native analogue of
    the reference reading the executor count from cluster conf at runtime
    (`hopsworks.py:236-244`). One runner per local chip subset for the
    TPU pool; one per local device otherwise. Remote pools must stay
    explicit: agents JOIN dynamically, the driver only caps admission."""
    nw = getattr(config, "num_workers", 1)
    if nw != "auto":
        return int(nw)
    pool = getattr(config, "pool", "thread")
    if pool == "remote":
        raise ValueError(
            "num_workers='auto' is for local pools; remote agents join "
            "dynamically — set the admission cap explicitly.")
    try:
        chips, devices = _probe_local_devices()
    except Exception as e:  # noqa: BLE001 - probe subprocess failed/hung
        raise ValueError(
            "num_workers='auto' could not probe the device inventory "
            "({!r}); pass an explicit count.".format(e)) from e
    if pool in ("tpu", "elastic"):
        return max(1, chips // max(1, getattr(config, "chips_per_trial", 1)))
    return max(1, devices)


class RunnerPool(ABC):
    def __init__(self, num_workers: int):
        self.num_workers = num_workers

    @abstractmethod
    def run(self, worker_fn: Callable[[int], None]) -> List[BaseException]:
        """Run ``worker_fn(partition_id)`` on all workers; block until done.

        Returns the list of runner failures (exceptions or RuntimeErrors for
        dead processes) instead of raising: a dead runner is survivable — the
        driver requeues its trial onto surviving runners (heartbeat-loss
        detection) and only escalates if the experiment could not complete.
        """

    def terminate(self) -> None:
        """Force-stop all workers (best effort). Used when the experiment is
        already doomed (e.g. a dead SPMD rank) and surviving workers may be
        wedged waiting on it. Threads cannot be killed — only process-backed
        pools act on this."""

    def kill_worker(self, partition_id: int) -> bool:
        """Kill ONE hung worker (best effort), leaving the rest of the pool
        running. Called by heartbeat-loss detection: a runner wedged inside
        an uninterruptible native call (XLA compile, a stuck device op)
        stops heartbeating but never returns, and without this its
        process would block the pool's final join forever — the hang case
        Spark's task-retry machinery covered for free in the reference.
        Returns True if a worker was actually killed. Thread pools cannot
        kill (Python threads are not interruptible): they return False and
        rely on the requeue alone, so wedge-resilience needs a process
        pool ('process'/'tpu')."""
        return False

    def stall_worker(self, partition_id: int, duration_s: float) -> bool:
        """Freeze ONE worker for ``duration_s`` seconds (fault injection:
        maggy_tpu.chaos ``stall_runner`` — the straggler/compile-stall
        simulator). Process pools SIGSTOP the process and SIGCONT it from
        a timer; thread pools return False and the chaos engine falls
        back to a cooperative RPC-hook stall."""
        return False


class ThreadRunnerPool(RunnerPool):
    def run(self, worker_fn: Callable[[int], None]) -> List[BaseException]:
        errors: List[BaseException] = []
        lock = threading.Lock()

        def target(pid: int):
            try:
                worker_fn(pid)
            except BaseException as e:  # noqa: BLE001
                with lock:
                    errors.append(e)
                traceback.print_exc()

        threads = [
            threading.Thread(target=target, args=(i,), name="runner-{}".format(i))
            for i in range(self.num_workers)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return errors


def chip_env(index: int, chips_per_trial: int = 1) -> dict:
    """Env vars pinning one runner to its disjoint TPU chip subset: runner
    ``index`` sees chips [index*k, (index+1)*k). libtpu reads
    TPU_VISIBLE_CHIPS before backend init — the TPU analogue of the
    reference pinning one GPU per Spark executor. Shared by the local
    TPURunnerPool (process pools) and the remote agent's --chips-per-agent
    / --agent-index flags (one agent per chip subset on each pod VM).

    TPU_VISIBLE_CHIPS alone defines the per-process sub-slice; libtpu
    derives its bounds from the visible set, so forcing 1x1x1 bounds here
    would contradict multi-chip trials.
    """
    chips = ",".join(str(c) for c in
                     range(index * chips_per_trial,
                           (index + 1) * chips_per_trial))
    return {
        "TPU_VISIBLE_CHIPS": chips,
        "ALLOW_MULTIPLE_LIBTPU_LOAD": "1",
    }


def _process_entry(worker_fn, pid, chip_env):
    # Device pinning must precede any jax import in the child.
    for k, v in (chip_env or {}).items():
        os.environ[k] = v
    worker_fn(pid)


def _stall_process(p, duration_s: float) -> bool:
    """SIGSTOP ``p`` now, SIGCONT it from a daemon timer after
    ``duration_s`` (fault injection: a straggler whose heartbeats freeze
    mid-trial). Best effort: a process that exits during the stall is
    simply not resumed."""
    import signal
    import threading as _threading

    if not (p.is_alive() and p.pid):
        return False
    try:
        os.kill(p.pid, signal.SIGSTOP)
    except OSError:
        return False

    def _resume():
        try:
            if p.is_alive():
                os.kill(p.pid, signal.SIGCONT)
        except OSError:
            pass

    t = _threading.Timer(duration_s, _resume)
    t.daemon = True
    t.start()
    return True


class ProcessRunnerPool(RunnerPool):
    """One OS process per runner. ``train_fn`` must be module-level picklable
    (declarative specs travel; closures need ThreadRunnerPool)."""

    def __init__(self, num_workers: int, start_method: str = "spawn",
                 chip_env_fn: Optional[Callable[[int], dict]] = None):
        super().__init__(num_workers)
        self.start_method = start_method
        self.chip_env_fn = chip_env_fn
        self._procs: list = []

    def terminate(self) -> None:
        for p in self._procs:
            if p.is_alive():
                p.terminate()

    def kill_worker(self, partition_id: int) -> bool:
        # SIGKILL, not SIGTERM: a SIGSTOPped or native-wedged process never
        # runs a TERM handler (for a stopped process TERM stays pending
        # until SIGCONT), while KILL reaps it unconditionally.
        if 0 <= partition_id < len(self._procs):
            p = self._procs[partition_id]
            if p.is_alive():
                p.kill()
                return True
        return False

    def stall_worker(self, partition_id: int, duration_s: float) -> bool:
        if 0 <= partition_id < len(self._procs):
            return _stall_process(self._procs[partition_id], duration_s)
        return False

    def run(self, worker_fn: Callable[[int], None]) -> List[BaseException]:
        ctx = mp.get_context(self.start_method)
        procs = []
        with _cpu_child_env():
            for i in range(self.num_workers):
                env = self.chip_env_fn(i) if self.chip_env_fn else {}
                p = ctx.Process(target=_process_entry, args=(worker_fn, i, env),
                                name="runner-{}".format(i))
                p.start()
                procs.append(p)
        self._procs = procs
        failures: List[BaseException] = []
        for p in procs:
            p.join()
            if p.exitcode != 0:
                failures.append(RuntimeError(
                    "Runner process {} died (exit code {}).".format(p.name, p.exitcode)))
        return failures


class TPURunnerPool(ProcessRunnerPool):
    """Per-trial TPU chip pinning: runner i sees only its chip subset.

    On a TPU VM with C local chips and ``chips_per_trial`` k, runner i gets
    chips [i*k, (i+1)*k). libtpu reads TPU_VISIBLE_CHIPS (v4+: bounds via
    TPU_PROCESS_BOUNDS/TPU_CHIPS_PER_PROCESS_BOUNDS) before backend init —
    this is the TPU analogue of the reference pinning one GPU per Spark
    executor.
    """

    def __init__(self, num_workers: int, chips_per_trial: int = 1,
                 total_chips: Optional[int] = None):
        if total_chips is not None and num_workers * chips_per_trial > total_chips:
            raise ValueError(
                "{} workers x {} chips/trial exceeds the {} chips on this "
                "host.".format(num_workers, chips_per_trial, total_chips)
            )

        super().__init__(
            num_workers, start_method="spawn",
            chip_env_fn=lambda i: chip_env(i, chips_per_trial))
        self.chips_per_trial = chips_per_trial
        self.total_chips = total_chips


class ElasticTPURunnerPool(RunnerPool):
    """Budget-sized chip sub-slices: SURVEY §7.3's slice-repartitioning
    problem. Each runner is an ephemeral pinned process; when the driver
    decides a runner's capacity no longer matches the schedule's needs
    (chips_per_budget), the runner exits with a resize request and this
    dispatcher respawns it pinned to the new chip count — libtpu reads the
    pinning env before backend init, so resizing is exit+respawn by
    construction. A chip free-list enforces sum(leases) <= total_chips;
    respawns wait until enough chips free up (the driver resizes idle
    runners toward parked work, so chips migrate instead of deadlocking).
    """

    def __init__(self, num_workers: int, total_chips: int,
                 chips_per_trial: int = 1, start_method: str = "spawn",
                 should_stop: Optional[Callable[[], bool]] = None,
                 resize_dir: Optional[str] = None):
        super().__init__(num_workers)
        if num_workers * chips_per_trial > total_chips:
            raise ValueError(
                "{} workers x {} chips exceeds the {}-chip lease budget"
                .format(num_workers, chips_per_trial, total_chips))
        self.total_chips = total_chips
        self.chips_per_trial = chips_per_trial
        self.start_method = start_method
        self.should_stop = should_stop or (lambda: False)
        import tempfile

        self.resize_dir = resize_dir or tempfile.mkdtemp(prefix="maggy_resize_")
        self._procs: dict = {}  # pid -> (process, chips_set)
        self._spawn_time: dict = {}  # pid -> monotonic start of current proc
        self._free: set = set()
        # Respawns queued for chips: [(partition_id, chips_needed)]. Kept on
        # self (under _lock) so the driver's resize watchdog can tell
        # "queued for chips" (healthy waiting — re-arm the watch) from
        # "process died before registering" (nothing will ever register —
        # expire the watch and reclaim the in-flight credit). spawn_stamp()
        # returns None for BOTH, which is exactly the ambiguity that leaked
        # credits before.
        self._pending_respawns: list = []  # guarded-by: _lock
        self._lock = threading.Lock()

    def spawn_stamp(self, partition_id: int):
        """Monotonic spawn time of the partition's CURRENT process, or
        None when no process exists (respawn still queued for chips).

        The driver's resize watchdog compares stamps, not ages: at resize
        request time the partition still runs its PRE-resize process, so a
        bare age check would see that (old, long-lived) process and kill a
        runner that is merely winding down. Only a process spawned AFTER
        the request (stamp > the stamp recorded at request time) that then
        fails to register is evidence of a wedged respawn."""
        with self._lock:
            if partition_id not in self._procs:
                return None
            return self._spawn_time.get(partition_id)

    def spawn_age(self, partition_id: int):
        """Seconds since the partition's CURRENT process was spawned, or
        None when no process exists."""
        t0 = self.spawn_stamp(partition_id)
        return None if t0 is None else time.monotonic() - t0

    def pending_respawn(self, partition_id: int) -> bool:
        """True while the partition still has a future: its respawn is
        QUEUED for chips, or a process exists RIGHT NOW (covers the race
        where the queued respawn was spawned between the watchdog's
        spawn_stamp() read and this call — without the _procs check the
        watchdog would misread that healthy just-spawned runner as 'died
        before registering' and kill it). False is terminal — a pid never
        re-enters _procs or the pending list once it left both — so the
        watchdog can safely expire the watch and reclaim the in-flight
        credit on a False."""
        with self._lock:
            if partition_id in self._procs:
                return True
            return any(pid == partition_id
                       for pid, _ in self._pending_respawns)

    def _resize_file(self, partition_id: int) -> str:
        return os.path.join(self.resize_dir, "{}.resize".format(partition_id))

    def _spawn(self, ctx, worker_fn, partition_id: int, chips: set):
        env = {
            "TPU_VISIBLE_CHIPS": ",".join(str(c) for c in sorted(chips)),
            "ALLOW_MULTIPLE_LIBTPU_LOAD": "1",
            "MAGGY_TPU_CAPACITY": str(len(chips)),
            "MAGGY_TPU_RESIZE_FILE": self._resize_file(partition_id),
        }
        p = ctx.Process(target=_process_entry,
                        args=(worker_fn, partition_id, env),
                        name="runner-{}".format(partition_id))
        with _cpu_child_env():
            p.start()
        self._procs[partition_id] = (p, chips)
        self._spawn_time[partition_id] = time.monotonic()

    def kill_worker(self, partition_id: int) -> bool:
        with self._lock:
            entry = self._procs.get(partition_id)
            if entry and entry[0].is_alive():
                entry[0].kill()
                return True
        return False

    def stall_worker(self, partition_id: int, duration_s: float) -> bool:
        with self._lock:
            entry = self._procs.get(partition_id)
        return bool(entry) and _stall_process(entry[0], duration_s)

    def terminate(self) -> None:
        with self._lock:
            for p, _ in self._procs.values():
                if p.is_alive():
                    p.terminate()

    def run(self, worker_fn: Callable[[int], None]) -> List[BaseException]:
        import json as _json
        import time as _time

        ctx = mp.get_context(self.start_method)
        chip_ids = list(range(self.total_chips))
        with self._lock:
            for i in range(self.num_workers):
                lease = set(chip_ids[i * self.chips_per_trial:
                                     (i + 1) * self.chips_per_trial])
                self._spawn(ctx, worker_fn, i, lease)
            self._free = set(chip_ids[self.num_workers * self.chips_per_trial:])
        failures: List[BaseException] = []
        while True:
            with self._lock:
                live = dict(self._procs)
            exited = [(pid, p, chips) for pid, (p, chips) in live.items()
                      if not p.is_alive()]
            for pid, p, chips in exited:
                p.join()
                # Read the resize request BEFORE releasing the partition's
                # pool slot: between _procs.pop and the pending append the
                # driver's watchdog would otherwise see stamp=None AND
                # pending_respawn=False — the died-before-registering
                # signature — for a healthy queued respawn.
                resize = None
                rf = self._resize_file(pid)
                if os.path.exists(rf):
                    try:
                        with open(rf) as f:
                            resize = int(_json.load(f)["chips"])
                    except (ValueError, KeyError, OSError):
                        pass
                    try:
                        os.unlink(rf)
                    except OSError:
                        pass
                with self._lock:
                    self._procs.pop(pid, None)
                    self._free |= chips
                    if p.exitcode == 0 and resize:
                        # resize 0 = retire: chips freed, no respawn
                        self._pending_respawns.append((pid, resize))
                if p.exitcode != 0:
                    failures.append(RuntimeError(
                        "Runner process {} died (exit code {})."
                        .format(p.name, p.exitcode)))
            # Serve respawns whose lease fits the free pool.
            with self._lock:
                still_pending = []
                for pid, k in self._pending_respawns:
                    if k > self.total_chips:
                        failures.append(RuntimeError(
                            "Runner {} asked for {} chips but the lease "
                            "budget is {} (check chips_per_budget).".format(
                                pid, k, self.total_chips)))
                        continue
                    if self.should_stop():
                        continue  # experiment over: drop the respawn
                    if len(self._free) >= k:
                        lease = set(sorted(self._free)[:k])
                        self._free -= lease
                        self._spawn(ctx, worker_fn, pid, lease)
                    else:
                        still_pending.append((pid, k))
                self._pending_respawns = still_pending
                pending = list(still_pending)
                alive = any(p.is_alive() for p, _ in self._procs.values())
            if not alive and (not pending or self.should_stop()):
                break
            _time.sleep(0.05)
        return failures


class RemoteRunnerPool(RunnerPool):
    """Cross-host fan-out over DCN: runners are external agent processes
    (``python -m maggy_tpu.runner``) on other machines — TPU VMs of a pod
    slice — that dial the driver's control plane and JOIN.

    Scope note: these agents belong to ONE experiment and exit with it.
    For a PERSISTENT cross-process fleet that outlives any experiment —
    agents leased, preempted, and re-bound across experiments — use
    fleet agents instead (``maggy_tpu/fleet/agent.py``, ``python -m
    maggy_tpu.fleet agent``): same ticket-and-JOIN shape, fleet-scoped.

    The pool spawns nothing. It publishes a join ticket (advertised address
    + shared secret) to the experiment directory — typically a shared
    filesystem or GCS, the same discovery role as the reference POSTing the
    driver address to Hopsworks REST (`hopsworks.py:129-178`) — then waits
    for the experiment to complete. Agents may join at any time up to
    ``num_workers``; the schedule completes with however many joined
    (heartbeat-loss recovery covers agents dying mid-trial).
    """

    def __init__(self, driver):
        super().__init__(driver.num_executors)
        self.driver = driver

    def ticket(self) -> dict:
        drv = self.driver
        host, port = drv.server_addr
        if host in ("0.0.0.0", "", "::"):
            host = drv.env.get_ip_address()
        return {"host": host, "port": port, "secret": drv.secret_for_clients(),
                "app_id": drv.app_id, "run_id": drv.run_id,
                "num_workers": self.num_workers}

    def run(self, worker_fn: Callable[[int], None]) -> List[BaseException]:
        import json
        import time

        from maggy_tpu import constants

        drv = self.driver
        drv.env.dump(json.dumps(self.ticket(), indent=2),
                     drv.exp_dir + "/runner_ticket.json")
        # Trial parallelism proceeds with however many agents join;
        # distributed training NEEDS the full world before anything runs.
        need_all = (drv.server.join_info or {}).get("trial_type") == "distributed"
        deadline = time.monotonic() + constants.REGISTRATION_TIMEOUT_S
        while not drv.experiment_done:
            reservations = drv.server.reservations
            if reservations.done() if need_all else bool(reservations.all()):
                break
            if time.monotonic() > deadline:
                raise TimeoutError(
                    "{} remote runner(s) missing after {}s; ticket at {}".format(
                        reservations.remaining() if need_all else "All",
                        constants.REGISTRATION_TIMEOUT_S,
                        drv.exp_dir + "/runner_ticket.json"))
            time.sleep(0.2)
        # Experiment wait, with an all-agents-dead liveness bound: if every
        # admitted agent has gone silent past the heartbeat-loss timeout,
        # nobody is left to poll GET — requeued trials would never be picked
        # up and this loop would spin forever. Surfacing the failure lets the
        # driver abort with the real cause instead of hanging.
        while not drv.experiment_done:
            time.sleep(0.2)
            bound = drv.server.hb_loss_timeout
            if bound is None:
                continue
            registered = drv.server.reservations.all()
            active = {pid for pid, rec in registered.items()
                      if not rec.get("released")}
            if active and active <= set(drv.server.reservations.silent(bound)):
                return [RuntimeError(
                    "all {} remote agent(s) silent for > {:.0f}s with the "
                    "experiment incomplete; presumed dead (partitions {})".format(
                        len(active), bound, sorted(active)))]
        # Don't let the driver tear the server down under agents that have
        # not yet observed GSTOP — their next poll would hit a dead socket
        # and crash an otherwise-successful agent. Dead agents can't ack, so
        # a grace cap bounds the wait.
        ack_deadline = time.monotonic() + 10.0
        while (not drv.server.reservations.all_released()
               and time.monotonic() < ack_deadline):
            time.sleep(0.1)
        return []
