"""Early-stopping rule contract.

Parity: reference `maggy/earlystop/abstractearlystop.py:20-42`. The driver
calls `earlystop_check` on METRIC messages, gated by es_min/es_interval
(`optimization_driver.py:346-361`); trials returned are flagged for stopping.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Dict, List

from maggy_tpu.trial import Trial


class AbstractEarlyStop(ABC):
    @staticmethod
    @abstractmethod
    def earlystop_check(
        to_check: Dict[str, Trial], finalized_trials: List[Trial], direction: str
    ) -> List[Trial]:
        """Return the subset of ``to_check`` trials that should stop early."""
