"""Median stopping rule.

Parity: reference `maggy/earlystop/medianrule.py:21-60`: stop a running trial
if its best-so-far metric is worse than the median of finalized trials'
running averages truncated at the same step.
"""

from __future__ import annotations

import statistics
from typing import Dict, List

from maggy_tpu.earlystop.abstractearlystop import AbstractEarlyStop
from maggy_tpu.trial import Trial


class MedianStoppingRule(AbstractEarlyStop):
    @staticmethod
    def earlystop_check(
        to_check: Dict[str, Trial], finalized_trials: List[Trial], direction: str
    ) -> List[Trial]:
        stop_list: List[Trial] = []
        maximize = direction == "max"
        for trial in to_check.values():
            with trial.lock:
                history = list(trial.metric_history)
            if not history:
                continue
            step = len(history)
            # Running averages of finalized trials truncated at this step.
            # Only trials that actually reached this step contribute —
            # shorter (e.g. early-stopped) histories would bias the median
            # toward warm-up values (reference `medianrule.py:38-44`).
            running_avgs = []
            for fin in finalized_trials:
                if len(fin.metric_history) >= step:
                    fh = fin.metric_history[:step]
                    running_avgs.append(sum(fh) / len(fh))
            if not running_avgs:
                continue
            median = statistics.median(running_avgs)
            best = max(history) if maximize else min(history)
            worse = best < median if maximize else best > median
            if worse:
                stop_list.append(trial)
        return stop_list
