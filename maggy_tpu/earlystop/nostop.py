"""No-op stopping rule (reference `maggy/earlystop/nostop.py:20-26`)."""

from __future__ import annotations

from typing import Dict, List

from maggy_tpu.earlystop.abstractearlystop import AbstractEarlyStop
from maggy_tpu.trial import Trial


class NoStoppingRule(AbstractEarlyStop):
    @staticmethod
    def earlystop_check(
        to_check: Dict[str, Trial], finalized_trials: List[Trial], direction: str
    ) -> List[Trial]:
        return []
