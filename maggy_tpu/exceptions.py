"""Framework exceptions.

Parity: reference `maggy/core/exceptions.py:22-121`. `EarlyStopException` is a
control-flow exception raised inside the user's training loop by the Reporter
when the driver has flagged the running trial for early stopping.
"""

from __future__ import annotations


class MaggyTPUError(Exception):
    """Base class for all framework errors."""


class EarlyStopException(MaggyTPUError):
    """Raised in the user training loop when the driver stops the trial.

    Carries the last reported metric so the executor can finalize with it
    (reference `exceptions.py:22-27`).
    """

    def __init__(self, metric):
        super().__init__("Trial stopped early by the driver.")
        self.metric = metric


class ReturnTypeError(MaggyTPUError):
    """User training function returned an unsupported type."""

    def __init__(self, optimization_key, return_val):
        super().__init__(
            "Training function returned {} but must return a number or a dict "
            "containing the optimization key '{}'.".format(
                type(return_val), optimization_key
            )
        )


class MetricTypeError(MaggyTPUError):
    """A reported metric was not numeric."""

    def __init__(self, optimization_key, value):
        super().__init__(
            "The optimization metric '{}' must be numeric, got {}.".format(
                optimization_key, type(value)
            )
        )


class BroadcastMetricTypeError(MaggyTPUError):
    def __init__(self, value):
        super().__init__(
            "reporter.broadcast() requires a numeric metric, got {}.".format(
                type(value)
            )
        )


class BroadcastStepTypeError(MaggyTPUError):
    def __init__(self, step):
        super().__init__(
            "reporter.broadcast() requires an integer step, got {}.".format(type(step))
        )


class BroadcastStepValueError(MaggyTPUError):
    """Steps reported via broadcast must be strictly increasing."""

    def __init__(self, step, last_step):
        super().__init__(
            "reporter.broadcast() steps must be monotonically increasing: got step "
            "{} after step {}.".format(step, last_step)
        )


class NotSupportedError(MaggyTPUError):
    def __init__(self, category, value, suggestion=""):
        super().__init__(
            "{} '{}' is not supported. {}".format(category, value, suggestion)
        )


class BadArgumentsError(MaggyTPUError):
    def __init__(self, callee, message=""):
        super().__init__("Bad arguments for {}. {}".format(callee, message))


class RendezvousError(MaggyTPUError):
    """Multi-host rendezvous (coordinator discovery) failed or timed out."""


class AuthenticationError(MaggyTPUError):
    """A control-plane message failed the shared-secret check."""


class RunAdoptionError(MaggyTPUError):
    """Another driver already adopted this run directory.

    Crash-only recovery admits exactly ONE driver incarnation per run dir
    at a time: adoption goes through an exclusive ``.driver_epoch.N``
    marker (``util.claim_driver_epoch``), and the loser of a
    two-restarting-drivers race gets this error instead of a second
    control plane silently double-driving the same experiment."""
