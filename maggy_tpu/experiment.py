"""`lagom` — the experiment entry points.

Parity: reference `maggy/experiment.py` — one-experiment-at-a-time module
guard (:42-45), `lagom(train_fn, config)` (:48-83), `@singledispatch` driver
dispatch on config type (:86-108), exception handler marking the experiment
FAILED (:111-128), atexit kill-handler (:131-148).

Beyond the reference: per-run state lives in `_Submission` objects handed
out under a lock (the reference's bare module globals let two threads both
pass the ``if RUNNING`` check), and `lagom_submit` attaches an experiment
to a shared runner fleet (`maggy_tpu.fleet`) instead of owning a pool —
any number of submissions may run concurrently in one process, multiplexed
by the fleet scheduler. The classic `lagom()` is the degenerate case: a
single-tenant fleet of one that owns its pool, bit-for-bit unchanged.

"Lagom" (Swedish): just the right amount — keep every runner busy with
asynchronous trials, never more resources than needed.
"""

from __future__ import annotations

import atexit
import itertools
import os
import threading
import time
from functools import singledispatch
from typing import Any, Callable, Optional

from maggy_tpu import util
from maggy_tpu.config import (
    AblationConfig,
    DistributedConfig,
    LagomConfig,
    OptimizationConfig,
)
from maggy_tpu.core.environment import EnvSing

#: Back-compat mirrors of the per-run state (tests and notebooks read /
#: monkeypatch these). The authoritative state is the _Submission registry
#: below — ALL mutation happens under _state_lock.
APP_ID: str | None = None
RUNNING = False
RUN_ID = 0

_state_lock = threading.RLock()
_active_runs: set = set()
_token_counter = itertools.count()


class _Submission:
    """One claimed run: (app_id, run_id) plus the registry token that
    marks it active until `_end_run`."""

    __slots__ = ("token", "app_id", "run_id")

    def __init__(self, token: int, app_id: str, run_id: int):
        self.token = token
        self.app_id = app_id
        self.run_id = run_id


def _begin_run(config, env, exclusive: bool) -> _Submission:
    """Claim per-run state under the lock: resolve the app id, claim a run
    id (atomically — `util.claim_run_id` stakes the run dir with
    `exclusive_create`, so two experiments starting under the same base
    dir can never mint the same id), and register the run as active.

    ``exclusive=True`` is classic `lagom` semantics: refuse while ANY run
    is active in this process. Fleet submissions pass False — concurrency
    is the point — and the unsynchronized two-threads-both-pass-the-check
    hazard of the old module-global ``RUNNING`` flag is gone either way."""
    global APP_ID, RUNNING, RUN_ID
    with _state_lock:
        if exclusive and _active_runs:
            raise RuntimeError("An experiment is already running in this process.")
        if APP_ID is None:
            APP_ID = os.environ.get(
                "MAGGY_TPU_APP_ID",
                "app-{}".format(time.strftime("%Y%m%d-%H%M%S")))
        app_id = APP_ID
        # Scan the SAME directory the driver will register under (a custom
        # experiment_dir must not collide at run 0), via the env's own fs.
        base = getattr(config, "experiment_dir", None) \
            or env.experiment_base_dir()
        if getattr(config, "resume", False):
            # Re-enter the most recent run OF THIS EXPERIMENT (matched by
            # registered name, not just position): one app id hosts many
            # experiments in fleet mode, and the bare most-recent rule
            # would adopt whichever tenant ran last.
            run_id = util.find_resume_run_id(base, app_id,
                                             name=config.name, env=env)
        else:
            run_id = util.claim_run_id(base, app_id, env=env)
        token = next(_token_counter)
        _active_runs.add(token)
        RUNNING = True
        RUN_ID = run_id
        return _Submission(token, app_id, run_id)


def _end_run(sub: _Submission) -> None:
    global RUNNING
    with _state_lock:
        _active_runs.discard(sub.token)
        RUNNING = bool(_active_runs)


def _build_config(config, kwargs) -> LagomConfig:
    """Config-or-kwargs resolution shared by lagom and lagom_submit."""
    if config is None:
        if not kwargs:
            raise TypeError(
                "lagom() needs a config object (OptimizationConfig / "
                "AblationConfig / DistributedConfig) or OptimizationConfig "
                "keyword arguments.")
        return OptimizationConfig(**kwargs)
    if kwargs:
        raise TypeError(
            "Pass EITHER a config object OR keyword arguments, not both "
            "(got config={!r} plus {}).".format(
                type(config).__name__, sorted(kwargs)))
    return config


def lagom(train_fn: Callable, config: LagomConfig = None, **kwargs) -> Any:
    """Launch an experiment: asynchronous HPO, an ablation study, or
    distributed training, selected by the config type.

    Compat: the reference's 0.x notebook style
    ``lagom(train_fn, searchspace=sp, optimizer="randomsearch",
    num_trials=15, direction="max")`` (its README quick start) is accepted —
    keyword arguments build an `OptimizationConfig`.

    One at a time per process (the reference's module guard). To run MANY
    experiments concurrently over one shared runner fleet, use
    ``lagom_submit``."""
    config = _build_config(config, kwargs)
    # Honor JAX_PLATFORMS even when a TPU plugin was registered before this
    # process's env could win (see util.apply_platform_env).
    util.apply_platform_env()
    env = EnvSing.get_instance()
    sub = _begin_run(config, env, exclusive=True)
    driver = None
    try:
        driver = lagom_driver(config, sub.app_id, sub.run_id)
        atexit.register(_exit_handler, driver)
        return driver.run_experiment(train_fn)
    finally:
        _end_run(sub)
        if driver is not None:
            atexit.unregister(_exit_handler)


def lagom_submit(train_fn: Callable, config: LagomConfig = None, *,
                 fleet, priority="normal", weight: float = 1.0,
                 min_runners: int = 0, max_runners: Optional[int] = None,
                 name: Optional[str] = None, block: bool = True,
                 **kwargs) -> Any:
    """Submit an experiment to a shared runner fleet (`maggy_tpu.fleet`).

    Unlike ``lagom``, any number of submissions may run concurrently in
    one process: the fleet's scheduler multiplexes its persistent runners
    across them by ``priority`` class ("high"/"normal"/"low" or an int;
    lower wins), weighted fair share (``weight``), and per-experiment
    quotas (``min_runners`` guaranteed — by preempting over-share,
    lower-priority trials when necessary; ``max_runners`` capped). A
    preempted trial resumes from its last `TrialCheckpointer` step on its
    next runner (requeue-from-scratch when it never checkpointed).

    ``block=True`` (default) waits and returns the experiment result —
    the same value ``lagom`` returns. ``block=False`` returns a
    ``FleetSubmission`` handle (``.result()``/``.done()``) so many
    experiments can be submitted before waiting on any."""
    config = _build_config(config, kwargs)
    # resume=True re-enters the most recent run dir. Concurrent
    # resubmissions racing for the same dir are arbitrated by the
    # driver's exclusive incarnation marker (util.claim_driver_epoch):
    # exactly one adopter wins; the loser's submission fails with
    # RunAdoptionError through the handle — a resubmitted tenant after a
    # driver crash recovers its run from the journal like lagom() does
    # (docs/developer.md "Crash-only recovery").
    util.apply_platform_env()
    handle = fleet.submit(train_fn, config, priority=priority, weight=weight,
                          min_runners=min_runners, max_runners=max_runners,
                          name=name)
    return handle.result() if block else handle


@singledispatch
def lagom_driver(config, app_id: str, run_id: int):
    raise TypeError(
        "Unsupported config type {}; use OptimizationConfig, AblationConfig, "
        "or DistributedConfig.".format(type(config))
    )


@lagom_driver.register(OptimizationConfig)
def _(config: OptimizationConfig, app_id: str, run_id: int):
    from maggy_tpu.core.driver.optimization_driver import OptimizationDriver

    return OptimizationDriver(config, app_id, run_id)


@lagom_driver.register(AblationConfig)
def _(config: AblationConfig, app_id: str, run_id: int):
    from maggy_tpu.core.driver.ablation_driver import AblationDriver

    return AblationDriver(config, app_id, run_id)


@lagom_driver.register(DistributedConfig)
def _(config: DistributedConfig, app_id: str, run_id: int):
    from maggy_tpu.core.driver.distributed_driver import DistributedDriver

    return DistributedDriver(config, app_id, run_id)


def _exit_handler(driver) -> None:
    """Mark the experiment KILLED if the process dies mid-run (reference
    `experiment.py:131-148`)."""
    try:
        if not driver.experiment_done:
            driver.env.finalize_experiment(driver.exp_dir, "KILLED", {})
    except Exception:  # noqa: BLE001 - never raise at interpreter exit
        pass
