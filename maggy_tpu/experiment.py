"""`lagom` — the single experiment entry point.

Parity: reference `maggy/experiment.py` — one-experiment-at-a-time module
guard (:42-45), `lagom(train_fn, config)` (:48-83), `@singledispatch` driver
dispatch on config type (:86-108), exception handler marking the experiment
FAILED (:111-128), atexit kill-handler (:131-148).

"Lagom" (Swedish): just the right amount — keep every runner busy with
asynchronous trials, never more resources than needed.
"""

from __future__ import annotations

import atexit
import os
import time
from functools import singledispatch
from typing import Any, Callable

from maggy_tpu import util
from maggy_tpu.config import (
    AblationConfig,
    DistributedConfig,
    LagomConfig,
    OptimizationConfig,
)
from maggy_tpu.core.environment import EnvSing

APP_ID: str | None = None
RUNNING = False
RUN_ID = 0


def lagom(train_fn: Callable, config: LagomConfig = None, **kwargs) -> Any:
    """Launch an experiment: asynchronous HPO, an ablation study, or
    distributed training, selected by the config type.

    Compat: the reference's 0.x notebook style
    ``lagom(train_fn, searchspace=sp, optimizer="randomsearch",
    num_trials=15, direction="max")`` (its README quick start) is accepted —
    keyword arguments build an `OptimizationConfig`."""
    global APP_ID, RUNNING, RUN_ID
    if config is None:
        if not kwargs:
            raise TypeError(
                "lagom() needs a config object (OptimizationConfig / "
                "AblationConfig / DistributedConfig) or OptimizationConfig "
                "keyword arguments.")
        config = OptimizationConfig(**kwargs)
    elif kwargs:
        raise TypeError(
            "Pass EITHER a config object OR keyword arguments, not both "
            "(got config={!r} plus {}).".format(
                type(config).__name__, sorted(kwargs)))
    if RUNNING:
        raise RuntimeError("An experiment is already running in this process.")
    # Honor JAX_PLATFORMS even when a TPU plugin was registered before this
    # process's env could win (see util.apply_platform_env).
    util.apply_platform_env()
    env = EnvSing.get_instance()
    if APP_ID is None:
        APP_ID = os.environ.get("MAGGY_TPU_APP_ID",
                                "app-{}".format(time.strftime("%Y%m%d-%H%M%S")))
    # Scan the SAME directory the driver will register under (a custom
    # experiment_dir must not collide at run 0), via the env's own fs.
    base = getattr(config, "experiment_dir", None) or env.experiment_base_dir()
    RUN_ID = util.next_run_id(base, APP_ID, env=env)
    if getattr(config, "resume", False):
        if RUN_ID == 0:
            raise ValueError(
                "resume=True but no previous run of app '{}' exists under "
                "{}".format(APP_ID, base))
        RUN_ID -= 1  # re-enter the most recent run's directory
    RUNNING = True
    driver = None
    try:
        driver = lagom_driver(config, APP_ID, RUN_ID)
        atexit.register(_exit_handler, driver)
        return driver.run_experiment(train_fn)
    finally:
        RUNNING = False
        if driver is not None:
            atexit.unregister(_exit_handler)


@singledispatch
def lagom_driver(config, app_id: str, run_id: int):
    raise TypeError(
        "Unsupported config type {}; use OptimizationConfig, AblationConfig, "
        "or DistributedConfig.".format(type(config))
    )


@lagom_driver.register(OptimizationConfig)
def _(config: OptimizationConfig, app_id: str, run_id: int):
    from maggy_tpu.core.driver.optimization_driver import OptimizationDriver

    return OptimizationDriver(config, app_id, run_id)


@lagom_driver.register(AblationConfig)
def _(config: AblationConfig, app_id: str, run_id: int):
    from maggy_tpu.core.driver.ablation_driver import AblationDriver

    return AblationDriver(config, app_id, run_id)


@lagom_driver.register(DistributedConfig)
def _(config: DistributedConfig, app_id: str, run_id: int):
    from maggy_tpu.core.driver.distributed_driver import DistributedDriver

    return DistributedDriver(config, app_id, run_id)


def _exit_handler(driver) -> None:
    """Mark the experiment KILLED if the process dies mid-run (reference
    `experiment.py:131-148`)."""
    try:
        if not driver.experiment_done:
            driver.env.finalize_experiment(driver.exp_dir, "KILLED", {})
    except Exception:  # noqa: BLE001 - never raise at interpreter exit
        pass
