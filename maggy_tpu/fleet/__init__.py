"""Shared-fleet scheduling: multiplex concurrent experiments over one
persistent runner fleet (see scheduler.py for the full design).

    from maggy_tpu.fleet import Fleet
    from maggy_tpu import experiment

    with Fleet(runners=8) as fleet:
        a = experiment.lagom_submit(train_a, cfg_a, fleet=fleet,
                                    weight=2.0, block=False)
        b = experiment.lagom_submit(train_b, cfg_b, fleet=fleet,
                                    priority="high", min_runners=2,
                                    block=False)
        results = a.result(), b.result()

CLI: ``python -m maggy_tpu.fleet start|submit|status`` (spool-file
submissions for cross-process use); live view:
``python -m maggy_tpu.monitor --fleet <home_dir>``.
"""

from maggy_tpu.fleet.agent import (AGENT_TICKET_NAME, AgentPlane,
                                   FleetAgent, read_fleet_ticket)
from maggy_tpu.fleet.scheduler import (FLEET_JOURNAL_NAME, ExperimentEntry,
                                       Fleet, FleetBinding, FleetLeasedPool,
                                       FleetPolicy, FleetSaturated,
                                       FleetScheduler, FleetSubmission,
                                       priority_rank, replay_fleet_journal)

__all__ = [
    "Fleet", "FleetPolicy", "FleetSaturated", "FleetScheduler",
    "FleetBinding", "FleetLeasedPool", "FleetSubmission",
    "ExperimentEntry", "FLEET_JOURNAL_NAME", "priority_rank",
    "replay_fleet_journal",
    "AgentPlane", "FleetAgent", "AGENT_TICKET_NAME", "read_fleet_ticket",
]
