"""``python -m maggy_tpu.fleet`` — host, feed, and watch a shared fleet.

    start   host a fleet in this process and serve submissions from a
            spec file and/or the fleet home's ``queue/`` spool directory
            (``--max-agents N`` additionally opens the remote-agent
            plane and writes ``<home>/agent_ticket.json``)
    agent   run a REMOTE AGENT daemon: read the fleet ticket, JOIN, and
            serve leases until the fleet releases us — start one per
            process/k8s pod/TPU-VM worker, anywhere that can reach the
            fleet's socket
    submit  drop a submission JSON into a running fleet's spool
    status  print the fleet's status.json + journal-replayed shares
    soak    run the built-in two-experiment preemption soak (invariants
            checked; exit 1 on violation); ``--agent`` runs the
            agent-kill soak (invariant 11) with real agent processes

A submission spec names a module-level train function and the
OptimizationConfig fields (searchspace as ``{name: [TYPE, range]}``):

    {"name": "sweep_a",
     "train_fn": "maggy_tpu.fleet.soak:demo_train_fn",
     "priority": "normal", "weight": 2.0,
     "min_runners": 0, "max_runners": 4,
     "config": {"num_trials": 8, "optimizer": "randomsearch",
                "direction": "max",
                "searchspace": {"lr": ["DOUBLE", [0.0, 0.2]],
                                "units": ["INTEGER", [8, 64]]}}}

Spool submissions are claimed with ``exclusive_create`` (a ``.claimed``
marker), so several feeders can share one spool without double-running.
"""

from __future__ import annotations

import argparse
import importlib
import json
import os
import sys
import time
import uuid
from typing import Any, Dict


def _load_train_fn(spec: str):
    mod_name, _, fn_name = spec.partition(":")
    if not fn_name:
        raise ValueError(
            "train_fn must be 'module.path:function', got {!r}".format(spec))
    return getattr(importlib.import_module(mod_name), fn_name)


def _build_config(conf: Dict[str, Any], base_dir=None):
    from maggy_tpu import OptimizationConfig, Searchspace

    conf = dict(conf)
    space = conf.pop("searchspace", None)
    if space is not None and not isinstance(space, Searchspace):
        space = Searchspace(**{k: (v[0], v[1]) for k, v in space.items()})
    if base_dir and not conf.get("experiment_dir"):
        conf["experiment_dir"] = base_dir
    return OptimizationConfig(searchspace=space, **conf)


def _submit_spec(fleet, spec: Dict[str, Any], handles: Dict[str, Any],
                 base_dir=None) -> None:
    from maggy_tpu import experiment

    handle = experiment.lagom_submit(
        _load_train_fn(spec["train_fn"]),
        _build_config(spec.get("config", {}), base_dir=base_dir),
        fleet=fleet,
        priority=spec.get("priority", "normal"),
        weight=spec.get("weight", 1.0),
        min_runners=spec.get("min_runners", 0),
        max_runners=spec.get("max_runners"),
        name=spec.get("name"), block=False)
    handles[handle.name] = handle
    print("submitted {!r} (priority={}, weight={})".format(
        handle.name, spec.get("priority", "normal"),
        spec.get("weight", 1.0)), flush=True)


def _drain_spool(fleet, env, spool: str, handles: Dict[str, Any],
                 base_dir=None, seen=None) -> int:
    """Claim and submit every unclaimed spec in the spool dir. The claim
    marker (exclusive_create) makes multiple hosts/restarts safe.

    Bounded scan: ``seen`` (caller-held, persisted across polls) records
    every spec name already resolved — claimed by us, observed claimed
    by another feeder, or submitted — so a poll over a spool holding
    thousands of processed specs costs ONE directory listing plus
    set-membership checks, not O(files) ``exists`` round trips per
    claim. Claim markers are only consulted for names this process has
    never resolved. A saturated fleet (admission queue at its
    ``max_queued`` bound) stops the drain WITHOUT claiming: unclaimed
    specs are the spool's natural backpressure buffer, picked up again
    once the queue drains — claiming and shedding would lose them."""
    from maggy_tpu.fleet.scheduler import FleetSaturated

    n = 0
    for name in sorted(env.ls(spool)):
        if not name.endswith(".json"):
            continue
        if seen is not None and name in seen:
            continue
        if fleet.scheduler.saturated():
            break
        path = "{}/{}".format(spool, name)
        marker = path + ".claimed"
        if env.exists(marker):
            if seen is not None:
                seen.add(name)
            continue
        if not env.exclusive_create(
                json.dumps({"claimed_at": time.time(),
                            "pid": os.getpid()}), marker):
            if seen is not None:
                seen.add(name)
            continue
        if seen is not None:
            seen.add(name)
        try:
            _submit_spec(fleet, json.loads(env.load(path)), handles,
                         base_dir=base_dir)
            n += 1
        except FleetSaturated:
            # Raced past the pre-claim check (a concurrent submit filled
            # the queue): un-burn the claim so the spec is retried — by
            # this host or any other — once the queue drains. Losing it
            # would contradict the spool's backpressure contract.
            try:
                env.delete(marker)
            except Exception:  # noqa: BLE001 - a stuck marker only delays the retry
                pass
            if seen is not None:
                seen.discard(name)
            break
        except Exception as e:  # noqa: BLE001 - one bad spec must not kill the host
            print("bad submission {}: {!r}".format(name, e),
                  file=sys.stderr, flush=True)
    return n


def _cmd_start(args) -> int:
    from maggy_tpu.core.environment import EnvSing
    from maggy_tpu.fleet import Fleet

    env = EnvSing.get_instance()
    fleet = Fleet(runners=args.runners, name=args.name,
                  home_dir=args.home, max_active=args.max_active,
                  max_queued=args.max_queued,
                  preempt_grace_s=args.preempt_grace,
                  max_agents=args.max_agents,
                  bind_host=args.bind_host,
                  sink=False if args.no_sink else None)
    spool = fleet.home_dir + "/queue"
    env.mkdir(spool)
    handles: Dict[str, Any] = {}
    seen: set = set()
    with fleet:
        print("fleet {!r}: {} runner(s), {} agent slot(s), home {}".format(
            fleet.name, fleet.num_runners, fleet.max_agents,
            fleet.home_dir), flush=True)
        if fleet.agent_plane is not None:
            print("agent ticket: {}/agent_ticket.json".format(
                fleet.home_dir), flush=True)
        for spec_path in args.spec or []:
            with open(spec_path) as f:
                loaded = json.load(f)
            for spec in loaded if isinstance(loaded, list) else [loaded]:
                _submit_spec(fleet, spec, handles, base_dir=args.base_dir)
        idle_since = None
        while True:
            _drain_spool(fleet, env, spool, handles,
                         base_dir=args.base_dir, seen=seen)
            pending = [h for h in handles.values() if not h.done()]
            if pending:
                idle_since = None
            elif args.idle_exit is not None:
                idle_since = idle_since or time.monotonic()
                if time.monotonic() - idle_since >= args.idle_exit:
                    break
            time.sleep(args.poll)
    failures = 0
    for name, h in sorted(handles.items()):
        try:
            result = h.result(timeout=0)
            print("{}: FINISHED best={}".format(
                name, result.get("best_val") if isinstance(result, dict)
                else result), flush=True)
        except BaseException as e:  # noqa: BLE001 - report, keep printing the rest
            failures += 1
            print("{}: FAILED {!r}".format(name, e), flush=True)
    return 1 if failures else 0


def _cmd_submit(args) -> int:
    from maggy_tpu.core.environment import EnvSing

    env = EnvSing.get_instance()
    with open(args.spec) as f:
        spec = json.load(f)
    name = spec.get("name", "experiment")
    path = "{}/queue/{}-{}.json".format(args.home.rstrip("/"), name,
                                        uuid.uuid4().hex[:8])
    if not env.exclusive_create(json.dumps(spec, indent=2), path):
        print("spool collision at {}; retry".format(path), file=sys.stderr)
        return 1
    print("queued {} -> {}".format(name, path))
    return 0


def _cmd_status(args) -> int:
    from maggy_tpu.monitor import _poll_fleet, render_fleet

    print(render_fleet(*_poll_fleet(args.home)))
    return 0


def _cmd_agent(args) -> int:
    from maggy_tpu.fleet.agent import agent_main

    return agent_main(args)


def _cmd_soak(args) -> int:
    from maggy_tpu.fleet.soak import (run_agent_soak, run_fleet_soak,
                                      run_sink_soak, run_slow_tenant_soak)

    if args.sink:
        report = run_sink_soak(seed=args.seed, lock_witness=True)
    elif args.agent:
        report = run_agent_soak(seed=args.seed, lock_witness=True)
    elif args.slow_tenant:
        # Witness on by default, like the chaos CLI's soaks: the
        # isolation run doubles as a dynamic lock-order check.
        report = run_slow_tenant_soak(
            seed=args.seed, dispatch_pool=not args.no_dispatch_pool,
            lock_witness=True)
    else:
        report = run_fleet_soak(runners=args.runners, seed=args.seed)
    print(json.dumps(report, indent=2, default=str))
    return 0 if report["ok"] else 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m maggy_tpu.fleet",
        description="Host, feed, and watch a shared experiment fleet.")
    sub = p.add_subparsers(dest="command", required=True)

    ps = sub.add_parser("start", help="host a fleet in this process")
    ps.add_argument("--home", help="fleet home dir (journal, status.json, "
                                   "queue/ spool); default under the "
                                   "environment base dir")
    ps.add_argument("--name", default="fleet")
    ps.add_argument("--runners", type=int, default=2)
    ps.add_argument("--max-active", type=int, default=None,
                    help="admission cap: concurrent experiments competing "
                         "for runners (default unbounded)")
    ps.add_argument("--max-queued", type=int, default=None,
                    help="admission-queue bound: submissions past it are "
                         "shed (journaled 'shed' events); the spool "
                         "feeder stops claiming while saturated "
                         "(default unbounded)")
    ps.add_argument("--preempt-grace", type=float, default=1.0,
                    help="seconds an experiment may sit below its "
                         "guaranteed allocation before the scheduler "
                         "preempts a victim")
    ps.add_argument("--spec", action="append",
                    help="submission spec JSON (file with one spec or a "
                         "list); repeatable")
    ps.add_argument("--base-dir", help="experiment_dir for submissions "
                                       "that don't set one")
    ps.add_argument("--poll", type=float, default=1.0,
                    help="spool poll interval seconds")
    ps.add_argument("--idle-exit", type=float, default=None,
                    help="exit after this many idle seconds (no pending "
                         "experiments, empty spool); default: run forever")
    ps.add_argument("--max-agents", type=int, default=0,
                    help="remote-agent slots: >0 opens the agent plane "
                         "and writes <home>/agent_ticket.json for "
                         "`python -m maggy_tpu.fleet agent` daemons "
                         "(default 0 = in-process only)")
    ps.add_argument("--bind-host", default="127.0.0.1",
                    help="address the shared listener binds (default "
                         "loopback; set 0.0.0.0 for cross-host agents — "
                         "the ticket then advertises this host's IP)")
    ps.add_argument("--no-sink", action="store_true",
                    help="disable the fleet journal sink (telemetry "
                         "fan-in into <home>/journal/): tenants with "
                         "config.sink then journal locally, agents keep "
                         "agent.jsonl private (default: sink on whenever "
                         "fleet telemetry is)")

    pa = sub.add_parser(
        "agent", help="run a remote fleet-agent daemon")
    pa.add_argument("--ticket",
                    help="path to the fleet's agent_ticket.json "
                         "(written by `start --max-agents`)")
    pa.add_argument("--wait-ticket", type=float, default=30.0,
                    help="seconds to wait for the ticket file to appear")
    pa.add_argument("--fleet-addr",
                    help="fleet control-plane address HOST:PORT "
                         "(alternative to --ticket)")
    pa.add_argument("--secret", help="fleet secret (hex)")
    pa.add_argument("--secret-file", help="file containing the fleet "
                                          "secret")
    pa.add_argument("--chips", type=int, default=1,
                    help="chip capacity this agent declares (and pins "
                         "to, with --pin)")
    pa.add_argument("--process-index", type=int, default=0,
                    help="this agent's index among the agents on this "
                         "host (selects its chip subset with --pin)")
    pa.add_argument("--pin", action="store_true",
                    help="pin this process to its disjoint TPU chip "
                         "subset (TPU_VISIBLE_CHIPS) before backend "
                         "init — one agent per subset per pod VM")
    pa.add_argument("--advertise-host", default="127.0.0.1",
                    help="host other gang members can reach this agent "
                         "on (the jax.distributed coordinator address "
                         "for remote gangs)")
    pa.add_argument("--obs-port", type=int, default=None,
                    help="per-agent observability: serve /healthz + "
                         "/metrics on this port (0 = ephemeral; default "
                         "off) — the k8s liveness probe")
    pa.add_argument("--home", help="agent scratch dir (obs journal); "
                                   "default: a tempdir")
    pa.add_argument("--profile", action="store_true",
                    help="capture a jax.profiler trace per trial")
    pa.add_argument("--max-leases", type=int, default=None,
                    help="exit after serving this many leases (batch "
                         "jobs/tests; default: run until AGSTOP)")
    pa.add_argument("--idle-exit", type=float, default=None,
                    help="exit after this many idle seconds with no "
                         "lease (default: run forever)")

    pq = sub.add_parser("submit", help="queue a spec into a fleet's spool")
    pq.add_argument("--home", required=True)
    pq.add_argument("spec", help="submission spec JSON file")

    pt = sub.add_parser("status", help="print fleet status + replayed "
                                       "shares")
    pt.add_argument("--home", required=True)

    pk = sub.add_parser("soak", help="run the built-in preemption soak")
    pk.add_argument("--runners", type=int, default=2)
    pk.add_argument("--seed", type=int, default=7)
    pk.add_argument("--agent", action="store_true",
                    help="run the agent-kill soak instead: real agent "
                         "subprocesses serve leases over sockets, one "
                         "is SIGKILLed mid-lease, and invariant 11 "
                         "(lease revoked, trial requeued exactly once) "
                         "is checked from the journals (run under the "
                         "lock-order witness)")
    pk.add_argument("--sink", action="store_true",
                    help="run the journal-sink soak instead: the fleet's "
                         "sink tenant is killed mid-soak and restarted — "
                         "invariant 12 (degrade to local journals, "
                         "re-ship on reconnect, zero lost / duplicate "
                         "events, zero experiment failures), under the "
                         "lock-order witness")
    pk.add_argument("--slow-tenant", action="store_true",
                    help="run the slow-tenant isolation soak instead: one "
                         "tenant's handlers artificially delayed, other "
                         "tenants' hand-off p95 must stay in bound "
                         "(run under the lock-order witness)")
    pk.add_argument("--no-dispatch-pool", action="store_true",
                    help="slow-tenant soak only: disable the per-tenant "
                         "dispatch pools (the pre-fix shared-loop "
                         "behavior) — for A/B comparison; the isolation "
                         "invariant is expected to FAIL in this mode")

    args = p.parse_args(argv)
    return {"start": _cmd_start, "agent": _cmd_agent,
            "submit": _cmd_submit, "status": _cmd_status,
            "soak": _cmd_soak}[args.command](args)


if __name__ == "__main__":
    sys.exit(main())
