"""Remote fleet agents: one persistent fleet across processes and hosts.

PR 5 built the shared fleet, but every runner was a THREAD in the fleet
host's process. This module is the cross-process half (the Podracer
shape, arXiv:2104.06272, completed): an **agent** is a long-lived daemon
started anywhere — a bare process, a k8s pod, a TPU-VM worker — that
reads a **fleet ticket** (advertised address + fleet secret, the fleet
generalization of the per-experiment ticket ``maggy_tpu/runner.py``
uses), declares its capacity (chips, host, process index), and JOINs the
fleet's ``SharedServer`` socket. The ``FleetScheduler`` then leases,
preempts, and **re-binds** the agent across experiments exactly like a
thread runner:

- lease delivery ships the target experiment's SECRET plus the train
  function's dotted path over an ``ABIND`` reply (the agent imports the
  function locally — only declarative data crosses the wire, never
  code);
- release (GSTOP / eviction) returns the agent to the fleet's idle pool
  instead of exiting — the next ``ALEASE`` poll may bind it to a
  DIFFERENT experiment on the same socket, same process, so warm slots
  (train/warm.py) survive same-family re-leases;
- agent death mid-lease is detected twice, on purpose: the experiment's
  own slot-reclaim liveness (``core/rpc.py`` heartbeat-loss scan)
  requeues the trial exactly once, and the fleet's per-agent proxy
  revokes the lease (journal: ``lease`` end ``reason=agent_lost``,
  ``agent`` phase ``lost``) so the runner slot frees — chaos invariant
  11 pins both halves.

Fleet side, one object: ``AgentPlane`` — the agent registry plus a
driver-side **proxy thread per agent** that pulls bindings from the
scheduler through the exact same ``next_binding`` path thread runners
use, delivers them as pending ``ABIND`` replies, and watches the leased
experiment's reservation liveness for the revocation half. Agent side,
one object: ``FleetAgent`` — JOIN, poll, run ``TrialExecutor`` against
the leased experiment's secret, ``ADONE``, repeat.

Wire contract (rpcconf-checked in ``core/rpc.py.FleetAgentServer``):
``AJOIN {host, chips, process_index, coord_addr, os_pid, agent}`` ->
``{agent, poll_s, liveness_s}``; ``ALEASE {agent}`` -> ``ABIND {exp,
partition_id, secret, hb_interval, exp_dir, optimization_key,
trial_type, warm_start, train_fn}`` | ``OK`` | ``AGSTOP``;
``ADONE {agent, error}`` -> ``OK``.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

#: Fleet-ticket filename inside the fleet home dir.
AGENT_TICKET_NAME = "agent_ticket.json"

#: Default idle-poll cadence the AJOIN reply hands the agent.
DEFAULT_POLL_S = 0.2

#: Default silence bound after which an agent is declared lost. Idle
#: agents are measured on their ALEASE polls; leased agents on the
#: target experiment's own heartbeat-loss bound (slot-reclaim liveness).
DEFAULT_LIVENESS_S = 10.0

#: How long a delivered lease may sit without the agent's REG arriving
#: at the experiment server (relative to the liveness bound) before the
#: proxy concludes the agent died between ABIND and REG.
_REG_GRACE_FACTOR = 1.5

#: Grace for the agent's ADONE after its partition was released (GSTOP
#: observed): the done message normally lands within one poll.
_DONE_GRACE_S = 10.0


def train_fn_path(fn) -> Optional[str]:
    """Dotted ``module:function`` path for a MODULE-LEVEL callable, or
    None when the callable cannot be named on the wire (lambda, closure,
    method, ``__main__``) — such experiments lease thread runners only;
    agents are never offered them."""
    import sys

    mod = getattr(fn, "__module__", None)
    qual = getattr(fn, "__qualname__", None)
    if not mod or not qual or mod == "__main__" \
            or "<" in qual or "." in qual:
        return None
    module = sys.modules.get(mod)
    if module is None or getattr(module, qual, None) is not fn:
        return None
    return "{}:{}".format(mod, qual)


def write_fleet_ticket(env, path: str, host: str, port: int, secret: str,
                       fleet: str, max_agents: int,
                       sink: Optional[str] = None) -> Dict[str, Any]:
    ticket = {"host": host, "port": int(port), "secret": secret,
              "fleet": fleet, "max_agents": int(max_agents)}
    if sink:
        # The journal-sink tenant's secret (telemetry/sink.py): agents
        # ship their own journals + counters to the fleet through it.
        # Absent for sink-less fleets — agents then journal locally only.
        ticket["sink"] = sink
    env.dump(json.dumps(ticket, indent=2), path)
    return ticket


def read_fleet_ticket(path: str, wait_s: float = 0.0) -> Dict[str, Any]:
    """Load the fleet ticket, optionally waiting for it to appear (the
    fleet host writes it at start). Validates before use: the writer may
    not be atomic on a shared fs, so a partial read retries."""
    deadline = time.monotonic() + wait_s
    while True:
        if os.path.exists(path):
            try:
                with open(path) as f:
                    ticket = json.load(f)
                ticket["host"], ticket["port"], ticket["secret"]
                return ticket
            except (json.JSONDecodeError, KeyError, OSError):
                pass
        if time.monotonic() >= deadline:
            raise FileNotFoundError("No fleet ticket at {}".format(path))
        time.sleep(0.5)


def reserve_coord_addr(host: str = "127.0.0.1") -> str:
    """Reserve a coordinator port for remote-gang rendezvous: bind an
    ephemeral port, note it, release it. The port is advertised at AJOIN
    and re-bound by ``jax.distributed.initialize`` when this agent
    becomes process 0 of a remote gang — a narrow reuse race, identical
    to every port-reservation scheme jax.distributed itself documents."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        sock.bind((host, 0))
        port = sock.getsockname()[1]
    finally:
        sock.close()
    return "{}:{}".format(host, port)


# ----------------------------------------------------------- fleet side


class AgentRecord:  # guarded-by: AgentPlane._lock
    """One joined agent's registry state. All mutable fields are guarded
    by the plane's lock (class-line annotation: externally
    synchronized)."""

    def __init__(self, agent_id: str, runner: int, host: str, chips: int,
                 process_index: int, coord_addr: Optional[str],
                 os_pid: Optional[int]):
        self.agent_id = agent_id
        self.runner = runner
        self.host = host
        self.chips = chips
        self.process_index = process_index
        self.coord_addr = coord_addr
        self.os_pid = os_pid
        self.joined_t = time.time()
        self.last_beat = time.monotonic()
        self.state = "idle"  # idle | leased | lost | left
        # Pending lease: the next ALEASE poll delivers it as ABIND.
        self.pending: Optional[Dict[str, Any]] = None
        self.pending_set_t = 0.0
        self.delivered = False
        self.delivered_t = 0.0
        self.abind_ms: Optional[float] = None
        # Current lease identity (exp name, pid) while leased.
        self.lease: Optional[Tuple[str, int]] = None
        self.done = False
        self.done_error: Optional[str] = None
        self.leases_served = 0

    def snapshot(self) -> Dict[str, Any]:
        return {"agent": self.agent_id, "runner": self.runner,
                "host": self.host, "chips": self.chips,
                "process_index": self.process_index,
                "state": self.state,
                "lease": self.lease[0] if self.lease else None,
                "pid": self.lease[1] if self.lease else None,
                "leases": self.leases_served,
                "last_beat_age_s": round(
                    time.monotonic() - self.last_beat, 2),
                "joined_t": self.joined_t}


class AgentPlane:
    """Fleet-side agent manager: admits agents (AJOIN), hands each a
    dedicated proxy thread that leases it through the scheduler's
    ordinary ``next_binding`` path, delivers leases as pending ABIND
    replies, and revokes leases whose agent went silent. Owns the
    ``FleetAgentServer`` published on the fleet's shared listener and
    the fleet ticket on disk."""

    def __init__(self, fleet, max_agents: int,
                 poll_s: float = DEFAULT_POLL_S,
                 liveness_s: float = DEFAULT_LIVENESS_S):
        self.fleet = fleet
        self.scheduler = fleet.scheduler
        self.telemetry = fleet.telemetry
        self.max_agents = int(max_agents)
        self.poll_s = float(poll_s)
        self.liveness_s = float(liveness_s)
        self._lock = threading.RLock()
        self._agents: Dict[str, AgentRecord] = {}  # guarded-by: _lock
        self._live_count = 0  # guarded-by: _lock
        self._seq = 0  # guarded-by: _lock
        self._stopped = False  # guarded-by: _lock
        self._threads: List[threading.Thread] = []  # guarded-by: _lock
        self.server = None
        self.ticket: Optional[Dict[str, Any]] = None

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "AgentPlane":
        from maggy_tpu.core.rpc import FleetAgentServer

        self.server = FleetAgentServer(self.max_agents)
        self.server.telemetry = self.telemetry
        self.server.attach_plane(self)
        host, port = self.fleet.shared_server.attach(
            self.server, host=self.fleet.bind_host)
        advertise = host
        if advertise in ("0.0.0.0", "", "::"):
            advertise = self.fleet.env.get_ip_address()
        sink_server = getattr(self.fleet, "sink_server", None)
        self.ticket = write_fleet_ticket(
            self.fleet.env,
            self.fleet.home_dir + "/" + AGENT_TICKET_NAME,
            advertise, port, self.server.secret_hex, self.fleet.name,
            self.max_agents,
            sink=sink_server.secret_hex if sink_server is not None
            else None)
        return self

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            threads = list(self._threads)
            leaving = [rec for rec in self._agents.values()
                       if rec.state in ("idle", "leased")]
            for rec in leaving:
                rec.state = "left"
        for rec in leaving:
            self._event(rec, "leave")
        for t in threads:
            t.join(timeout=5)
        if self.server is not None:
            self.server.stop()  # detaches from the shared listener

    # ---------------------------------------------------------- rpc handlers

    def agent_join(self, host, chips, process_index, coord_addr, os_pid,
                   agent) -> Dict[str, Any]:
        """AJOIN handler body. ``agent`` (a previous id) is accepted for
        restart-rejoin symmetry but a fresh identity is always minted —
        the dead record's lease was already revoked by its proxy, and id
        reuse would let two processes interleave one lease."""
        del agent
        with self._lock:
            if self._stopped:
                return {"type": "ERR", "error": "fleet is shutting down"}
            if self._live_count >= self.max_agents:
                return {"type": "ERR",
                        "error": "fleet is full ({} agent slot(s))".format(
                            self.max_agents)}
            self._seq += 1
            agent_id = "a{}-{}".format(self._seq, os.urandom(3).hex())
            self._live_count += 1
        runner = self.scheduler.agent_slot_attach()
        rec = AgentRecord(agent_id, runner, host=str(host or "?"),
                          chips=int(chips or 1),
                          process_index=int(process_index or 0),
                          coord_addr=coord_addr,
                          os_pid=int(os_pid) if os_pid else None)
        thread = threading.Thread(target=self._proxy_loop, args=(rec,),
                                  daemon=True,
                                  name="agent-proxy-{}".format(agent_id))
        with self._lock:
            self._agents[agent_id] = rec
            self._threads = [t for t in self._threads if t.is_alive()]
            self._threads.append(thread)
        self._event(rec, "join", host=rec.host, chips=rec.chips,
                    process_index=rec.process_index)
        thread.start()
        # rpc-ok: AJOIN reply literal, not a request producer — poll_s/liveness_s/server_t are consumed by the agent CLIENT (FleetAgent.join), a direction the checker does not model
        return {"type": "AJOIN", "agent": agent_id,
                "poll_s": self.poll_s, "liveness_s": self.liveness_s,
                "server_t": time.time()}

    def agent_lease(self, agent, offset_s=None,
                    rtt_s=None) -> Dict[str, Any]:
        """ALEASE handler body: idle heartbeat + lease delivery. A
        retried ALEASE (lost reply) re-serves the same undelivered ABIND
        — at-least-once delivery, idempotent on the agent side because
        the lease names one (exp, partition) pair.

        Clock piggyback: every reply carries ``server_t`` (this host's
        wall clock at reply build) so the agent's RTT-bounded offset
        estimator (telemetry.sink.ClockOffsetEstimator) gets a sample
        per poll; the agent reports its current estimate back on a
        cadence via ``offset_s``/``rtt_s``, journaled here as a
        ``clock_offset`` event per agent — the unified trace's
        cross-process time base."""
        if offset_s is not None:
            telem = self.telemetry
            if telem is not None:
                telem.event("clock_offset", agent=agent,
                            offset_s=float(offset_s),
                            rtt_s=float(rtt_s) if rtt_s is not None
                            else None)
        lease = None
        with self._lock:
            rec = self._agents.get(agent)
            if rec is None:
                return {"type": "ERR",
                        "error": "unknown agent {!r} (fleet restarted?); "
                                 "rejoin with AJOIN".format(agent)}
            rec.last_beat = time.monotonic()
            if self._stopped or rec.state in ("left", "lost"):
                # "lost": the fleet already revoked this agent's slot
                # (silence past the liveness bound) — a still-alive
                # agent reconnecting afterwards must exit and rejoin
                # under a FRESH identity, not zombie-poll a record whose
                # proxy is gone and that can never be leased again.
                return {"type": "AGSTOP"}
            if rec.pending is not None and not rec.done:
                first = not rec.delivered
                rec.delivered = True
                rec.delivered_t = time.monotonic()
                if first:
                    rec.abind_ms = round(
                        (rec.delivered_t - rec.pending_set_t) * 1e3, 3)
                lease = dict(rec.pending)
                abind_ms = rec.abind_ms
        if lease is not None:
            self._event_raw(agent, "lease", exp=lease.get("exp"),
                            pid=lease.get("partition_id"),
                            abind_ms=abind_ms)
            lease["server_t"] = time.time()
            return lease
        return {"type": "OK", "server_t": time.time()}

    def agent_done(self, agent, error) -> Dict[str, Any]:
        with self._lock:
            rec = self._agents.get(agent)
            if rec is None:
                return {"type": "ERR",
                        "error": "unknown agent {!r}".format(agent)}
            rec.last_beat = time.monotonic()
            rec.done = True
            rec.done_error = str(error) if error else None
            rec.pending = None
        return {"type": "OK"}

    # ------------------------------------------------------------ proxy loop

    def _proxy_loop(self, rec: AgentRecord) -> None:
        """Driver-side stand-in for one remote agent: the exact shape of
        ``Fleet._runner_loop``, with the executor call replaced by lease
        delivery + remote liveness watching. Runs until the agent leaves
        or is lost; the runner slot then returns to the vacancy pool."""
        scheduler = self.scheduler
        why = "leave"
        while True:
            with self._lock:
                stopped = self._stopped or rec.state == "left"
                idle_age = time.monotonic() - rec.last_beat
            if stopped:
                break
            if idle_age > self.liveness_s:
                why = "lost"
                break
            binding = scheduler.next_binding(rec.runner, timeout=0.25)
            if binding is None:
                if scheduler.stopped:
                    break
                continue
            entry, pid = binding
            err, reason = self._serve_lease(rec, entry, pid)
            scheduler.release_binding(rec.runner, entry, pid, error=err,
                                      reason=reason)
            if reason == "agent_lost":
                why = "lost"
                break
        with self._lock:
            rec.state = "lost" if why == "lost" else "left"
            rec.pending = None
            self._live_count -= 1
        if why == "lost":
            self._event(rec, "lost")
        scheduler.agent_slot_detach(rec.runner)

    def _serve_lease(self, rec: AgentRecord, entry, pid: int):
        """Deliver one lease to the agent and watch it to a terminal
        state. Returns ``(error, lease_end_reason)`` for
        ``release_binding``. The trial-requeue half of agent death is NOT
        here: the leased experiment's own heartbeat-loss scan (slot-
        reclaim liveness in core/rpc.py) requeues exactly once; this
        proxy only closes the fleet-side lease accounting."""
        info = dict(entry.agent_info or {})
        lease = {"type": "ABIND", "exp": entry.name,
                 "partition_id": int(pid), **info}
        now = time.monotonic()
        with self._lock:
            rec.pending = lease
            rec.pending_set_t = now
            rec.delivered = False
            rec.abind_ms = None
            rec.done = False
            rec.done_error = None
            rec.state = "leased"
            rec.lease = (entry.name, pid)
        drv = entry.driver
        res = drv.server.reservations if drv is not None else None
        bound = (drv.server.hb_loss_timeout
                 if drv is not None and drv.server.hb_loss_timeout
                 else self.liveness_s)
        deliver_deadline = now + max(self.liveness_s, 4 * self.poll_s)
        released_at: Optional[float] = None
        err: Optional[BaseException] = None
        reason = "released"
        while True:
            with self._lock:
                done, done_error = rec.done, rec.done_error
                delivered = rec.delivered
                delivered_t = rec.delivered_t
                stopped = self._stopped or rec.state == "left"
            if done:
                if done_error:
                    err = RuntimeError(
                        "agent {} failed lease for {!r} (partition {}): "
                        "{}".format(rec.agent_id, entry.name, pid,
                                    done_error))
                    reason = "error"
                break
            if stopped or self.scheduler.stopped:
                break
            now = time.monotonic()
            if not delivered:
                if now > deliver_deadline:
                    err = RuntimeError(
                        "agent {} vanished before its ABIND for {!r} was "
                        "delivered".format(rec.agent_id, entry.name))
                    reason = "agent_lost"
                    break
            else:
                rrec = res.get(pid) if res is not None else None
                if rrec is None:
                    if now - delivered_t > bound * _REG_GRACE_FACTOR:
                        # ABIND delivered but the agent never REGed: it
                        # died in between. No trial was assigned, so
                        # only the lease closes.
                        err = RuntimeError(
                            "agent {} took lease for {!r} but never "
                            "registered partition {}".format(
                                rec.agent_id, entry.name, pid))
                        reason = "agent_lost"
                        break
                elif rrec.get("released"):
                    # GSTOP observed by the executor: the ADONE is one
                    # poll away — bounded grace, then close anyway.
                    released_at = released_at or now
                    if now - released_at > _DONE_GRACE_S:
                        break
                elif res.is_silent(pid, bound):
                    # Mid-lease death: the experiment's LOST scan is
                    # requeueing the trial (exactly once); revoke the
                    # fleet lease.
                    err = RuntimeError(
                        "agent {} went silent mid-lease in {!r} "
                        "(partition {})".format(rec.agent_id, entry.name,
                                                pid))
                    reason = "agent_lost"
                    break
            time.sleep(0.05)
        with self._lock:
            rec.pending = None
            rec.lease = None
            rec.done = False
            if rec.state == "leased":
                rec.state = "idle"
            rec.leases_served += 1
            if reason != "agent_lost":
                # The agent was provably alive moments ago (experiment
                # heartbeats / its ADONE); without this, a long lease
                # whose last ALEASE poll predates it would read as
                # instant idle-silence back in the proxy loop.
                rec.last_beat = time.monotonic()
        self._event(rec, "done", exp=entry.name, pid=pid,
                    error=err is not None, reason=reason)
        return err, reason

    # -------------------------------------------------------------- querying

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [rec.snapshot() for rec in
                    sorted(self._agents.values(), key=lambda r: r.runner)]

    def record(self, agent_id: str) -> Optional[AgentRecord]:
        with self._lock:
            return self._agents.get(agent_id)

    def kill_agent_by_runner(self, runner_idx: int) -> bool:
        """SIGKILL the agent holding ``runner_idx``'s slot — same-host
        only (the soak/chaos path; an agent on another host can only be
        lost, not killed, from here). Returns True when a signal was
        sent."""
        import signal

        with self._lock:
            rec = next((r for r in self._agents.values()
                        if r.runner == runner_idx
                        and r.state in ("idle", "leased")), None)
            os_pid = rec.os_pid if rec is not None else None
        if not os_pid or os_pid == os.getpid():
            return False
        try:
            os.kill(os_pid, signal.SIGKILL)
            return True
        except OSError:
            return False

    def _event(self, rec: AgentRecord, phase: str, **fields: Any) -> None:
        self._event_raw(rec.agent_id, phase, runner=rec.runner, **fields)

    def _event_raw(self, agent_id: str, phase: str, **fields: Any) -> None:
        telem = self.telemetry
        if telem is not None:
            telem.event("agent", phase=phase, agent=agent_id, **fields)


# ----------------------------------------------------------- agent side


class _AgentChannel:
    """One persistent authenticated connection to the fleet's shared
    socket, with a single reconnect retry per call — the agent's polls
    are cheap and idempotent, so aggressive retry logic lives in the
    caller's loop, not here."""

    def __init__(self, addr: Tuple[str, int], secret: str,
                 timeout: float = 10.0):
        self.addr = tuple(addr)
        self.secret = secret.encode() if isinstance(secret, str) else secret
        self.timeout = timeout
        self._sock: Optional[socket.socket] = None

    def _connect(self) -> socket.socket:
        sock = socket.create_connection(self.addr, timeout=self.timeout)
        sock.settimeout(self.timeout)
        return sock

    def call(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        from maggy_tpu.core.rpc import MessageSocket

        for attempt in (0, 1):
            try:
                if self._sock is None:
                    self._sock = self._connect()
                MessageSocket.send_msg(self._sock, msg, self.secret)
                return MessageSocket.recv_msg(self._sock, self.secret)
            except (ConnectionError, socket.timeout, OSError):
                self.close()
                if attempt:
                    raise
        raise ConnectionError("unreachable")

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None


class FleetAgent:
    """The agent daemon body: JOIN the fleet, poll for leases, run each
    leased experiment's trial-executor loop with THAT experiment's
    secret on the SAME shared socket, report done, repeat. Lives in one
    process across many leases, which is exactly what keeps warm slots
    (train/warm.py) resident across same-family re-leases — the only
    cross-process reuse is the persistent XLA cache (docs/user.md)."""

    def __init__(self, ticket: Dict[str, Any], chips: int = 1,
                 process_index: int = 0, host: Optional[str] = None,
                 advertise_host: str = "127.0.0.1",
                 obs_port: Optional[int] = None, home: Optional[str] = None,
                 profile: bool = False):
        from maggy_tpu.telemetry.sink import ClockOffsetEstimator

        self.addr = (ticket["host"], int(ticket["port"]))
        self.secret = ticket["secret"]
        self.chips = int(chips)
        self.process_index = int(process_index)
        self.host = host or socket.gethostname()
        self.coord_addr = reserve_coord_addr(advertise_host)
        self.profile = profile
        self.agent_id: Optional[str] = None
        self.poll_s = DEFAULT_POLL_S
        self.liveness_s = DEFAULT_LIVENESS_S
        self.leases_served = 0
        self.last_error: Optional[str] = None
        self.current_exp: Optional[str] = None
        self._channel = _AgentChannel(self.addr, self.secret)
        self._stop = threading.Event()
        self._obs_port = obs_port
        self._home = home
        self._telemetry = None
        self._obs_registration = None
        #: Journal-sink shipping (telemetry/sink.py): with the ticket's
        #: ``sink`` secret present, this agent's journal + counters ship
        #: to the fleet host over the shared socket.
        self._sink_secret = ticket.get("sink")
        #: RTT-bounded clock-offset estimate vs the fleet host, fed by
        #: the server_t every AJOIN/ALEASE reply carries; reported back
        #: on a cadence and journaled fleet-side per agent.
        self.clock = ClockOffsetEstimator()
        self._offset_reported: Optional[float] = None
        self._offset_report_t = 0.0

    @classmethod
    def from_ticket(cls, path: str, wait_s: float = 0.0,
                    **kwargs) -> "FleetAgent":
        return cls(read_fleet_ticket(path, wait_s=wait_s), **kwargs)

    # ------------------------------------------------------------- lifecycle

    def join(self) -> str:
        t_send = time.time()
        resp = self._channel.call({
            "type": "AJOIN", "host": self.host, "chips": self.chips,
            "process_index": self.process_index,
            "coord_addr": self.coord_addr, "os_pid": os.getpid(),
            "agent": self.agent_id,
        })
        if resp.get("type") != "AJOIN":
            raise RuntimeError("AJOIN rejected: {}".format(
                resp.get("error", resp)))
        self.clock.sample(t_send, resp.get("server_t"), time.time())
        self.agent_id = resp["agent"]
        self.poll_s = float(resp.get("poll_s") or DEFAULT_POLL_S)
        self.liveness_s = float(resp.get("liveness_s")
                                or DEFAULT_LIVENESS_S)
        return self.agent_id

    def stop(self) -> None:
        self._stop.set()

    def status(self) -> Dict[str, Any]:
        return {"agent": self.agent_id, "host": self.host,
                "chips": self.chips, "process_index": self.process_index,
                "leases_served": self.leases_served,
                "lease": self.current_exp,
                "last_error": self.last_error}

    def _start_obs(self) -> None:
        if self._obs_port is None and not self._sink_secret:
            return
        from maggy_tpu.core.environment import EnvSing
        from maggy_tpu.telemetry import Telemetry
        from maggy_tpu.telemetry.sink import SinkBinding

        home = self._home
        if home is None:
            import tempfile

            home = tempfile.mkdtemp(prefix="maggy_agent_")
        self._home = home
        # With the ticket's sink secret, the agent's journal ships to
        # the fleet host (source = this agent's id) and agent.jsonl
        # becomes the degraded-mode fallback; without it, agent.jsonl is
        # the journal, exactly as before.
        sink = SinkBinding(self.addr, self._sink_secret) \
            if self._sink_secret else None
        self._telemetry = Telemetry(
            env=EnvSing.get_instance(),
            journal_path=home + "/agent.jsonl", enabled=True,
            sink=sink, sink_source=self.agent_id or "agent")
        if self._obs_port is not None:
            from maggy_tpu.telemetry import obs as obs_mod

            self._obs_registration = obs_mod.ObsRegistration(
                key="agent:{}".format(self.agent_id),
                labels={"experiment": "fleet-agent",
                        "run": self.agent_id or "agent"},
                telemetry=self._telemetry, status_fn=self.status)
            server = obs_mod.register(self._obs_registration,
                                      port=self._obs_port)
            self._telemetry.event("obs_started", host=server.address[0],
                                  port=server.address[1],
                                  experiment=self.agent_id)

    def _offset_to_report(self):
        """The (offset_s, rtt_s) pair to piggyback on the next ALEASE —
        when the estimate changed since the last report or the report
        cadence elapsed; None otherwise (most polls carry nothing)."""
        from maggy_tpu.telemetry.sink import OFFSET_REPORT_INTERVAL_S

        if self.clock.offset_s is None:
            return None
        changed = self._offset_reported != self.clock.offset_s
        due = (time.monotonic() - self._offset_report_t
               >= OFFSET_REPORT_INTERVAL_S)
        if changed or due:
            return (self.clock.offset_s, self.clock.rtt_s)
        return None

    def _stop_obs(self) -> None:
        if self._obs_registration is not None:
            from maggy_tpu.telemetry import obs as obs_mod

            obs_mod.deregister(self._obs_registration)
            self._obs_registration = None
        if self._telemetry is not None:
            self._telemetry.close()
            self._telemetry = None

    # ------------------------------------------------------------ agent loop

    def run(self, max_leases: Optional[int] = None,
            idle_exit_s: Optional[float] = None) -> int:
        """Poll until the fleet says AGSTOP (or ``max_leases`` /
        ``idle_exit_s`` for tests and batch jobs). Returns the number of
        leases served. Transient channel failures are retried up to the
        liveness bound — past it the fleet has already declared this
        agent lost, so exiting (for the supervisor to restart us into a
        FRESH identity) is the correct move."""
        if self.agent_id is None:
            self.join()
        os.environ["MAGGY_TPU_CAPACITY"] = str(self.chips)
        self._start_obs()
        idle_since = time.monotonic()
        fail_since: Optional[float] = None
        try:
            while not self._stop.is_set():
                req = {"type": "ALEASE", "agent": self.agent_id}
                report = self._offset_to_report()
                if report is not None:
                    req["offset_s"] = report[0]
                    req["rtt_s"] = report[1]
                t_send = time.time()
                try:
                    resp = self._channel.call(req)
                    fail_since = None
                except (ConnectionError, OSError):
                    now = time.monotonic()
                    fail_since = fail_since or now
                    if now - fail_since > self.liveness_s:
                        raise
                    time.sleep(min(1.0, self.poll_s * 2))
                    continue
                if report is not None:
                    self._offset_reported = report[0]
                    self._offset_report_t = time.monotonic()
                if self.clock.sample(t_send, resp.get("server_t"),
                                      time.time()) \
                        and self._telemetry is not None:
                    self._telemetry.event("clock_offset",
                                          agent=self.agent_id,
                                          offset_s=self.clock.offset_s,
                                          rtt_s=self.clock.rtt_s)
                rtype = resp.get("type")
                if rtype == "AGSTOP":
                    break
                if rtype == "ABIND":
                    idle_since = time.monotonic()
                    if self._telemetry is not None:
                        # Agent-side span of the lease: the unified
                        # trace renders lease..done as this agent's
                        # execution slice, the middle anchor of the
                        # ABIND -> execution -> FINAL flow arrow.
                        self._telemetry.event(
                            "agent", phase="lease", agent=self.agent_id,
                            exp=resp.get("exp"),
                            pid=resp.get("partition_id"),
                            # Warm prewarming hint: the experiment's
                            # program-family key ABIND shipped — same
                            # family as this process's last lease means
                            # its warm slots (train/warm.py) stay hot.
                            family=resp.get("family"))
                    error = self._serve(resp)
                    self.leases_served += 1
                    self.last_error = error
                    if self._telemetry is not None:
                        self._telemetry.event(
                            "agent", phase="done", agent=self.agent_id,
                            exp=resp.get("exp"),
                            pid=resp.get("partition_id"),
                            error=bool(error))
                        self._telemetry.metrics.counter(
                            "agent.leases").inc()
                        if error:
                            self._telemetry.metrics.counter(
                                "agent.lease_errors").inc()
                    # Same transient-failure patience as the poll: a
                    # brief host blip right at lease end must not kill
                    # an otherwise healthy agent before the liveness
                    # bound the poll path already tolerates.
                    done_deadline = time.monotonic() + self.liveness_s
                    while True:
                        try:
                            self._channel.call({"type": "ADONE",
                                                "agent": self.agent_id,
                                                "error": error})
                            break
                        except (ConnectionError, OSError):
                            if time.monotonic() >= done_deadline:
                                raise
                            time.sleep(min(1.0, self.poll_s * 2))
                    idle_since = time.monotonic()
                    if max_leases is not None \
                            and self.leases_served >= max_leases:
                        break
                    continue
                if rtype == "ERR":
                    raise RuntimeError(
                        "fleet refused poll: {}".format(resp.get("error")))
                if idle_exit_s is not None \
                        and time.monotonic() - idle_since > idle_exit_s:
                    break
                self._stop.wait(self.poll_s)
        finally:
            self._stop_obs()
            self._channel.close()
        return self.leases_served

    def _serve(self, lease: Dict[str, Any]) -> Optional[str]:
        """Run one lease to completion: import the train function by its
        dotted path and drive the standard TrialExecutor loop against
        the leased experiment's secret on the shared address. Returns an
        error string (for ADONE) or None."""
        from maggy_tpu.core.executors.trial_executor import TrialExecutor
        from maggy_tpu.runner import load_train_fn

        self.current_exp = lease.get("exp")
        try:
            train_fn = load_train_fn(lease["train_fn"])
            executor = TrialExecutor(
                server_addr=self.addr,
                secret=lease["secret"],
                hb_interval=lease["hb_interval"],
                exp_dir=lease["exp_dir"],
                optimization_key=lease["optimization_key"],
                train_fn=train_fn,
                trial_type=lease.get("trial_type", "optimization"),
                profile=self.profile,
                warm_start=lease.get("warm_start", True),
                host_port=self.coord_addr,
            )
            executor(int(lease["partition_id"]))
            return None
        except BaseException as e:  # noqa: BLE001 - lease failure, agent survives
            return repr(e)
        finally:
            self.current_exp = None


# ------------------------------------------------------------------- CLI


def agent_main(args) -> int:
    """Body of ``python -m maggy_tpu.fleet agent`` (argparse namespace
    built in fleet/__main__.py)."""
    if args.chips is not None and args.chips > 0 and args.pin:
        # Chip pinning must precede the first jax/libtpu init in this
        # process — same env contract as the local TPU pools.
        from maggy_tpu.core.runner_pool import chip_env

        for key, value in chip_env(args.process_index, args.chips).items():
            os.environ[key] = value
    if args.ticket:
        ticket = read_fleet_ticket(args.ticket, wait_s=args.wait_ticket)
    elif args.fleet_addr:
        host, _, port = args.fleet_addr.rpartition(":")
        if args.secret_file:
            with open(args.secret_file) as f:
                secret = f.read().strip()
        elif args.secret:
            secret = args.secret
        else:
            raise SystemExit("--fleet-addr requires --secret or "
                             "--secret-file")
        ticket = {"host": host, "port": int(port), "secret": secret}
    else:
        raise SystemExit("one of --ticket or --fleet-addr is required")
    agent = FleetAgent(
        ticket, chips=args.chips or 1, process_index=args.process_index,
        advertise_host=args.advertise_host, obs_port=args.obs_port,
        home=args.home, profile=args.profile)
    agent.join()
    print("agent {} joined fleet at {}:{}".format(
        agent.agent_id, ticket["host"], ticket["port"]), flush=True)
    try:
        served = agent.run(max_leases=args.max_leases,
                           idle_exit_s=args.idle_exit)
    except (ConnectionError, OSError) as e:
        # The fleet host vanished (no AGSTOP possible) and stayed gone
        # past the liveness bound — the fleet has already declared this
        # agent lost, so exit nonzero for the supervisor to restart us
        # into a fresh identity. A traceback here is noise, not signal.
        print("agent {} lost the fleet ({!r}); exiting for restart".format(
            agent.agent_id, e), flush=True)
        return 1
    print("agent {} done ({} lease(s) served)".format(
        agent.agent_id, served), flush=True)
    return 0
