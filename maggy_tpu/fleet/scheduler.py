"""Shared-fleet scheduler: multiplex concurrent experiments over one
persistent runner fleet.

The classic ``lagom()`` path owns its runner pool for the lifetime of one
experiment and tears it down afterwards — a v4-32 pod serving many users
sits idle between sweeps. Fleet mode inverts the ownership (the Podracer
shape, arXiv:2104.06272): a ``Fleet`` holds a long-lived pool of runner
loops, and a ``FleetScheduler`` leases them to whichever submitted
experiments deserve them under

- **priority classes** (``high``/``normal``/``low`` or any int; lower rank
  wins) — capacity is granted strictly by class when computing targets;
- **weighted fair share** — within the capacity a class receives, runners
  are split proportionally to each experiment's ``weight`` (largest
  remainder), and lease-time accounting (virtual time = runner-seconds /
  weight) breaks ties so long-run shares track the weights even when
  allocation is lumpy;
- **per-experiment quotas** — ``min_runners`` is satisfied first (in
  priority order), ``max_runners`` caps what fair share may grant;
- an **admission queue** — at most ``max_active`` experiments compete at
  once; the rest wait in (priority, submit-order) line;
- **preemption** — an experiment below its guaranteed allocation for
  longer than ``preempt_grace_s`` triggers a *graceful* preemption of the
  most-over-share victim: the victim driver flags the trial through the
  existing early-stop machinery (the STOP reply carries ``preempt``), the
  runner acks with a preempted FINAL carrying its last checkpoint step
  (``train/checkpoint.py`` layout), the driver requeues the trial so it
  *resumes from that step* on its next runner (requeue-from-scratch when
  it never checkpointed), and the freed runner re-binds to the starving
  experiment.

Runners are re-bindable: one fleet runner executes experiment A's trial
executor until released (GSTOP or eviction), then asks the scheduler for
its next binding and re-registers against experiment B's server with B's
secret and executor config. Per-experiment control-plane traffic shares
ONE listening socket (``core.rpc.SharedServer``), routed by which
experiment's HMAC secret authenticates the frame.

Everything the scheduler decides is journaled to ``fleet.jsonl``
(``lease`` start/end, ``preempt``, admission, lifecycle), so shares,
queue waits, and preemption counts are replayable offline
(``replay_fleet_journal``) and renderable as per-experiment lanes on each
runner track (``python -m maggy_tpu.telemetry trace <fleet_home>``).
"""

from __future__ import annotations

import heapq
import itertools
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from maggy_tpu.core.runner_pool import RunnerPool, ThreadRunnerPool

#: Fleet journal filename inside the fleet home dir.
FLEET_JOURNAL_NAME = "fleet.jsonl"

#: Named priority classes (lower rank = served first). Ints pass through.
PRIORITY_CLASSES = {"high": 0, "normal": 1, "low": 2}

#: How long a computed fair-share target table may be reused before the
#: next binding/preemption decision recomputes it. Structural changes
#: (admit/finish/activate) invalidate it immediately; the TTL only covers
#: live-read drift (a driver flipping experiment_done between ticks),
#: and matches the scheduler's own 0.1-0.2 s decision cadence.
TARGETS_TTL_S = 0.05


class FleetSaturated(RuntimeError):
    """Admission shedding: the fleet's submission queue is at its
    ``max_queued`` bound — the submission was refused (and journaled as
    a ``shed`` event) instead of queued unboundedly. Callers back off
    and resubmit; the spool feeder simply leaves specs unclaimed until
    the queue drains."""


def _base_name(name: str) -> str:
    """A resubmitted tenant's scheduler name carries a ``-<seq>`` dedup
    suffix (Fleet.submit); failover matching (parked gang blocks) keys
    on the base name so the restarted submission finds its block."""
    stem, sep, tail = name.rpartition("-")
    if sep and tail.isdigit():
        return stem
    return name


def priority_rank(priority) -> int:
    if isinstance(priority, str):
        try:
            return PRIORITY_CLASSES[priority.lower()]
        except KeyError:
            raise ValueError(
                "Unknown priority {!r}; use one of {} or an int".format(
                    priority, sorted(PRIORITY_CLASSES)))
    if isinstance(priority, bool) or not isinstance(priority, int):
        raise ValueError("priority must be a class name or int, got "
                         "{!r}".format(priority))
    return priority


class FleetPolicy:
    """Scheduling policy of one submission: priority class, fair-share
    weight, and the min/max runner quota."""

    __slots__ = ("priority", "weight", "min_runners", "max_runners")

    def __init__(self, priority="normal", weight: float = 1.0,
                 min_runners: int = 0, max_runners: Optional[int] = None):
        priority_rank(priority)  # validate early
        self.priority = priority
        self.weight = float(weight)
        if self.weight <= 0:
            raise ValueError("weight must be > 0, got {}".format(weight))
        self.min_runners = int(min_runners)
        if self.min_runners < 0:
            raise ValueError("min_runners must be >= 0")
        self.max_runners = None if max_runners is None else int(max_runners)
        if self.max_runners is not None and self.max_runners < 1:
            raise ValueError("max_runners must be >= 1 (or None)")
        if self.max_runners is not None \
                and self.min_runners > self.max_runners:
            raise ValueError("min_runners {} exceeds max_runners {}".format(
                self.min_runners, self.max_runners))

    @property
    def rank(self) -> int:
        return priority_rank(self.priority)

    def to_dict(self) -> Dict[str, Any]:
        return {"priority": self.priority, "weight": self.weight,
                "min_runners": self.min_runners,
                "max_runners": self.max_runners}


class ExperimentEntry:  # guarded-by: FleetScheduler._lock
    """One submitted experiment's scheduling state. All mutable fields are
    guarded by the scheduler's lock (class-line annotation: the guards
    checker treats the whole class as externally synchronized)."""

    def __init__(self, name: str, policy: FleetPolicy, seq: int):
        self.name = name
        self.policy = policy
        self.seq = seq
        self.state = "queued"  # queued -> active -> done | failed
        self.submitted_t = time.time()
        self.admitted_t: Optional[float] = None
        self.first_lease_t: Optional[float] = None
        # Dotted module:function path of the submission's train fn (set
        # at submit when derivable) — what an ABIND lease ships to a
        # REMOTE agent. None = agent-ineligible (closure/lambda/__main__
        # train fns can't be named on the wire): only thread runners
        # serve this experiment.
        self.train_fn_path: Optional[str] = None
        # Built at activate(): the executor config an agent lease
        # carries (secret, hb_interval, exp_dir, ..., train_fn). None =
        # agent-ineligible.
        self.agent_info: Optional[Dict[str, Any]] = None
        # Bound at activate() (the driver exists by then):
        self.driver = None
        self.executor_fn: Optional[Callable[[int], None]] = None
        self.slots = 0
        self.free_pids: set = set()
        self.exp_dir: Optional[str] = None
        # Lease accounting.
        self.open_leases: Dict[int, Tuple[int, float]] = {}  # runner -> (pid, t0)
        self.service_s = 0.0
        self.lease_count = 0
        self.preemptions = 0          # suffered
        self.preempting_pids: set = set()
        self.failures: List[BaseException] = []
        self.deficit_since: Optional[float] = None

    # -- read helpers (scheduler lock held) --------------------------------

    def allocated(self) -> int:
        return len(self.open_leases)

    def effective_max(self, fleet_size: int) -> int:
        cap = fleet_size
        if self.policy.max_runners is not None:
            cap = min(cap, self.policy.max_runners)
        if self.slots:
            cap = min(cap, self.slots)
        return cap

    def chip_seconds(self, now: float) -> float:
        """Total chip-time this tenant has held: closed leases
        (``service_s``) plus the live time of every still-open lease.
        Fleet runners lease one chip each, so lease-seconds ==
        chip-seconds; the goodput ledger divides this same number into
        train vs badput buckets from the tenant's own journal."""
        live = sum(now - t0 for _, t0 in self.open_leases.values())
        return self.service_s + live

    def vtime(self, now: float) -> float:
        return self.chip_seconds(now) / self.policy.weight

    def ready(self) -> bool:
        return self.state == "active" and self.executor_fn is not None

    def wants_runners(self) -> bool:
        if not self.ready() or not self.free_pids:
            return False
        drv = self.driver
        return not (drv is not None and drv.experiment_done)

    def snapshot(self) -> Dict[str, Any]:
        qw = None
        if self.first_lease_t is not None:
            qw = round(self.first_lease_t - self.submitted_t, 3)
        return {"name": self.name, "state": self.state,
                **self.policy.to_dict(),
                "allocated": self.allocated(), "leases": self.lease_count,
                "service_s": round(self.service_s, 3),
                "chip_seconds": round(self.chip_seconds(time.monotonic()),
                                      3),
                "preemptions": self.preemptions,
                "queue_wait_s": qw, "failures": len(self.failures),
                "exp_dir": self.exp_dir}


class FleetScheduler:
    """Decides which experiment each free fleet runner serves next, and
    when a running one must give a runner up. Pure in-process state; every
    decision is journaled through the fleet's telemetry."""

    def __init__(self, fleet_size: int, telemetry=None,
                 max_active: Optional[int] = None,
                 preempt_grace_s: float = 1.0,
                 max_queued: Optional[int] = None,
                 max_size: Optional[int] = None,
                 tenant_grace_s: float = 10.0):
        self.fleet_size = int(fleet_size)
        # Upper bound the fleet can GROW to as remote agents join
        # (thread runners + agent slots). Gang feasibility checks
        # compare against this, not the current size — a gang that fits
        # once the agents arrive must park, not fail.
        self.max_size = int(max_size) if max_size is not None \
            else self.fleet_size
        self.telemetry = telemetry
        self.max_active = max_active
        self.max_queued = max_queued
        self.preempt_grace_s = float(preempt_grace_s)
        self._lock = threading.RLock()
        self._wake = threading.Condition(self._lock)
        self._entries: Dict[str, ExperimentEntry] = {}  # guarded-by: _lock
        # Indexes keeping every per-decision sweep O(active), not
        # O(submitted): the ADMITTED set (binding, targets, preemption
        # all iterate only this — at most max_active entries no matter
        # how many hundreds sit queued behind it) and the admission
        # queue as a (rank, seq) heap popped lazily, so admitting one
        # experiment is O(log queued) instead of re-sorting every
        # queued entry per submit/finish.
        self._active: Dict[str, ExperimentEntry] = {}  # guarded-by: _lock
        self._queued_heap: List[Tuple[int, int, str]] = []  # guarded-by: _lock
        self._queued_count = 0  # guarded-by: _lock
        # Cached fair-share target table (the waterfill is O(active *
        # rounds)): invalidated on structural change, TTL-bounded
        # otherwise, so a burst of next_binding calls between changes
        # shares one computation.
        self._targets_cache: Optional[Dict[str, int]] = None  # guarded-by: _lock
        self._targets_stamp = 0.0  # guarded-by: _lock
        self.shed_count = 0  # guarded-by: _lock
        # Final snapshots of completed experiments (bounded): finished
        # entries leave _entries so scheduling decisions stay O(live)
        # and a long-lived fleet host doesn't grow without bound.
        self._finished: List[Dict[str, Any]] = []  # guarded-by: _lock
        # Gang-block reservations: experiment name -> contiguous fleet
        # runner ids reserved for its gang-scheduled trials. A runner
        # inside a block binds ONLY to the reserving experiment (and is
        # protected from preemption), so the experiment's driver can
        # assemble an N-chip contiguous mesh slice out of fleet runners
        # without fair share starving the gang at N-1 members forever.
        self._gang_blocks: Dict[str, List[int]] = {}  # guarded-by: _lock
        # Crash-only tenant failover: a FAILED tenant's gang block is
        # PARKED for tenant_grace_s instead of redistributed — a driver
        # restart (the resubmitted tenant, matched by base name) reclaims
        # the same contiguous window instead of re-queueing behind every
        # other experiment's block demand; expiry releases it to fair
        # share. base-name -> (block, monotonic expiry).
        self.tenant_grace_s = float(tenant_grace_s)
        self._parked_blocks: Dict[str, Tuple[List[int], float]] = {}  # guarded-by: _lock
        # Warm prewarming hints: agent slot -> the program-family key
        # (the submission's dotted train-fn path) it last served — the
        # binding pick prefers handing an agent a same-family experiment
        # so its per-process warm slots (train/warm.py) stay hot.
        self._slot_family: Dict[int, str] = {}  # guarded-by: _lock
        # Parent affinity (checkpoint-forking search): agent slot -> the
        # EXPERIMENT it last served. A re-lease to the same experiment
        # is strictly warmer than same-family: the agent holds that
        # experiment's warm slots AND its parents' trial checkpoints on
        # local disk, so a forked promotion staged there loads without a
        # cross-process copy. Ranked above family, below fair share.
        self._slot_exp: Dict[int, str] = {}  # guarded-by: _lock
        # Remote-agent runner slots (maggy_tpu.fleet.agent): indexes at
        # and above the thread-fleet size, allocated as agents join.
        # Vacant slots (their agent left/died) stay allocated — indexes
        # are identities in the journal — but stop counting toward
        # fair-share capacity until the next joiner reuses them.
        self._agent_slots: set = set()  # guarded-by: _lock
        self._vacant_agent_slots: set = set()  # guarded-by: _lock
        self._seq = itertools.count()
        self.stopped = False

    # ------------------------------------------------------------ lifecycle

    def submit(self, name: str, policy: FleetPolicy) -> ExperimentEntry:
        with self._lock:
            if name in self._entries:
                raise ValueError(
                    "experiment {!r} is already submitted to this "
                    "fleet".format(name))
            if self.max_queued is not None \
                    and self._queued_count >= self.max_queued:
                # Admission shedding: refuse instead of queueing without
                # bound — a saturated control plane must say so, not
                # absorb submissions into an ever-slower backlog.
                self.shed_count += 1
                self._event("shed", exp=name, scope="admission",
                            queued=self._queued_count)
                telem = self.telemetry
                if telem is not None:
                    telem.metrics.counter("fleet.shed_total").inc()
                raise FleetSaturated(
                    "fleet admission queue is full ({} queued, bound {}); "
                    "submission {!r} shed — resubmit after the queue "
                    "drains".format(self._queued_count, self.max_queued,
                                    name))
            entry = ExperimentEntry(name, policy, next(self._seq))
            self._entries[name] = entry
            self._queued_count += 1
            heapq.heappush(self._queued_heap,
                           (policy.rank, entry.seq, name))
            self._event("fleet_submit", exp=name, **policy.to_dict())
            self._admit_locked()
            self._wake.notify_all()
        return entry

    # locked-by: _lock
    def _admit_locked(self) -> None:
        """Admit from the (rank, seq) heap up to ``max_active``. Heap
        entries are popped lazily: an entry that finished (or was never
        created) while queued is skipped, so admission stays O(log
        queued) per admit with no rebuild on finish."""
        while self._queued_heap:
            if self.max_active is not None \
                    and len(self._active) >= self.max_active:
                break
            _rank, _seq, name = heapq.heappop(self._queued_heap)
            entry = self._entries.get(name)
            if entry is None or entry.state != "queued":
                continue  # finished/failed while queued: lazy deletion
            entry.state = "active"
            entry.admitted_t = time.time()
            self._active[name] = entry
            self._queued_count -= 1
            self._targets_cache = None
            self._event("fleet_admit", exp=entry.name,
                        queued_s=round(entry.admitted_t
                                       - entry.submitted_t, 3))

    def activate(self, entry: ExperimentEntry, driver,
                 executor_fn: Callable[[int], None], slots: int) -> None:
        """The experiment's driver is up: bind it so leasing can begin.
        ``slots`` is the driver's partition-id range (its server's
        num_executors)."""
        agent_info = self._build_agent_info(entry, driver)
        with self._lock:
            entry.driver = driver
            entry.executor_fn = executor_fn
            entry.agent_info = agent_info
            entry.slots = int(slots)
            entry.free_pids = set(range(int(slots)))
            entry.exp_dir = getattr(driver, "exp_dir", None)
            self._targets_cache = None
            self._event("fleet_experiment", exp=entry.name, phase="start",
                        slots=entry.slots, exp_dir=entry.exp_dir)
            self._wake.notify_all()

    @staticmethod
    def _build_agent_info(entry: ExperimentEntry,
                          driver) -> Optional[Dict[str, Any]]:
        """The executor config an ABIND lease ships to a remote agent —
        the fleet generalization of the per-experiment JOIN reply. None
        when the experiment can't be served remotely: no wire-nameable
        train fn, or a driver without the trial-executor loop shape
        (only HPO/ablation drivers lease agents today)."""
        if entry.train_fn_path is None:
            return None
        okey = getattr(driver, "optimization_key", None)
        if okey is None:
            return None
        return {
            "secret": driver.secret_for_clients(),
            "hb_interval": driver.hb_interval,
            "exp_dir": driver.exp_dir,
            "optimization_key": okey,
            "trial_type": "optimization",
            # Honest warm-state note: warm slots are PER-PROCESS — the
            # flag keeps them across same-family re-leases within one
            # agent process; the persistent XLA cache is the only
            # cross-process reuse (docs/user.md).
            "warm_start": bool(getattr(driver.config, "warm_start", True)),
            "train_fn": entry.train_fn_path,
            # The experiment's program-family key (prewarming hints):
            # the scheduler prefers re-leasing an agent to the family it
            # last served, and the agent journals the key so warm-hint
            # accuracy is auditable end to end.
            "family": entry.train_fn_path,
        }

    def wait_admitted(self, entry: ExperimentEntry,
                      timeout: Optional[float] = None) -> bool:
        """Block until ``entry`` is admitted past the queue (True) or the
        fleet stops / the entry finishes first (False). The deferred-
        activation hook: a submission thread builds its driver only after
        this returns True, so a thousand queued tenants cost a thousand
        heap entries — not a thousand live drivers."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                if entry.state == "active":
                    return True
                if self.stopped or entry.state in ("done", "failed"):
                    return False
                if deadline is not None:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        return False
                    self._wake.wait(timeout=min(left, 0.2))
                else:
                    self._wake.wait(timeout=0.2)

    def finish(self, entry: ExperimentEntry, state: str = "done") -> None:
        with self._lock:
            if entry.state in ("done", "failed"):
                return
            was_queued = entry.state == "queued"
            entry.state = state
            if was_queued:
                self._queued_count -= 1  # heap entry reaped lazily
            else:
                self._active.pop(entry.name, None)
            self._targets_cache = None
            self._event("fleet_experiment", exp=entry.name, phase=state)
            # A finished experiment's gang block must not park runners
            # forever (the driver normally releases it, but a crashed
            # driver may not have). A FAILED tenant — a crashed driver
            # awaiting restart — keeps its block PARKED for the grace
            # window instead: the resubmitted tenant reclaims the same
            # contiguous window (crash-only failover) and only expiry
            # redistributes it.
            block = self._gang_blocks.pop(entry.name, None)
            if block is not None:
                if state == "failed" and self.tenant_grace_s > 0:
                    self._parked_blocks[_base_name(entry.name)] = (
                        block, time.monotonic() + self.tenant_grace_s)
                    self._event("pack", op="fleet_park", exp=entry.name,
                                block=block,
                                grace_s=self.tenant_grace_s)
                else:
                    self._event("pack", op="fleet_release",
                                exp=entry.name)
            # Retire the entry: late release_binding calls still work on
            # the object itself; only the scheduling/status sets forget
            # it. Keep a bounded tail of final snapshots for status.json.
            self._entries.pop(entry.name, None)
            self._finished.append(entry.snapshot())
            del self._finished[:-100]
            self._admit_locked()
            self._wake.notify_all()

    def stop(self) -> None:
        with self._lock:
            self.stopped = True
            self._wake.notify_all()

    # ---------------------------------------------------------- agent slots

    def agent_slot_attach(self) -> int:
        """Allocate a runner index for a joining remote agent: reuse the
        lowest vacant agent slot, else grow the fleet by one. The index
        behaves exactly like a thread runner's in every lease path."""
        with self._lock:
            if self._vacant_agent_slots:
                idx = min(self._vacant_agent_slots)
                self._vacant_agent_slots.discard(idx)
            else:
                idx = self.fleet_size
                self.fleet_size += 1
                self._agent_slots.add(idx)
            self._targets_cache = None
            self._wake.notify_all()
            return idx

    def agent_slot_detach(self, runner_idx: int) -> None:
        """The slot's agent left or was lost: the index stops counting
        toward fair-share capacity until the next joiner reuses it. Its
        warm-family hint dies with the process — the NEXT joiner reusing
        this index is a fresh interpreter with cold slots, and a stale
        hint would fake warmth."""
        with self._lock:
            if runner_idx in self._agent_slots:
                self._vacant_agent_slots.add(runner_idx)
                self._slot_family.pop(runner_idx, None)
                self._slot_exp.pop(runner_idx, None)
                self._targets_cache = None
                self._wake.notify_all()

    def is_agent_slot(self, runner_idx: int) -> bool:
        with self._lock:
            return runner_idx in self._agent_slots

    def live_agent_slots(self) -> int:
        with self._lock:
            return len(self._agent_slots) - len(self._vacant_agent_slots)

    # -------------------------------------------------------------- targets

    # locked-by: _lock
    def _targets_locked(self) -> Dict[str, int]:
        """Cached wrapper around the fair-share waterfill: structural
        changes (admit/finish/activate) clear the cache; otherwise a
        short TTL bounds staleness to the scheduler's own decision
        cadence. Keeps a burst of binding decisions from recomputing an
        identical table per free runner."""
        now = time.monotonic()
        cached = self._targets_cache
        if cached is not None and now - self._targets_stamp < TARGETS_TTL_S:
            return cached
        targets = self._compute_targets_locked()
        self._targets_cache = targets
        self._targets_stamp = now
        return targets

    # locked-by: _lock
    def _compute_targets_locked(self) -> Dict[str, int]:
        """Per-experiment runner target: min_runners first in priority
        order, then leftover capacity waterfilled class by class with a
        weighted largest-remainder split, clamped to each experiment's
        effective max. This is the allocation both binding and preemption
        steer toward. Iterates the ADMITTED index only — queued tenants
        cannot deserve runners, so they must not cost sweep time."""
        active = [e for e in self._active.values()
                  if e.ready() and not (e.driver is not None
                                        and e.driver.experiment_done)]
        targets = {e.name: 0 for e in active}
        # Vacant agent slots hold no runner: capacity they'd promise can
        # never be leased, so the waterfill excludes them.
        remaining = self.fleet_size - len(self._vacant_agent_slots)
        # Guaranteed minimums, strictly by priority then submit order.
        for e in sorted(active, key=lambda e: (e.policy.rank, e.seq)):
            give = min(e.policy.min_runners, e.effective_max(self.fleet_size),
                       remaining)
            targets[e.name] = give
            remaining -= give
        # Leftovers: class by class, weighted largest remainder.
        by_rank: Dict[int, List[ExperimentEntry]] = {}
        for e in active:
            by_rank.setdefault(e.policy.rank, []).append(e)
        for rank in sorted(by_rank):
            if remaining <= 0:
                break
            members = by_rank[rank]
            while remaining > 0:
                head = [e for e in members
                        if targets[e.name] < e.effective_max(self.fleet_size)]
                if not head:
                    break
                wsum = sum(e.policy.weight for e in head)
                grant = {}
                for e in head:
                    grant[e.name] = remaining * e.policy.weight / wsum
                floors = {n: int(g) for n, g in grant.items()}
                used = 0
                for e in head:
                    room = e.effective_max(self.fleet_size) - targets[e.name]
                    add = min(floors[e.name], room)
                    targets[e.name] += add
                    used += add
                if used == 0:
                    # All floors were zero: hand single runners out by
                    # largest fractional remainder until spent.
                    order = sorted(
                        head, key=lambda e: (-(grant[e.name]
                                               - floors[e.name]), e.seq))
                    for e in order:
                        if remaining - used <= 0:
                            break
                        if targets[e.name] < e.effective_max(self.fleet_size):
                            targets[e.name] += 1
                            used += 1
                if used == 0:
                    break
                remaining -= used
        return targets

    # ---------------------------------------------------------- gang blocks

    def request_gang(self, entry: ExperimentEntry,
                     size: int) -> Optional[List[int]]:
        """Reserve a contiguous block of ``size`` fleet runners for
        ``entry``'s gang-scheduled trials (topology-aware: lowest start
        among windows disjoint from other experiments' blocks, preferring
        size-aligned starts, fewest currently-bound-elsewhere runners so
        the block drains fastest). Sticky until ``release_gang``; the
        reservation both routes freed block runners to the experiment
        and shields them from preemption sweeps."""
        from maggy_tpu.gang import aligned_windows

        size = int(size)
        if size > self.max_size:
            # Clamping would latch a too-small block and hang the
            # experiment's gang demand forever — fail loudly instead.
            # Compared against the GROWN-TO bound: a fleet still waiting
            # for its agents returns None below (no window yet) and the
            # caller retries.
            raise ValueError(
                "a gang of {} runners can never assemble on a {}-runner "
                "fleet".format(size, self.max_size))
        with self._lock:
            existing = self._gang_blocks.get(entry.name)
            if existing is not None:
                return list(existing)
            self._expire_parked_locked()
            # Crash-only failover: a restarted tenant reclaims the block
            # its dead incarnation held (parked at finish("failed"))
            # instead of re-competing for a window.
            parked = self._parked_blocks.pop(_base_name(entry.name), None)
            if parked is not None:
                block = parked[0]
                self._gang_blocks[entry.name] = block
                self._event("pack", op="fleet_reclaim", exp=entry.name,
                            block=block)
                self._wake.notify_all()
                return list(block)
            taken = {r for b in self._gang_blocks.values() for r in b}
            # Parked blocks stay un-redistributable for the grace window:
            # another tenant's gang must not squat the window a
            # restarting driver is about to reclaim. (1-runner bindings
            # still flow — only gang WINDOWS are shielded.)
            taken |= {r for b, _exp in self._parked_blocks.values()
                      for r in b}
            bound_elsewhere = set()
            for e in self._entries.values():
                if e is not entry:
                    bound_elsewhere |= set(e.open_leases.keys())
            aligned = aligned_windows(self.fleet_size, size, taken)
            if not aligned:
                return None
            block = min(aligned, key=lambda w: (
                sum(1 for r in w if r in bound_elsewhere), w[0]))
            self._gang_blocks[entry.name] = block
            self._event("pack", op="fleet_reserve", exp=entry.name,
                        block=block)
            self._wake.notify_all()
            return list(block)

    def release_gang(self, entry: ExperimentEntry) -> None:
        with self._lock:
            block = self._gang_blocks.pop(entry.name, None)
            if block is not None:
                self._event("pack", op="fleet_release", exp=entry.name,
                            block=block)
                self._wake.notify_all()

    # locked-by: _lock
    def _expire_parked_locked(self) -> None:
        """Release parked blocks whose restart grace ran out — the dead
        tenant never came back; its window returns to fair share."""
        now = time.monotonic()
        for base, (block, expiry) in list(self._parked_blocks.items()):
            if now >= expiry:
                del self._parked_blocks[base]
                self._event("pack", op="fleet_release", exp=base,
                            block=block, expired=True)
                self._wake.notify_all()

    # locked-by: _lock
    def _gang_owner_locked(self, runner_idx: int
                           ) -> Optional[ExperimentEntry]:
        for name, block in self._gang_blocks.items():
            if runner_idx in block:
                return self._entries.get(name)
        return None

    # -------------------------------------------------------------- binding

    def next_binding(self, runner_idx: int,
                     timeout: Optional[float] = None
                     ) -> Optional[Tuple[ExperimentEntry, int]]:
        """Block until an experiment deserves this runner; returns
        ``(entry, partition_id)`` or None when the fleet is shutting down
        (or ``timeout`` elapsed)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                if self.stopped:
                    return None
                picked = self._pick_locked(runner_idx)
                if picked is not None:
                    return self._lease_locked(runner_idx, picked)
                if deadline is not None and time.monotonic() >= deadline:
                    return None
                self._wake.wait(timeout=0.2)

    # locked-by: _lock
    def _pick_locked(self, runner_idx: int) -> Optional[ExperimentEntry]:
        # A runner inside a gang block binds ONLY to the reserving
        # experiment — and bypasses the fair-share target (a gang needs
        # its whole contiguous block SIMULTANEOUSLY; granting N-1 and
        # fair-sharing the Nth would deadlock the gang). If the owner
        # can't take it right now, the runner waits: binding it
        # elsewhere would re-busy the block instead of draining it.
        is_agent = runner_idx in self._agent_slots
        owner = self._gang_owner_locked(runner_idx)
        if owner is not None:
            if is_agent and owner.agent_info is None:
                return None
            if owner.wants_runners() and \
                    owner.allocated() < owner.effective_max(self.fleet_size):
                return owner
            return None
        targets = self._targets_locked()
        now = time.monotonic()
        slot_family = self._slot_family.get(runner_idx) if is_agent \
            else None
        slot_exp = self._slot_exp.get(runner_idx) if is_agent else None
        best = None
        best_key = None
        for e in self._active.values():
            if not e.wants_runners():
                continue
            if is_agent and e.agent_info is None:
                # A remote agent can only serve experiments whose train
                # fn is wire-nameable (ABIND ships a dotted path).
                continue
            if e.allocated() >= e.effective_max(self.fleet_size):
                continue
            # Warm prewarming hint: among equally-deserving (same
            # deficit, same class) candidates, prefer (0) the SAME
            # experiment this agent last served — parent affinity: its
            # warm slots AND its trials' checkpoints (fork sources) live
            # in that agent's process/disk — then (1) the same program
            # family (compiled step reuse, train/warm.py), then (2)
            # cold. Ranked below deficit and class so warmth can never
            # override fair share or priority.
            if slot_exp is not None and e.name == slot_exp:
                cold = 0
            elif slot_family is not None \
                    and e.train_fn_path == slot_family:
                cold = 1
            else:
                cold = 2
            key = (e.allocated() - targets.get(e.name, 0),
                   e.policy.rank, cold, e.vtime(now), e.seq)
            if best_key is None or key < best_key:
                best, best_key = e, key
        return best

    # locked-by: _lock
    def _lease_locked(self, runner_idx: int,
                      entry: ExperimentEntry) -> Tuple[ExperimentEntry, int]:
        pid = min(entry.free_pids)
        entry.free_pids.discard(pid)
        entry.open_leases[runner_idx] = (pid, time.monotonic())
        entry.lease_count += 1
        entry.deficit_since = None
        if entry.first_lease_t is None:
            entry.first_lease_t = time.time()
        # Warm prewarming hint bookkeeping (agent slots only: warm slots
        # are per-process, and only agents persist across leases):
        # warm_hint=True means this lease lands on an agent that already
        # holds the experiment's program family warm; warm_affinity
        # grades it — "experiment" (parent affinity: same experiment,
        # checkpoints on local disk) beats "family" (compiled step only).
        warm_hint = None
        warm_affinity = None
        if runner_idx in self._agent_slots \
                and entry.train_fn_path is not None:
            warm_hint = self._slot_family.get(runner_idx) \
                == entry.train_fn_path
            if self._slot_exp.get(runner_idx) == entry.name:
                warm_affinity = "experiment"
            elif warm_hint:
                warm_affinity = "family"
            self._slot_family[runner_idx] = entry.train_fn_path
            self._slot_exp[runner_idx] = entry.name
        self._event("lease", exp=entry.name, runner=runner_idx, pid=pid,
                    phase="start", exp_dir=entry.exp_dir,
                    warm_hint=warm_hint, warm_affinity=warm_affinity)
        self._chip_gauge(entry)
        return entry, pid

    def release_binding(self, runner_idx: int, entry: ExperimentEntry,
                        pid: int, error: Optional[BaseException] = None,
                        reason: Optional[str] = None) -> None:
        """``reason`` overrides the journaled lease-end reason (vocab
        LEASE_END_REASONS) — the agent plane passes ``agent_lost`` when
        it revokes a lease whose agent went silent mid-lease."""
        with self._lock:
            held = entry.open_leases.pop(runner_idx, None)
            if held is not None:
                entry.service_s += time.monotonic() - held[1]
            entry.free_pids.add(pid)
            entry.preempting_pids.discard(pid)
            if error is not None:
                entry.failures.append(error)
            self._event("lease", exp=entry.name, runner=runner_idx, pid=pid,
                        phase="end",
                        reason=reason or (
                            "error" if error is not None else "released"),
                        duration_s=round(time.monotonic() - held[1], 3)
                        if held is not None else None)
            self._chip_gauge(entry)
            self._wake.notify_all()

    def runner_for(self, entry: ExperimentEntry,
                   pid: int) -> Optional[int]:
        with self._lock:
            for runner, (p, _t0) in entry.open_leases.items():
                if p == pid:
                    return runner
        return None

    # ----------------------------------------------------------- preemption

    def maybe_preempt(self) -> int:
        """One preemption sweep: every experiment below its guaranteed
        allocation (``max(1, min_runners)`` capped by its target/max) for
        longer than ``preempt_grace_s`` gets ONE runner carved out of the
        most-over-share victim. Returns the number of preemptions
        initiated. Driver calls happen outside the scheduler lock."""
        actions: List[Tuple[ExperimentEntry, ExperimentEntry, int]] = []
        now = time.monotonic()
        with self._lock:
            if self.stopped:
                return 0
            self._expire_parked_locked()
            targets = self._targets_locked()
            for e in self._active.values():
                if not e.wants_runners():
                    e.deficit_since = None
                    continue
                want = max(1, min(e.policy.min_runners,
                                  e.effective_max(self.fleet_size)),
                           targets.get(e.name, 0))
                want = min(want, e.effective_max(self.fleet_size))
                if e.allocated() >= want:
                    e.deficit_since = None
                    continue
                if e.deficit_since is None:
                    e.deficit_since = now
                    continue
                if now - e.deficit_since < self.preempt_grace_s:
                    continue
                victim = self._victim_locked(e, targets)
                if victim is None:
                    continue
                # Never carve a runner out of the victim's own gang
                # block: a mid-gang preemption would revoke the whole
                # N-chip lease for a 1-runner rebalance.
                protected = set(self._gang_blocks.get(victim.name) or ())
                leases = [(r, v) for r, v in victim.open_leases.items()
                          if r not in protected]
                if not leases:
                    continue
                runner, (pid, _t0) = max(leases, key=lambda kv: kv[1][1])
                if pid in victim.preempting_pids:
                    continue
                victim.preempting_pids.add(pid)
                e.deficit_since = now  # re-arm: one preemption per grace
                actions.append((victim, e, pid))
        fired = 0
        for victim, starving, pid in actions:
            trial = None
            ok = True
            try:
                trial = victim.driver.preempt_partition(pid, evict=True)
            except Exception:  # noqa: BLE001 - a failed preempt must not kill the tick
                ok = False
            with self._lock:
                if not ok:
                    # Nothing was delivered: un-throttle the pid so a
                    # later sweep can retry, and don't count/journal a
                    # preemption that never happened.
                    victim.preempting_pids.discard(pid)
                    continue
                victim.preemptions += 1
            fired += 1
            # trial=None marks an idle eviction (the runner was between
            # trials — released without any work lost).
            self._event("preempt", exp=victim.name, pid=pid,
                        runner=self.runner_for(victim, pid),
                        trial=trial, for_exp=starving.name)
        return fired

    # locked-by: _lock
    def _victim_locked(self, starving: ExperimentEntry,
                       targets: Dict[str, int]
                       ) -> Optional[ExperimentEntry]:
        now = time.monotonic()
        candidates = []
        for v in self._active.values():
            if v is starving or v.state != "active" or not v.open_leases:
                continue
            if v.allocated() - 1 < min(v.policy.min_runners,
                                       v.effective_max(self.fleet_size)):
                continue
            over_share = v.allocated() > targets.get(v.name, 0)
            lower_class = v.policy.rank > starving.policy.rank
            # Rotation: with more same-class experiments than runners,
            # everyone sits exactly AT target (ties broken by submit
            # order) and leases last whole experiments — without this, a
            # runner-less peer would starve until someone finished.
            # Preempting the peer with the most weighted service hands
            # the fleet around in virtual-time order, so the starvation
            # bound is the grace period plus one service-differential.
            rotation = (starving.allocated() == 0
                        and v.policy.rank == starving.policy.rank
                        and v.vtime(now) > starving.vtime(now))
            if not (over_share or lower_class or rotation):
                continue
            candidates.append(v)
        if not candidates:
            return None
        return max(candidates,
                   key=lambda v: (v.policy.rank,
                                  v.allocated() - targets.get(v.name, 0),
                                  v.vtime(now)))

    # ------------------------------------------------------------- querying

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            entries = sorted(self._entries.values(), key=lambda e: e.seq)
            experiments = list(self._finished) \
                + [e.snapshot() for e in entries]
            return {
                "fleet_size": self.fleet_size,
                "agent_slots": len(self._agent_slots)
                - len(self._vacant_agent_slots),
                "queue_depth": self._queued_count,
                "active": len(self._active),
                "shed": self.shed_count,
                "max_queued": self.max_queued,
                # Fleet-wide chip-time granted so far (finished +
                # resident tenants, live leases included) — the
                # denominator the goodput ledger accounts against.
                "chip_seconds": round(
                    sum(e.get("chip_seconds") or e.get("service_s") or 0.0
                        for e in experiments), 3),
                "experiments": experiments,
            }

    def saturated(self) -> bool:
        """True while new submissions would be shed (``max_queued``
        reached) — the spool feeder's stop-claiming signal."""
        with self._lock:
            return self.max_queued is not None \
                and self._queued_count >= self.max_queued

    def _event(self, ev: str, **fields: Any) -> None:
        telem = self.telemetry
        if telem is not None:
            telem.event(ev, **fields)

    def _chip_gauge(self, entry: ExperimentEntry) -> None:
        """Refresh the per-tenant ``tenant.chip_seconds.<exp>`` gauge on
        a lease transition so the fleet's /metrics exposition carries
        each tenant's granted chip-time (obs labels it
        ``tenant_chip_seconds{tenant=...}``). Best-effort: gauges are a
        read-side convenience, the journal stays the source of truth."""
        telem = self.telemetry
        if telem is None or not getattr(telem, "enabled", False):
            return
        try:
            telem.metrics.gauge(
                "tenant.chip_seconds.{}".format(entry.name)).set(
                round(entry.chip_seconds(time.monotonic()), 3))
        except Exception:  # noqa: BLE001 - accounting must not break leasing
            pass


class FleetSubmission:
    """Handle for one ``Fleet.submit``: blocks on ``result()``."""

    def __init__(self, name: str, entry: ExperimentEntry):
        self.name = name
        self.entry = entry
        self._done = threading.Event()
        self._result: Any = None
        self._exc: Optional[BaseException] = None

    def _set_result(self, result: Any) -> None:
        self._result = result
        self._done.set()

    def _set_exception(self, exc: BaseException) -> None:
        self._exc = exc
        self._done.set()

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None) -> Any:
        if not self._done.wait(timeout=timeout):
            raise TimeoutError(
                "experiment {!r} did not finish within {}s".format(
                    self.name, timeout))
        if self._exc is not None:
            raise self._exc
        return self._result


class FleetBinding:
    """What ``config.fleet`` carries for a fleet-attached experiment: the
    fleet handle plus this experiment's scheduler entry. The driver uses
    it to (a) publish its RPC server on the fleet's shared listener and
    (b) lease runners instead of owning a pool."""

    def __init__(self, fleet: "Fleet", entry: ExperimentEntry):
        self.fleet = fleet
        self.entry = entry

    def attach_server(self, server) -> Tuple[str, int]:
        return self.fleet.shared_server.attach(server)

    def lease_pool(self, driver) -> "FleetLeasedPool":
        return FleetLeasedPool(self, driver)

    def request_gang(self, size: int) -> Optional[List[int]]:
        """Reserve a contiguous fleet-runner block for this experiment's
        gang trials (see FleetScheduler.request_gang)."""
        return self.fleet.scheduler.request_gang(self.entry, size)

    def release_gang(self) -> None:
        self.fleet.scheduler.release_gang(self.entry)


class FleetLeasedPool(RunnerPool):
    """The driver-facing pool adapter in fleet mode: ``run`` registers the
    experiment's executor with the scheduler and waits for completion —
    the fleet's runner loops are the actual substrate (the same shape as
    ``RemoteRunnerPool``, with the scheduler standing in for the join
    ticket)."""

    #: A fleet runner that keeps dying inside this experiment's executor
    #: is quarantined after this many failures per slot — without a cap a
    #: pathological executor would rebind-and-crash forever.
    MAX_FAILURES_PER_SLOT = 3

    def __init__(self, binding: FleetBinding, driver):
        super().__init__(driver.num_executors)
        self.binding = binding
        self.driver = driver

    def run(self, worker_fn: Callable[[int], None]) -> List[BaseException]:
        fleet = self.binding.fleet
        entry = self.binding.entry
        scheduler = fleet.scheduler
        scheduler.activate(entry, self.driver, worker_fn,
                           slots=self.num_workers)
        cap = self.MAX_FAILURES_PER_SLOT * max(1, self.num_workers)
        while not self.driver.experiment_done:
            if scheduler.stopped:
                return [RuntimeError(
                    "fleet shut down while experiment {!r} was "
                    "running".format(entry.name))]
            with scheduler._lock:
                n_failures = len(entry.failures)
            if n_failures > cap:
                return list(entry.failures)
            time.sleep(0.05)
        # Let leased runners observe their GSTOP before the driver tears
        # the server down (mirrors RemoteRunnerPool's release-ack grace).
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            with scheduler._lock:
                if not entry.open_leases:
                    break
            time.sleep(0.05)
        return list(entry.failures)

    def kill_worker(self, partition_id: int) -> bool:
        fleet = self.binding.fleet
        runner = fleet.scheduler.runner_for(self.binding.entry,
                                            partition_id)
        if runner is None:
            return False
        if fleet.scheduler.is_agent_slot(runner):
            # The lease is held by a REMOTE agent: route the kill to the
            # agent plane (same-host SIGKILL — the chaos/soak path).
            plane = fleet.agent_plane
            return plane is not None \
                and plane.kill_agent_by_runner(runner)
        return fleet.pool.kill_worker(runner)

    def chip_of(self, partition_id: int) -> Optional[int]:
        """The fleet runner index (runner ≈ chip) this partition is
        currently leased to — the gang placer's topology coordinate in
        fleet mode, so 'contiguous chips' means contiguous FLEET runners
        (the block ``FleetScheduler.request_gang`` reserves), not
        per-experiment slot numbers. None while unbound."""
        return self.binding.fleet.scheduler.runner_for(
            self.binding.entry, partition_id)

    def terminate(self) -> None:
        # The fleet owns its runners; a doomed experiment must not take
        # the shared substrate down with it.
        pass


class Fleet:
    """A persistent, shared runner fleet plus its scheduler, shared RPC
    listener, and journal. In-process: submissions are train-fn callables,
    so the fleet and its experiments live in one Python process (threads);
    the ``python -m maggy_tpu.fleet`` CLI hosts one for spool-file
    submissions from other processes."""

    def __init__(self, runners: int = 2, *, pool: str = "thread",
                 name: str = "fleet", home_dir: Optional[str] = None,
                 env=None, max_active: Optional[int] = None,
                 max_queued: Optional[int] = None,
                 preempt_grace_s: float = 1.0, telemetry: bool = True,
                 obs_port: Optional[int] = None,
                 obs_host: str = "127.0.0.1",
                 dispatch_pool: Optional[bool] = None,
                 max_agents: int = 0,
                 bind_host: str = "127.0.0.1",
                 agent_liveness_s: Optional[float] = None,
                 sink: Optional[bool] = None):
        if pool != "thread":
            raise ValueError(
                "fleet pools are in-process ('thread'): experiments are "
                "submitted as live callables and scheduler bindings cross "
                "no process boundary (got pool={!r}). Cross-process "
                "capacity comes from REMOTE AGENTS instead: pass "
                "max_agents=N and start agents with `python -m "
                "maggy_tpu.fleet agent --ticket <home>/agent_ticket.json`")
        from maggy_tpu.core.environment import EnvSing
        from maggy_tpu.core.rpc import SharedServer
        from maggy_tpu.telemetry import Telemetry

        self.name = name
        self.env = env or EnvSing.get_instance()
        self.num_runners = int(runners)
        self.pool = ThreadRunnerPool(self.num_runners)
        self.home_dir = home_dir or os.path.join(
            self.env.experiment_base_dir(), "fleets", name)
        self.telemetry = Telemetry(
            env=self.env,
            journal_path=self.home_dir + "/" + FLEET_JOURNAL_NAME,
            enabled=telemetry)
        self.scheduler = FleetScheduler(
            self.num_runners, telemetry=self.telemetry,
            max_active=max_active, max_queued=max_queued,
            preempt_grace_s=preempt_grace_s,
            max_size=self.num_runners + int(max_agents))
        # dispatch_pool=None -> per-tenant handler pools on (the
        # default; MAGGY_TPU_SHARED_DISPATCH_POOL=0 or False restores
        # handlers-on-the-loop for A/B measurement).
        self.shared_server = SharedServer(dispatch_pool=dispatch_pool)
        # Remote agents (maggy_tpu.fleet.agent): max_agents > 0 opens
        # the agent plane at start() — a FleetAgentServer on the shared
        # listener plus the fleet ticket in home_dir. 0 (default) keeps
        # the fleet purely in-process, bit-for-bit the old behavior.
        self.max_agents = int(max_agents)
        self.bind_host = bind_host
        self._agent_liveness_s = agent_liveness_s
        self.agent_plane = None
        # Fleet-wide telemetry fan-in (maggy_tpu.telemetry.sink): the
        # journal-sink service demuxing tenant/agent journals into
        # <home>/journal/ per-source files, plus its SinkServer tenant
        # on the shared listener. Default: on whenever fleet telemetry
        # is (tenants still opt IN per experiment via config.sink).
        self.sink_enabled = bool(telemetry) if sink is None else bool(sink)
        self.sink = None
        self.sink_server = None
        self._pool_thread: Optional[threading.Thread] = None
        self._tick_thread: Optional[threading.Thread] = None
        self._started = False
        self._stopped = False
        self._lock = threading.Lock()
        self._submissions: Dict[str, FleetSubmission] = {}  # guarded-by: _lock
        self._sub_threads: List[threading.Thread] = []  # guarded-by: _lock
        self._sub_seq = itertools.count()
        #: Live observability plane for the fleet HOST process: the fleet
        #: registers its scheduler status with the process obs server, so
        #: /status shows share allocation and queue depth even between
        #: experiments (each attached driver additionally registers its
        #: own experiment). None (+ no MAGGY_TPU_OBS_PORT) = off.
        from maggy_tpu.config import resolved_env_obs_port

        self._obs_port = obs_port if obs_port is not None \
            else resolved_env_obs_port()
        self._obs_host = obs_host
        self._obs_registration = None

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "Fleet":
        with self._lock:
            if self._started:
                return self
            self._started = True
        self.telemetry.event("fleet", phase="start", name=self.name,
                             runners=self.num_runners, pool="thread")
        if self.sink_enabled:
            from maggy_tpu.core.rpc import SinkServer
            from maggy_tpu.telemetry.sink import SINK_DIR_NAME, JournalSink

            self.sink = JournalSink(
                self.env, self.home_dir + "/" + SINK_DIR_NAME,
                telemetry=self.telemetry)
            self.sink_server = SinkServer()
            self.sink_server.telemetry = self.telemetry
            self.sink_server.attach_sink(self.sink)
            self.shared_server.attach(self.sink_server,
                                      host=self.bind_host)
        if self._obs_port is not None and self.telemetry.enabled:
            from maggy_tpu.telemetry import obs as obs_mod

            self._obs_registration = obs_mod.ObsRegistration(
                key="fleet:{}".format(self.name),
                labels={"experiment": self.name, "run": "fleet"},
                telemetry=self.telemetry, status_fn=self.status,
                snapshots_fn=self._federated_metrics)
            server = obs_mod.register(self._obs_registration,
                                      port=self._obs_port,
                                      host=self._obs_host)
            self.telemetry.event("obs_started", host=server.address[0],
                                 port=server.address[1],
                                 experiment=self.name)
        self._pool_thread = threading.Thread(
            target=self.pool.run, args=(self._runner_loop,),
            daemon=True, name="fleet-pool")
        self._pool_thread.start()
        self._tick_thread = threading.Thread(
            target=self._tick_loop, daemon=True, name="fleet-tick")
        self._tick_thread.start()
        if self.max_agents > 0:
            from maggy_tpu.fleet.agent import DEFAULT_LIVENESS_S, AgentPlane

            self.agent_plane = AgentPlane(
                self, max_agents=self.max_agents,
                liveness_s=self._agent_liveness_s
                if self._agent_liveness_s is not None
                else DEFAULT_LIVENESS_S).start()
        self._dump_status()
        return self

    def _runner_loop(self, runner_idx: int) -> None:
        """One persistent fleet runner: bind -> run the experiment's
        executor until released -> re-bind. An executor exception (e.g. a
        dead control plane) is a lease failure, not a fleet failure — the
        runner survives and re-binds."""
        while True:
            binding = self.scheduler.next_binding(runner_idx)
            if binding is None:
                return
            entry, pid = binding
            err: Optional[BaseException] = None
            try:
                entry.executor_fn(pid)
            except BaseException as e:  # noqa: BLE001 - lease failure, runner survives
                err = RuntimeError(
                    "fleet runner {} failed in experiment {!r} (partition "
                    "{}): {!r}".format(runner_idx, entry.name, pid, e))
            finally:
                self.scheduler.release_binding(runner_idx, entry, pid,
                                               error=err)

    def _tick_loop(self) -> None:
        last_status = 0.0
        while not self.scheduler.stopped:
            self.scheduler.maybe_preempt()
            now = time.monotonic()
            if now - last_status >= 0.5:
                last_status = now
                self._dump_status()
            time.sleep(0.1)

    def shutdown(self, wait: bool = True, timeout: float = 30.0) -> None:
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
            subs = list(self._sub_threads)
        if wait:
            deadline = time.monotonic() + timeout
            for t in subs:
                t.join(timeout=max(0.1, deadline - time.monotonic()))
        self.scheduler.stop()
        if self.agent_plane is not None:
            # After scheduler.stop(): proxies wake from next_binding,
            # and agents' next ALEASE polls read AGSTOP.
            self.agent_plane.stop()
        for t in (self._pool_thread, self._tick_thread):
            if t is not None:
                t.join(timeout=5)
        self.shared_server.stop()
        if self.sink is not None:
            # After the listener: no more JSINK frames can land, so the
            # sink can seal every per-source journal cleanly.
            self.sink.stop()
        if self._obs_registration is not None:
            from maggy_tpu.telemetry import obs as obs_mod

            obs_mod.deregister(self._obs_registration)
            self._obs_registration = None
        self.telemetry.event("fleet", phase="stop")
        self._dump_status()
        self.telemetry.close()

    # ------------------------------------------------------------ sink plane

    def sink_binding(self):
        """Where this fleet's journal shippers dial (telemetry.sink.
        SinkBinding), or None when the sink is off / not started."""
        if self.sink_server is None or self.shared_server.addr is None:
            return None
        from maggy_tpu.telemetry.sink import SinkBinding

        return SinkBinding(self.shared_server.addr,
                           self.sink_server.secret_hex)

    def kill_sink(self) -> bool:
        """Chaos/test hook (invariant 12): detach the sink tenant from
        the shared listener — in-flight and future JSINK frames fail
        authentication and shippers degrade to their local journals.
        The sink service itself (writers, dedup state) stays intact for
        ``restart_sink``."""
        if self.sink_server is None:
            return False
        self.shared_server.detach(self.sink_server)
        return True

    def restart_sink(self) -> bool:
        """Re-attach the sink tenant under the SAME secret: degraded
        shippers reconnect on their next cycle and re-ship their spooled
        suffix (the sink's sid dedup absorbs any overlap)."""
        if self.sink_server is None:
            return False
        self.shared_server.attach(self.sink_server, host=self.bind_host)
        return True

    def _federated_metrics(self):
        """Per-source shipped counter snapshots for the fleet's
        /metrics registration (obs.ObsRegistration.snapshots_fn)."""
        return self.sink.federated_snapshots() if self.sink is not None \
            else []

    def __enter__(self) -> "Fleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown()

    # ------------------------------------------------------------ submission

    def submit(self, train_fn: Callable, config, *, priority="normal",
               weight: float = 1.0, min_runners: int = 0,
               max_runners: Optional[int] = None,
               name: Optional[str] = None) -> FleetSubmission:
        """Queue one experiment onto the fleet; returns a handle whose
        ``result()`` blocks for the experiment's result (the same value
        ``lagom`` would return)."""
        self.start()
        policy = FleetPolicy(priority=priority, weight=weight,
                             min_runners=min_runners,
                             max_runners=max_runners)
        base = name or getattr(config, "name", "experiment")
        from maggy_tpu.fleet.agent import train_fn_path

        fn_path = train_fn_path(train_fn)
        with self._lock:
            if self._stopped:
                raise RuntimeError("fleet {!r} is shut down".format(self.name))
            sub_name = base
            while sub_name in self._submissions:
                sub_name = "{}-{}".format(base, next(self._sub_seq))
            entry = self.scheduler.submit(sub_name, policy)
            # Wire-nameable train fns make the experiment leasable to
            # REMOTE agents (ABIND ships the dotted path); closures and
            # lambdas keep it on thread runners only.
            entry.train_fn_path = fn_path
            handle = FleetSubmission(sub_name, entry)
            self._submissions[sub_name] = handle
            # Prune finished submission threads so a long-lived host
            # doesn't accumulate one dead Thread per spool submission.
            self._sub_threads = [t for t in self._sub_threads
                                 if t.is_alive()]
            thread = threading.Thread(
                target=self._run_submission,
                args=(handle, train_fn, config),
                daemon=True, name="fleet-exp-{}".format(sub_name))
            self._sub_threads.append(thread)
        thread.start()
        return handle

    def _run_submission(self, handle: FleetSubmission, train_fn: Callable,
                        config) -> None:
        """Submission thread: claim a run id, build the driver with the
        fleet binding in its config, and run the experiment — the driver's
        pool is a ``FleetLeasedPool``, so all its runners come from the
        shared fleet."""
        import dataclasses

        from maggy_tpu import experiment as exp_mod

        entry = handle.entry
        sub = None
        driver = None
        try:
            # Deferred activation: build the driver (run-dir claim, RPC
            # server, telemetry, threads) only once the scheduler admits
            # this tenant past the queue. A churn of hundreds of queued
            # submissions costs hundreds of heap entries and parked
            # threads — not hundreds of live control planes.
            if not self.scheduler.wait_admitted(entry):
                raise RuntimeError(
                    "fleet {!r} stopped before experiment {!r} was "
                    "admitted".format(self.name, entry.name))
            sub = exp_mod._begin_run(config, self.env, exclusive=False)
            # Partition-id range: thread runners PLUS agent slots — an
            # agent-backed fleet must be able to lease more runners to
            # one experiment than the host process has threads.
            slots = entry.effective_max(self.num_runners + self.max_agents)
            replacements = dict(fleet=FleetBinding(self, entry),
                                num_workers=max(1, slots))
            if self._obs_port is not None \
                    and getattr(config, "obs_port", None) is None:
                # The fleet host's obs plane covers its tenants: an
                # attached experiment registers onto the SAME process
                # server (one per process), so /status shows every
                # live experiment next to the fleet's share state. An
                # experiment's own obs_port still wins when set.
                replacements["obs_port"] = self._obs_port
            cfg = dataclasses.replace(config, **replacements)
            driver = exp_mod.lagom_driver(cfg, sub.app_id, sub.run_id)
            import atexit

            atexit.register(exp_mod._exit_handler, driver)
            try:
                result = driver.run_experiment(train_fn)
            finally:
                atexit.unregister(exp_mod._exit_handler)
            self.scheduler.finish(entry, "done")
            handle._set_result(result)
        except BaseException as exc:  # noqa: BLE001 - surface via the handle
            self.scheduler.finish(entry, "failed")
            handle._set_exception(exc)
        finally:
            if sub is not None:
                exp_mod._end_run(sub)
            self._dump_status()

    # ------------------------------------------------------------- querying

    def status(self) -> Dict[str, Any]:
        snap = self.scheduler.snapshot()
        plane = self.agent_plane
        return {"t": time.time(), "name": self.name,
                "runners": self.num_runners, "pool": "thread",
                "stopped": self._stopped,
                "max_agents": self.max_agents,
                "agents": plane.snapshot() if plane is not None else [],
                "sink": self.sink.snapshot()
                if self.sink is not None else {},
                **snap}

    def _dump_status(self) -> None:
        try:
            self.env.dump(json.dumps(self.status(), indent=2, default=str),
                          self.home_dir + "/status.json")
        except Exception:  # noqa: BLE001 - status mirror is best-effort
            pass


# ----------------------------------------------------------------- replay


def replay_fleet_journal(path: str, env=None,
                         share_names=None) -> Dict[str, Any]:
    """Offline replay of a fleet journal: per-experiment queue waits,
    lease-derived runner-seconds, share fractions over the window where
    experiments overlapped (vs the weight-expected split), preemption
    counts, admission latency (submit -> admit), shed counts, and
    scheduler decision throughput. Pure — the same journal always
    reproduces the same numbers (bench.py's ``detail.fleet`` /
    ``detail.scale`` blocks are exactly this call).

    ``share_names``: restrict the fair-share computation to this subset
    of experiments. Under churn the overlap window of ALL experiments is
    empty (cohorts start and finish at different times), so the share
    check runs over the long-lived resident cohort instead."""
    from maggy_tpu.telemetry import read_events
    from maggy_tpu.telemetry.spans import _dist_stats

    if os.path.isdir(path):  # a fleet home dir stands in for its journal
        path = os.path.join(path, "fleet.jsonl")
    events = read_events(path, env=env)
    exps: Dict[str, Dict[str, Any]] = {}
    preempts = 0
    sheds = 0
    admission_ms: List[float] = []
    decisions = 0
    first_t: Optional[float] = None
    last_t = 0.0
    # Remote-agent lanes: per-agent lifecycle counts plus the ABIND
    # delivery latency (lease set -> ALEASE poll pickup) distribution —
    # the "lease round-trip" number bench.py --scale --remote reports.
    agent_joins = 0
    agent_losses = 0
    agent_leases: Dict[str, int] = {}
    abind_ms: List[float] = []
    agent_lost_leases = 0
    # Warm prewarming hints: how many agent-slot leases landed on an
    # agent already holding the experiment's program family warm
    # (lease-event warm_hint field; None = thread runner / family-less).
    # warm_affinity grades the hits: "experiment" = parent affinity
    # (same experiment re-lease — fork checkpoints on local disk),
    # "family" = compiled-step reuse only.
    warm_hint_hits = 0
    warm_hint_misses = 0
    warm_affinity_exp = 0
    # Journal-sink ingest records (jsink) + per-agent clock offsets —
    # the telemetry fan-in plane's replayable numbers.
    sink_batches = 0
    sink_events = 0
    sink_dup = 0
    sink_lag_ms: List[float] = []
    sink_sources_seen: set = set()
    clock_offsets: Dict[str, Dict[str, Any]] = {}

    def exp(name: str) -> Dict[str, Any]:
        return exps.setdefault(name, {
            "submitted_t": None, "first_lease_t": None, "leases": [],
            "open": {}, "preemptions": 0, "weight": 1.0, "priority": None,
            "exp_dir": None})

    for ev in events:
        t = ev.get("t")
        if isinstance(t, (int, float)):
            last_t = max(last_t, t)
        kind = ev.get("ev")
        if kind in ("fleet_admit", "lease", "preempt", "shed"):
            # Scheduler decisions: admissions, lease grants/releases,
            # preemptions, sheds — the control plane's output rate.
            decisions += 1
            if isinstance(t, (int, float)):
                first_t = t if first_t is None else min(first_t, t)
        if kind == "shed":
            sheds += 1
        elif kind == "fleet_admit":
            if ev.get("queued_s") is not None:
                admission_ms.append(float(ev["queued_s"]) * 1e3)
        elif kind == "fleet_submit":
            e = exp(ev["exp"])
            e["submitted_t"] = t
            e["weight"] = float(ev.get("weight", 1.0))
            e["priority"] = ev.get("priority")
        elif kind == "lease":
            e = exp(ev["exp"])
            if ev.get("exp_dir"):
                e["exp_dir"] = ev["exp_dir"]
            key = (ev.get("runner"), ev.get("pid"))
            if ev.get("phase") == "start":
                e["open"][key] = t
                if e["first_lease_t"] is None:
                    e["first_lease_t"] = t
                if ev.get("warm_hint") is True:
                    warm_hint_hits += 1
                elif ev.get("warm_hint") is False:
                    warm_hint_misses += 1
                if ev.get("warm_affinity") == "experiment":
                    warm_affinity_exp += 1
            elif ev.get("phase") == "end":
                t0 = e["open"].pop(key, None)
                if t0 is not None and t is not None:
                    e["leases"].append((t0, t))
                if ev.get("reason") == "agent_lost":
                    agent_lost_leases += 1
        elif kind == "agent":
            phase = ev.get("phase")
            if phase == "join":
                agent_joins += 1
            elif phase == "lost":
                agent_losses += 1
            elif phase == "lease":
                aid = str(ev.get("agent"))
                agent_leases[aid] = agent_leases.get(aid, 0) + 1
                if ev.get("abind_ms") is not None:
                    abind_ms.append(float(ev["abind_ms"]))
        elif kind == "jsink":
            sink_batches += 1
            sink_events += int(ev.get("n") or 0)
            sink_dup += int(ev.get("dup") or 0)
            if ev.get("source"):
                sink_sources_seen.add(str(ev["source"]))
            if ev.get("lag_ms") is not None:
                sink_lag_ms.append(float(ev["lag_ms"]))
        elif kind == "clock_offset":
            if ev.get("agent"):
                clock_offsets[str(ev["agent"])] = {
                    "offset_s": ev.get("offset_s"),
                    "rtt_s": ev.get("rtt_s"), "t": t,
                    "reports": clock_offsets.get(
                        str(ev["agent"]), {}).get("reports", 0) + 1}
        elif kind == "preempt":
            preempts += 1
            exp(ev["exp"])["preemptions"] += 1
        elif kind == "fleet_experiment":
            e = exp(ev["exp"])
            if ev.get("exp_dir"):
                e["exp_dir"] = ev["exp_dir"]

    queue_waits_ms: List[float] = []
    out_exps: Dict[str, Dict[str, Any]] = {}
    for name, e in exps.items():
        for key, t0 in e["open"].items():  # journal ended mid-lease
            e["leases"].append((t0, last_t))
        e["open"] = {}
        runner_s = sum(t1 - t0 for t0, t1 in e["leases"])
        qw = None
        if e["submitted_t"] is not None and e["first_lease_t"] is not None:
            qw = e["first_lease_t"] - e["submitted_t"]
            queue_waits_ms.append(qw * 1e3)
        out_exps[name] = {
            "runner_seconds": round(runner_s, 3),
            "leases": len(e["leases"]),
            "queue_wait_s": round(qw, 3) if qw is not None else None,
            "preemptions": e["preemptions"],
            "weight": e["weight"], "priority": e["priority"],
            "exp_dir": e["exp_dir"],
        }

    # Per-tenant chip-time ledger: lease-derived chip-seconds (the
    # denominator the scheduler granted) plus each tenant's OWN journal
    # folded through the goodput accountant — local journal merged
    # exactly-once with any sink-shipped segment, so a tenant that ran
    # on a remote agent (no surviving local journal) still folds. A
    # tenant's journal is written by one driver process, so the fold is
    # single-clock; cross-process merges go through
    # ``goodput.merge_corrected`` with the replay's ``clock_offsets``.
    from maggy_tpu.telemetry import JOURNAL_NAME
    from maggy_tpu.telemetry.goodput import compute_goodput

    home = os.path.dirname(os.path.abspath(path))
    shipped_by_source: Dict[str, Any] = {}
    try:
        from maggy_tpu.telemetry.sink import SINK_DIR_NAME, read_sink_dir

        sink_dir = os.path.join(home, SINK_DIR_NAME)
        if os.path.isdir(sink_dir):
            shipped_by_source = read_sink_dir(sink_dir)
    except Exception:  # noqa: BLE001 - a torn sink dir must not kill replay
        shipped_by_source = {}
    tenants: Dict[str, Dict[str, Any]] = {}
    fleet_held = 0.0
    fleet_train = 0.0
    for name, oe in sorted(out_exps.items()):
        local = None
        exp_dir = oe.get("exp_dir")
        if exp_dir:
            jp = os.path.join(exp_dir, JOURNAL_NAME)
            if os.path.exists(jp):
                local = read_events(jp, env=env)
        shipped = None
        if shipped_by_source:
            from maggy_tpu.telemetry.sink import sanitize_source

            shipped = shipped_by_source.get(sanitize_source(name))
        gp: Dict[str, Any] = {}
        if shipped is not None and local is not None:
            from maggy_tpu.telemetry.sink import merge_source_events

            gp = compute_goodput(merge_source_events(shipped, local))
        elif local is not None or shipped is not None:
            gp = compute_goodput(local if local is not None else shipped)
        tenants[name] = {"chip_seconds": oe["runner_seconds"],
                         "goodput": gp}
        held = gp.get("held_chip_s") or 0.0
        frac = gp.get("goodput_fraction")
        if held > 0 and frac is not None:
            fleet_held += held
            fleet_train += held * frac
    goodput_block: Dict[str, Any] = {
        "tenants": tenants,
        "chip_seconds": round(
            sum(t["chip_seconds"] or 0.0 for t in tenants.values()), 3),
        # Held-time-weighted fleet goodput across every tenant that had
        # a foldable journal (None when none did).
        "goodput_fraction": round(fleet_train / fleet_held, 4)
        if fleet_held > 0 else None,
    }

    # Fair-share check over the overlap window: the span in which EVERY
    # leased experiment had started leasing and none had fully finished —
    # outside it, a lone experiment legitimately takes the whole fleet.
    share: Dict[str, float] = {}
    expected: Dict[str, float] = {}
    share_error = None
    leased = {n: e for n, e in exps.items() if e["leases"]
              and (share_names is None or n in share_names)}
    if len(leased) >= 2:
        w0 = max(min(t0 for t0, _ in e["leases"]) for e in leased.values())
        w1 = min(max(t1 for _, t1 in e["leases"]) for e in leased.values())
        if w1 > w0:
            clipped = {
                n: sum(max(0.0, min(t1, w1) - max(t0, w0))
                       for t0, t1 in e["leases"])
                for n, e in leased.items()}
            total = sum(clipped.values())
            wsum = sum(e["weight"] for e in leased.values())
            if total > 0 and wsum > 0:
                share = {n: round(s / total, 3) for n, s in clipped.items()}
                expected = {n: round(e["weight"] / wsum, 3)
                            for n, e in leased.items()}
                share_error = round(
                    max(abs(share[n] - expected[n]) for n in share), 3)

    window_s = None
    if first_t is not None and last_t > first_t:
        window_s = last_t - first_t
    admission_sorted = sorted(admission_ms)
    admission_p99 = None
    if admission_sorted:
        admission_p99 = round(
            admission_sorted[min(len(admission_sorted) - 1,
                                 int(0.99 * len(admission_sorted)))], 3)
    return {
        "experiments": out_exps,
        "preemptions": preempts,
        "sheds": sheds,
        # Remote-agent plane (empty/zero for purely in-process fleets).
        "agents": {
            "joins": agent_joins,
            "losses": agent_losses,
            "lost_leases": agent_lost_leases,
            "leases": sum(agent_leases.values()),
            "per_agent_leases": dict(sorted(agent_leases.items())),
            "abind_ms": _dist_stats(abind_ms),
            # Prewarming-hint accuracy: agent leases that landed on an
            # already-warm family vs cold re-binds; warm_affinity_exp =
            # the subset that re-leased the SAME experiment (parent
            # affinity: fork checkpoints on the agent's local disk).
            "warm_hint_hits": warm_hint_hits,
            "warm_hint_misses": warm_hint_misses,
            "warm_affinity_exp": warm_affinity_exp,
        },
        # Journal-sink ingest (empty/zero when no tenant/agent shipped).
        "sink": {
            "batches": sink_batches,
            "events": sink_events,
            "dup": sink_dup,
            "sources": len(sink_sources_seen),
            "lag_ms": _dist_stats(sink_lag_ms),
        },
        # Last reported clock offset per agent — the unified trace's
        # cross-process time base.
        "clock_offsets": clock_offsets,
        # Per-tenant chip-time ledger: lease-granted chip-seconds plus
        # each tenant's own journal fold (``python -m
        # maggy_tpu.telemetry goodput <fleet home>`` prints this).
        "goodput": goodput_block,
        "share": share,
        "expected_share": expected,
        "share_error": share_error,
        "queue_wait_ms": _dist_stats(queue_waits_ms),
        "max_queue_wait_s": round(max(queue_waits_ms) / 1e3, 3)
        if queue_waits_ms else None,
        # Admission latency: fleet_submit -> fleet_admit, per admitted
        # experiment (the scheduler's own queued_s measurement).
        "admission_ms": _dist_stats(admission_ms),
        "admission_p99_ms": admission_p99,
        # Scheduler decision throughput over the decision window:
        # admits + lease starts/ends + preempts + sheds per second.
        "decisions": decisions,
        "decision_window_s": round(window_s, 3) if window_s else None,
        "decisions_per_s": round(decisions / window_s, 2)
        if window_s else None,
        "torn_lines": getattr(events, "torn_lines", 0),
    }
